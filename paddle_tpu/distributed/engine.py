"""Distributed train-step engine: the pjit execution path.

This is the TPU-native replacement for the reference's whole static-graph distributed
machinery (meta-optimizers rewriting programs + InterpreterCore + NCCL rings, SURVEY.md §3.4):
the forward, backward, grad sync, clip, and optimizer update become ONE jitted XLA program
over the hcg mesh. Parallelism is expressed as shardings:

- dp / sharding(ZeRO data axis): batch dims sharded; XLA turns the mean-loss grad into an
  allreduce (the Reducer/fuse_all_reduce_ops analogue — one fused collective per step).
- mp (tensor parallel): parameters carry PartitionSpec dist_attrs from the mp_layers;
  GSPMD inserts the c_identity/c_allreduce/c_concat collectives the reference codes by hand.
- sharding stage1/2 (ZeRO-1/2): optimizer states sharded over the sharding axis — the
  weight update runs 1/N-sized per device and XLA all-gathers updated params
  (= reference GroupShardedOptimizerStage2, group_sharded_optimizer_stage2.py:48).
- sp: sequence dims of activations sharded; attention gathers as needed.
- parameters are donated: the update is in-place in HBM (buffer donation ≙ the
  reference's in-place optimizer ops).
"""
from __future__ import annotations

import math
import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import compile_cache as _compile_cache
from ..core import flags as _flags
from ..core import monitor as _monitor
from ..core.exec_registry import ExecutableRegistry
from ..core import random as random_mod
from ..core.tensor import Tensor
from ..jit import functional_call
from ..observability import exec_introspect as _obs_exec
from ..observability import exporter as _obs_exporter
from ..observability import flight_recorder as _obs_flight
from ..observability import health as _obs_health
from ..observability import metrics as _obs_metrics
from ..observability import tracer as _obs_tracer
from ..observability.step_telemetry import StepTelemetry
from ..optimizer import functional as opt_funct
from . import elastic as _elastic
from . import grad_comm as _gc
from . import prefetcher as _pf
from .mesh import HybridCommunicateGroup, get_hybrid_communicate_group

# jit-path observability (core.monitor registry): every compile of a step
# program is counted (engine.jit_compiles / jit_recompiles / jit_compile_ms,
# now driven through ExecutableRegistry.note_compiles with
# engine_counters=True); a compile on a step function that ALREADY had an
# executable is a recompile — the shape/dtype-churn alarm the reference
# surfaces via its cache-miss logs.
_NAN_LOSS_STEPS = _monitor.stat("engine.nan_loss_steps")


def _jit_cache_size(fn) -> int:
    try:
        return fn._cache_size()
    except Exception:
        return -1


def _divides(n, d):
    return d > 0 and n % d == 0


def model_input_count(n_batch_args, num_model_inputs=None):
    """How many leading batch args feed the model when a loss_fn is present
    (the rest are labels for loss_fn). Shared by TrainStepEngine and
    auto_parallel.Engine so the convention cannot drift: default is
    all-but-last (min 1); num_model_inputs overrides for e.g. multi-input
    self-supervised models.

    BREAKING (round 1 -> 2, ADVICE r1): previously the model received EVERY
    batch arg and loss_fn only the outputs; now the last arg is the label and
    loss_fn receives (outputs..., labels). Callers on the old convention must
    pass num_model_inputs=n_batch_args."""
    if num_model_inputs is not None:
        if not 1 <= num_model_inputs <= n_batch_args:
            raise ValueError(
                f"num_model_inputs={num_model_inputs} out of range for "
                f"{n_batch_args} batch args")
        return num_model_inputs
    return max(1, n_batch_args - 1)


def _param_spec(p, shape, hcg) -> P:
    if getattr(p, "dist_attr", None) is not None:
        return p.dist_attr if isinstance(p.dist_attr, P) else P(*p.dist_attr)
    return P()


def _opt_state_spec(param_spec: P, shape, hcg, use_sharding: bool) -> P:
    """Shard optimizer state over the 'sharding' axis in the first divisible unsharded
    dim (ZeRO-1 weight-update sharding, arXiv:2004.13336 style)."""
    entries = list(param_spec) + [None] * (len(shape) - len(param_spec))
    if not use_sharding:
        return P(*entries) if any(e is not None for e in entries) else P()
    deg = hcg.degrees["sharding"]
    if deg <= 1:
        return P(*entries) if any(e is not None for e in entries) else P()
    for i, (e, s) in enumerate(zip(entries, shape)):
        if e is None and _divides(s, deg):
            entries[i] = "sharding"
            break
    return P(*entries)


def _default_input_spec(shape, hcg) -> P:
    batch_axes = tuple(a for a in ("dp", "sharding") if hcg.degrees[a] > 1)
    first = batch_axes if len(batch_axes) > 1 else (batch_axes[0] if batch_axes else None)
    entries = [first]
    if len(shape) >= 2 and hcg.degrees["sp"] > 1 and _divides(shape[1], hcg.degrees["sp"]):
        entries.append("sp")
    return P(*entries)


class TrainStepEngine:
    """Fused distributed train step.

    model: an nn.Layer whose forward returns the scalar loss given the batch.
           Alternatively pass loss_fn: with >= 2 batch args the model consumes
           all but the last and loss_fn(model_outputs..., labels) combines
           them (auto_parallel.Engine convention); with a single batch arg the
           model consumes it and loss_fn(model_outputs...) is self-supervised.
    optimizer: a paddle_tpu.optimizer.Optimizer (its functional rule is reused).
    """

    def __init__(self, model, optimizer, loss_fn: Optional[Callable] = None,
                 hcg: Optional[HybridCommunicateGroup] = None, strategy=None,
                 input_specs: Optional[List[P]] = None, donate: bool = True,
                 num_model_inputs: Optional[int] = None,
                 microbatches: int = 1, zero_update: bool = False,
                 fsdp: bool = False):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.num_model_inputs = num_model_inputs
        self.hcg = hcg or get_hybrid_communicate_group() or HybridCommunicateGroup()
        self.mesh: Mesh = self.hcg.mesh
        self.strategy = strategy
        self.input_specs = input_specs
        self._donate = donate
        use_sharding = bool(strategy and getattr(strategy, "sharding", False)) or \
            self.hcg.degrees["sharding"] > 1

        state = model.state_dict(include_non_persistable_buffer=True)
        self._param_names = [n for n, t in state.items() if not t.stop_gradient]
        self._buffer_names = [n for n, t in state.items() if t.stop_gradient]
        self._state_refs = state

        # build sharded global arrays for params + opt state
        self.param_specs = {}
        self.params = {}
        for n in self._param_names:
            p = state[n]
            spec = _param_spec(p, p.shape, self.hcg)
            self.param_specs[n] = spec
            self.params[n] = jax.device_put(p._data, NamedSharding(self.mesh, spec))
        self.buffers = {n: state[n]._data for n in self._buffer_names}

        rule = optimizer._rule
        # offload (GroupShardedOptimizerStage2(offload=True), reference
        # group_sharded_optimizer_stage2.py:48): optimizer state lives in host
        # memory between steps — XLA streams it to HBM for the update and back,
        # freeing per-device HBM at the cost of host<->device traffic.
        # (pinned_host on TPU/GPU; older CPU clients expose unpinned_host only)
        from ..core.jax_compat import host_memory_kind

        self._opt_memory_kind = (host_memory_kind()
                                 if getattr(optimizer, "_offload", False) else None)
        self.opt_specs = {}
        self.opt_state = {}
        for n in self._param_names:
            st = opt_funct.init_state(rule, self.params[n])
            spec = _opt_state_spec(self.param_specs[n], state[n].shape, self.hcg,
                                   use_sharding)
            self.opt_specs[n] = spec
            self.opt_state[n] = tuple(
                jax.device_put(s, self._opt_sharding(spec)) for s in st)

        # ONE keyed ExecutableRegistry replaces the step/accum/scan fn
        # caches (keys ("train.step",), ("train.accum",)+config,
        # ("train.run_steps", fixed)); unbounded — the train working set is
        # a handful of pinned executables per topology. The legacy
        # attribute views (_step_fn, _accum_fns, _exec_stash) stay as
        # properties over it.
        self._execs = ExecutableRegistry(name="train")
        # microbatch gradient accumulation (distributed/grad_comm.py): K
        # splits the global batch inside ONE compiled program — one dispatch
        # and one deferred fused gradient all-reduce per optimizer step.
        # Mutable until the first accumulated step; fns cached per config.
        self.microbatches = max(1, int(microbatches))
        self._grad_residual = None     # error-feedback state, lazily built
        self._gspmd_warned = False
        # ZeRO weight-update sharding (grad_comm.make_zero_accum_step):
        # requested per-engine or via FLAGS_zero_update; the optimizer state
        # converts one-way into flat f32 1/N shards on the first sharded
        # step (self.opt_state becomes None; _gather_zero_opt reconstructs)
        self.zero_update = bool(zero_update)
        self._zero_opt = None          # tuple of flat [n_pad] f32 slot shards
        self._zero_warned = False
        self._zero_reason = "unset"    # cached fallback reason (None = ok)
        # Full FSDP (grad_comm.make_fsdp_accum_step): params AND opt state
        # live only as per-layer flat f32 1/N shards between steps after the
        # first sharded step (self.params/self.opt_state become None;
        # _gather_fsdp_params/_gather_fsdp_opt reconstruct the dict forms).
        # Same eligibility gate as zero_update; supersedes it when both set.
        self.fsdp = bool(fsdp)
        self._fsdp_params = None       # tuple of per-bucket [pad] f32 shards
        self._fsdp_opt = None          # tuple (per slot) of per-bucket shards
        self._fsdp_warned = False
        self._fsdp_cache = None        # (nrep, chunk) -> bucket layout
        self._param_dtypes = None      # captured at fsdp engagement
        self._batch_shardings = None   # resolved lazily from the first batch
        self._pending_h2d = None       # (h2d_ms, depth) staged by prefetch()
        self.prefetcher = None         # last DevicePrefetcher built by prefetch()
        self._scan_batch_shardings = {}  # fixed_batch -> shardings
        self._step_count = optimizer._step_count
        self._key = jax.random.key(random_mod.default_generator().initial_seed() or 0)
        self.last_loss = None
        self._lr_cache = (None, None)  # (python value, device scalar)
        # PADDLE_TPU_TELEMETRY_DIR auto-attaches a JSONL sink; otherwise
        # telemetry stays None and the step path pays nothing for it
        self.telemetry = StepTelemetry.from_env()
        if self.telemetry is not None and self.telemetry.flops_per_token is None:
            self.telemetry.flops_per_token = 6 * self._n_params()
        # PADDLE_TPU_METRICS_PORT / PADDLE_TPU_FLIGHT_DIR opt-ins: one
        # getenv each when unset, zero per-step cost while off
        _obs_exporter.ensure_started_from_env()
        _obs_flight.ensure_from_env()
        # FLAGS_health_monitor / PADDLE_TPU_HEALTH_DIR: in-program training
        # health stats as an aux output of the compiled step. None (the
        # default) keeps the step program byte-identical to pre-health builds
        self._health = _obs_health.from_env_or_flags(
            {n: tuple(self._state_refs[n].shape) for n in self._param_names})
        # FLAGS_ckpt_dir / PADDLE_TPU_CKPT_DIR: elastic checkpointing
        # (distributed/elastic.py) — async crash-safe snapshots every
        # FLAGS_ckpt_interval steps. None (the default) costs one flag read
        # here and one None-check per step
        self._ckpt = _elastic.from_flags()

    def _n_params(self) -> int:
        return int(sum(
            int(np.prod(self._state_refs[n].shape) or 1)
            for n in self._param_names))

    def enable_telemetry(self, sink=None, path=None,
                         flops_per_token: Optional[int] = None,
                         peak_flops: Optional[float] = None,
                         collect_live_buffers: bool = False) -> StepTelemetry:
        """Attach per-step telemetry. Default flop model is parameter-only
        (6*N per token); pass flops_per_token from
        observability.transformer_flops_per_token for the full bench.py
        accounting with the attention term. collect_live_buffers=True adds
        a per-record live-array census + high-water — the donation proof on
        backends where PJRT exposes no memory stats."""
        from ..observability.step_telemetry import JsonlSink

        if sink is None and path is not None:
            sink = JsonlSink(path)
        self.telemetry = StepTelemetry(
            sink=sink,
            flops_per_token=(flops_per_token if flops_per_token is not None
                             else 6 * self._n_params()),
            peak_flops=peak_flops,
            collect_live_buffers=collect_live_buffers)
        return self.telemetry

    def disable_telemetry(self) -> None:
        if self.telemetry is not None:
            self.telemetry.close()
        self.telemetry = None

    # ---- training-health telemetry (observability/health.py) ----
    def enable_health(self, interval: Optional[int] = None,
                      spike_factor: Optional[float] = None, sink=None,
                      path: Optional[str] = None, ring_capacity: int = 64):
        """Attach the in-program TrainingHealthMonitor: grad/weight/update
        norms + non-finite localization computed as an aux output of the
        SAME compiled step (zero extra dispatches), fetched to host every
        `interval` steps as ONE packed f32 [4P] transfer. Invalidates the
        cached step executables (the program's output arity changes)."""
        from ..observability.step_telemetry import JsonlSink

        if sink is None and path is not None:
            sink = JsonlSink(path)
        self._health = _obs_health.TrainingHealthMonitor(
            {n: tuple(self._state_refs[n].shape) for n in self._param_names},
            interval=interval, spike_factor=spike_factor, sink=sink,
            ring_capacity=ring_capacity)
        self._invalidate_step_fns()
        return self._health

    def disable_health(self) -> None:
        if self._health is not None:
            self._health.close()
        self._health = None
        self._invalidate_step_fns()

    # ---- elastic checkpointing (distributed/elastic.py) ----
    def enable_checkpointing(self, dirname: str, interval: Optional[int] = None,
                             keep: Optional[int] = None,
                             async_save: Optional[bool] = None,
                             rollback_on_nonfinite: Optional[bool] = None,
                             resume: bool = False):
        """Attach a CheckpointManager: async crash-safe snapshots of
        params / optimizer state (including ZeRO flat shards) / RNG / step
        every `interval` optimizer steps, committed by atomic rename with
        checksummed manifests, newest `keep` retained. ``resume=True``
        restores the newest valid checkpoint from `dirname` right now (a
        preempted job's restart line), silently starting fresh when the
        directory holds none. Unset kwargs fall back to the FLAGS_ckpt_*
        defaults. Does NOT touch the compiled step (the snapshot is pure
        host-side capture), so no recompile."""
        if self._ckpt is not None:
            self._ckpt.close()
        self._ckpt = _elastic.CheckpointManager(
            dirname,
            interval=(_flags.flag("ckpt_interval") if interval is None
                      else interval),
            keep=_flags.flag("ckpt_keep") if keep is None else keep,
            async_save=(_flags.flag("ckpt_async") if async_save is None
                        else async_save),
            rollback_on_nonfinite=(
                _flags.flag("ckpt_rollback") if rollback_on_nonfinite is None
                else rollback_on_nonfinite))
        if resume:
            try:
                self._ckpt.restore(self)
            except FileNotFoundError:
                pass  # nothing saved yet: a fresh run, not an error
        return self._ckpt

    def disable_checkpointing(self) -> None:
        if self._ckpt is not None:
            self._ckpt.close()
        self._ckpt = None

    # ---- legacy executable-cache views over the ExecutableRegistry ------
    @property
    def _step_fn(self):
        entry = self._execs.entry_for(("train.step",))
        return entry.fn if entry is not None else None

    @_step_fn.setter
    def _step_fn(self, fn) -> None:
        if fn is None:
            self._execs.discard("train.step")
        else:
            self._execs.put(("train.step",), fn, label="train.step",
                            pin=True)

    @property
    def _accum_fns(self):
        """Legacy view: {(k, dtype, use_residual, chunk, health_on, zero):
        fn} — the config tuple is the registry key minus its program id."""
        return {e.key[1:]: e.fn for e in self._execs.entries()
                if e.key[0] == "train.accum"}

    @property
    def _exec_stash(self):
        """label -> (jitted fn, abstract args), owned by the registry."""
        return self._execs.stash_map()

    def exec_registry(self) -> ExecutableRegistry:
        """This engine's ExecutableRegistry (step/accum/scan executables)."""
        return self._execs

    def _invalidate_step_fns(self) -> None:
        """Drop cached step executables + their introspection stash — the
        next step() recompiles with the new output signature."""
        self._execs.discard("train.step")
        self._execs.discard("train.accum")
        self._execs.clear_stash()

    def reform_mesh(self, new_hcg: HybridCommunicateGroup) -> None:
        """Live in-memory mesh reformation (elastic autoscaling).

        Re-forms this engine onto ``new_hcg``'s mesh without a disk bounce:
        params and optimizer state are host-gathered from the old mesh
        (flat ZeRO slot shards at their true ``[:n]`` prefix — the same
        segment_layout-ordered vector elastic.py's checkpoint reslice
        uses), every device placement is rebuilt against the new topology,
        and only then does the engine commit. Any failure before the
        commit point leaves the engine fully on the OLD mesh, so the
        caller's ``restore_latest`` fallback still has a coherent engine
        to restore into.

        Bit-equality contract: the host values placed here are exactly the
        bytes a synchronous checkpoint at this boundary would hold, and the
        target shardings are exactly what a fresh engine + restore onto
        ``new_hcg`` would build — so the continued loss curve is
        bit-identical to the checkpoint-restore path on the same topology
        change (tests/test_elastic_live.py pins this for both the
        replicated and ZeRO optimizer layouts).

        The ZeRO flat buffer re-pads to the NEW replica count: pad elements
        are zeros by construction and stay zero through every whitelisted
        update rule, so growing/shrinking the pad tail never perturbs real
        state.
        """
        new_mesh = new_hcg.mesh
        use_sharding = bool(self.strategy and
                            getattr(self.strategy, "sharding", False)) or \
            new_hcg.degrees["sharding"] > 1

        # ---- host gather off the OLD mesh (owned copies) ----
        fsdp_live = self._fsdp_params is not None
        host_zero = None
        if fsdp_live:
            # decode the per-layer bucket shards into the replicated host
            # view — exactly the bytes a synchronous checkpoint at this
            # boundary would hold — then re-encode below against the NEW
            # replica count (the flat param shards reslice, like ZeRO's)
            host_params = {n: np.array(v, copy=True)
                           for n, v in self._gather_fsdp_params().items()}
            host_opt = {n: tuple(np.array(s, copy=True) for s in slots)
                        for n, slots in self._gather_fsdp_opt().items()}
        else:
            host_params = {n: np.array(self.params[n], copy=True)
                           for n in self._param_names}
            host_opt = None
            if self.opt_state is not None:
                host_opt = {n: tuple(np.array(s, copy=True)
                                     for s in self.opt_state[n])
                            for n in self._param_names}
            if self._zero_opt is not None:
                n_elems = self._n_grad_elems()
                host_zero = [np.array(f, copy=True)[:n_elems]
                             for f in self._zero_opt]

        # ---- rebuild placements against the NEW mesh (temporaries) ----
        new_param_specs = {}
        new_params = {}
        for n in self._param_names:
            p = self._state_refs[n]
            spec = _param_spec(p, p.shape, new_hcg)
            new_param_specs[n] = spec
            if not fsdp_live:     # fsdp re-encodes shards, never replicates
                new_params[n] = jax.device_put(
                    host_params[n], NamedSharding(new_mesh, spec))
        new_opt_specs = {
            n: _opt_state_spec(new_param_specs[n],
                               self._state_refs[n].shape, new_hcg,
                               use_sharding)
            for n in self._param_names}

        def _opt_sh(spec):
            if self._opt_memory_kind:
                return NamedSharding(new_mesh, spec,
                                     memory_kind=self._opt_memory_kind)
            return NamedSharding(new_mesh, spec)

        new_opt_state = None
        if host_opt is not None and not fsdp_live:
            new_opt_state = {
                n: tuple(jax.device_put(s, _opt_sh(new_opt_specs[n]))
                         for s in host_opt[n])
                for n in self._param_names}

        new_zero = None
        if host_zero is not None:
            batch_axes = tuple(a for a in ("dp", "sharding")
                               if new_hcg.degrees[a] > 1)
            nrep_new = _gc.replica_count(new_mesh, batch_axes)
            n_elems = self._n_grad_elems()
            n_pad_new = _gc.zero_pad_elems(n_elems, nrep_new,
                                           _gc.chunk_size())
            spec = P(batch_axes if len(batch_axes) > 1
                     else (batch_axes[0] if batch_axes else None))
            sh = NamedSharding(new_mesh, spec)
            flats = []
            for f in host_zero:
                buf = np.zeros((n_pad_new,), np.float32)
                buf[:n_elems] = f
                flats.append(jax.device_put(buf, sh))
            new_zero = tuple(flats)

        new_fsdp_params = new_fsdp_opt = None
        if fsdp_live:
            batch_axes = tuple(a for a in ("dp", "sharding")
                               if new_hcg.degrees[a] > 1)
            nrep_new = _gc.replica_count(new_mesh, batch_axes)
            buckets_new = _gc.fsdp_buckets(
                {n: tuple(self._state_refs[n].shape)
                 for n in self._param_names},
                nrep_new, _gc.chunk_size(), layer_key=self._fsdp_layer_key())
            spec = P(batch_axes if len(batch_axes) > 1
                     else (batch_axes[0] if batch_axes else None))
            new_fsdp_params, new_fsdp_opt = self._encode_fsdp_state(
                host_params, host_opt, buckets_new,
                NamedSharding(new_mesh, spec))

        # surface transfer failures (OOM, detached device) BEFORE commit
        for arr in new_params.values():
            arr.block_until_ready()
        if new_opt_state is not None:
            for slots in new_opt_state.values():
                for s in slots:
                    s.block_until_ready()
        if new_zero is not None:
            for f in new_zero:
                f.block_until_ready()
        if new_fsdp_params is not None:
            for f in new_fsdp_params:
                f.block_until_ready()
            for slot in new_fsdp_opt:
                for f in slot:
                    f.block_until_ready()

        # ---- commit + drop every mesh-derived cache ----
        self.hcg = new_hcg
        self.mesh = new_mesh
        self.param_specs = new_param_specs
        self.params = None if fsdp_live else new_params
        self.opt_specs = new_opt_specs
        self.opt_state = new_opt_state
        self._zero_opt = new_zero
        self._fsdp_params = new_fsdp_params
        self._fsdp_opt = new_fsdp_opt
        self._invalidate_step_fns()
        self._execs.discard("train.run_steps")
        self._scan_batch_shardings = {}
        self._batch_shardings = None
        # error-feedback residual is per-replica accumulator state tied to
        # the old replica count; reformation restarts it at zero (same as
        # the checkpoint-restore path, which never persists it)
        self._grad_residual = None
        self._pending_h2d = None
        self._lr_cache = (None, None)
        self._zero_reason = "unset"
        self._zero_warned = False
        self._fsdp_cache = None
        self._fsdp_warned = False
        self._gspmd_warned = False

    # ---- compiled-executable introspection (observability/exec_introspect) --
    def _stash_exec(self, label: str, fn, call_args) -> None:
        """First call per label: remember (jitted fn, abstract args) so
        introspect_executables() can AOT-lower the same program later, and
        auto-capture now when FLAGS_exec_introspect is on. Abstract
        ShapeDtypeStructs replace the arrays (no live-buffer retention);
        PRNG keys stay concrete (extended dtypes don't round-trip avals)."""
        def aval(a):
            try:
                if jax.dtypes.issubdtype(a.dtype, jax.dtypes.prng_key):
                    return a
            except Exception:
                pass
            # weak_type rides along: the recompile-hazard analysis pass reads
            # it off the stashed signature (a weak lr would retrace per call)
            return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                        weak_type=getattr(a, "weak_type",
                                                          False))

        self._execs.stash(label, fn, call_args, donate=(), aval_fn=aval)

    def introspect_executables(self, force: bool = False) -> Dict[str, dict]:
        """Capture XLA memory_analysis()/cost_analysis() for every train
        executable this engine has dispatched (label -> stats dict; also
        mirrored into registry gauges exec.<label>.* when metrics are
        active). Costs one extra AOT compile per uncaptured label."""
        out = {}
        for label, (fn, avals) in list(self._exec_stash.items()):
            out[label] = _obs_exec.capture_jit(
                label, fn, avals, force=force,
                extra=self._introspect_extra(label))
        return out

    def _introspect_extra(self, label: str):
        """Per-label annotations merged into exec_introspect stats: fsdp
        train programs carry the resolved gather-prefetch depth and the
        analytic live-gathered window bytes, so the
        exec.train.fsdp_*.fsdp_window_bytes gauge lands next to the
        measured temp bytes it bounds (mem_report cross-checks the two)."""
        if not label.startswith("train.fsdp"):
            return None
        depth = self._fsdp_prefetch()
        return {"fsdp_prefetch": depth,
                "fsdp_window_bytes": _gc.fsdp_window_bytes(
                    self._fsdp_layout(), depth),
                "fsdp_ahead_bytes": _gc.fsdp_prefetch_ahead_bytes(
                    self._fsdp_layout(), depth)}

    # ---- static analysis (paddle_tpu.analysis) ----------------------------
    def _analysis_state_bytes(self, include_opt: bool = True) -> int:
        """Bytes of the donation-eligible carried state (replicated host
        view) — the same params(+opt) accounting the donation perf gate
        measures alias coverage against."""
        tree = (self.params, self.opt_state) if include_opt else self.params
        return sum(int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
                   for a in jax.tree_util.tree_leaves(tree)
                   if hasattr(a, "shape"))

    def default_contracts(self) -> list:
        """The contracts this engine's own executables are expected to meet,
        derived from its configuration: hygiene (no host transfers, no
        constant bloat, no recompile hazards) on every train label, donation
        coverage when donation is on, and — on pure-dp meshes with real
        replicas — the collective shapes each path promises (one fused accum
        all-reduce, the ZeRO reduce-scatter/all-gather decomposition, the
        quantized-gather int8 path, combining-backend GSPMD step shapes)."""
        from .. import analysis as _an

        cs = [_an.ProgramContract(label="train.*", name="train-hygiene")]
        if self._donate:
            full = self._analysis_state_bytes()
            for pat in ("train.step", "train.run_steps", "train.accum_*"):
                cs.append(_an.ProgramContract(
                    label=pat, donated_bytes=full, name="train-donation"))
            # ZeRO donates full params but only this shard's opt state
            cs.append(_an.ProgramContract(
                label="train.zero_*",
                donated_bytes=self._analysis_state_bytes(include_opt=False),
                name="zero-donation"))
        ndp = self.hcg.degrees["dp"] * self.hcg.degrees["sharding"]
        if ndp > 1 and self._dp_pure():
            # ByGlobalNorm clip adds one scalar norm psum to the fused reduce
            clip_hi = 2 if self.optimizer._grad_clip is not None else 1
            # with a resolved prefetch window the f32/bf16 fsdp programs
            # additionally promise the overlap-ahead schedule: each bucket's
            # all-gather defined before the previous bucket's dominant
            # consumer (ISSUE 20's schedule-order pass)
            sched = ("all-gather-ahead" if self._fsdp_prefetch() >= 2
                     else None)
            cs += [
                _an.ProgramContract(
                    "train.accum_*_f32",
                    collectives={"all-reduce": (1, clip_hi)},
                    while_loops=(1, None), name="accum-fused-reduce"),
                _an.ProgramContract(
                    "train.accum_*_bf16*",
                    collectives={"all-reduce": (1, clip_hi)},
                    while_loops=(1, None), comm_dtype="bf16",
                    name="accum-fused-reduce-bf16"),
                _an.ProgramContract(
                    "train.accum_*_int8*",
                    collectives={"all-gather": (1, None),
                                 "reduce-scatter": 0},
                    while_loops=(1, None), comm_dtype="int8",
                    name="accum-quantized-gather"),
                _an.ProgramContract(
                    "train.zero_*",
                    collectives={"reduce-scatter": 1, "all-gather": (1, 2),
                                 "all-reduce": (0, clip_hi - 1),
                                 "all-to-all": 0},
                    while_loops=(1, None), name="zero-decomposition"),
                # fsdp: exactly L per-bucket weight gathers + ONE grad
                # reduce-scatter, zero full-buffer all-reduces, K-independent
                # (int8 swaps the scatter for two EQuARX all-to-alls)
                _an.ProgramContract(
                    "train.fsdp_*_f32",
                    collectives={"all-gather": len(self._fsdp_layout()),
                                 "reduce-scatter": 1,
                                 "all-reduce": (0, clip_hi - 1),
                                 "all-to-all": 0},
                    while_loops=(1, None), schedule_order=sched,
                    name="fsdp-decomposition"),
                _an.ProgramContract(
                    "train.fsdp_*_bf16*",
                    collectives={"all-gather": len(self._fsdp_layout()),
                                 "reduce-scatter": 1,
                                 "all-reduce": (0, clip_hi - 1),
                                 "all-to-all": 0},
                    while_loops=(1, None), schedule_order=sched,
                    name="fsdp-decomposition-bf16"),
                _an.ProgramContract(
                    "train.fsdp_*_int8*",
                    collectives={"all-gather": len(self._fsdp_layout()),
                                 "reduce-scatter": 0,
                                 "all-to-all": 2,
                                 "all-reduce": (0, clip_hi - 1)},
                    while_loops=(1, None), name="fsdp-quantized"),
                _an.ProgramContract(
                    "train.step", requires_combining=True,
                    collectives={"all-reduce": (1, 4)},
                    name="step-fused-reduce"),
                _an.ProgramContract(
                    "train.run_steps", requires_combining=True,
                    collectives={"all-reduce": (1, 4)}, while_loops=1,
                    name="run-steps-one-loop"),
            ]
        return cs

    def analyze(self, contracts=None, dump: Optional[bool] = None):
        """Run the static-analysis pass suite over every executable this
        engine has dispatched (see paddle_tpu.analysis). Dispatch-free:
        programs are AOT-lowered from the stashed abstract signatures, never
        executed. Returns an AnalysisReport; violations bump the
        analysis.* counters and (FLAGS_analysis_flight_dump) flight-dump."""
        from .. import analysis as _an

        progs = _an.programs_from_stash(self._exec_stash)
        if contracts is None:
            contracts = self.default_contracts()
        return _an.PassManager().run(progs, contracts, dump=dump)

    def _obs_step_tail(self, fr, mreg, rec, t0, t1, h2d_ms, compiled, loss,
                       hist="train.step_ms"):
        """Shared observability tail for step/_accum_step/run_steps: feed
        the metrics histograms and tee the step record into the flight
        recorder ring. Both fr and mreg are usually None (one check each in
        the callers); loss is only fetched when a recorder needs it."""
        if mreg is not None:
            mreg.histogram(hist).observe((t1 - t0) * 1e3)
            if h2d_ms:
                mreg.histogram("train.h2d_ms").observe(h2d_ms)
            if compiled:
                mreg.histogram("train.compile_ms").observe((t1 - t0) * 1e3)
        if fr is not None:
            if rec is None:
                rec = {"event": "train_step", "step": self._step_count,
                       "wall_time_s": t1 - t0,
                       "loss": float(jax.device_get(loss)),
                       "h2d_ms": h2d_ms, "compiled": compiled}
            fr.record(rec)
            lv = rec.get("loss")
            if lv is not None and not math.isfinite(lv):
                # diverged step: bump the counter and capture a post-mortem
                # dump whose ring tail ends with this very record
                _NAN_LOSS_STEPS.increase()
                fr.on_nan_inf("train_loss", {"step": self._step_count})

    @staticmethod
    def _batch_stats(arrays, lead_axes=0):
        """(samples, tokens) per dispatch from the first batch array: the
        leading dim is the sample axis. Tokens are only counted for integer
        id batches ([b, s] LM inputs) — dim 1 of a float feature matrix is
        features, not sequence, and must not inflate tokens/s."""
        if not arrays:
            return None, None
        shape = arrays[0].shape[lead_axes:]
        if not shape:
            return None, None
        samples = int(shape[0])
        tokens = None
        if len(shape) >= 2 and np.issubdtype(np.dtype(arrays[0].dtype),
                                             np.integer):
            tokens = samples * int(shape[1])
        return samples, tokens

    def _opt_sharding(self, spec):
        """NamedSharding for one optimizer-state leaf; host-memory-resident
        when the optimizer requested offload."""
        if self._opt_memory_kind:
            return NamedSharding(self.mesh, spec,
                                 memory_kind=self._opt_memory_kind)
        return NamedSharding(self.mesh, spec)

    # ---- step function construction ----
    def _build_compute_loss(self):
        """(params, key, *batch) -> scalar loss: the EXACT forward trace the
        fused step differentiates (sp scope, amp autocast, buffers, loss_fn
        convention). Shared by _raw_step and analysis_loss so the planner's
        policy-aware residual accounting can never trace a different program
        than the one that trains."""
        model = self.model
        loss_fn = self.loss_fn
        num_model_inputs = self.num_model_inputs
        buffer_names = self._buffer_names
        buffers = self.buffers

        import contextlib

        from .meta_parallel.sequence_parallel import sequence_parallel_scope

        sp_deg = self.hcg.degrees["sp"]
        # default matches DistributedStrategy.sep_impl: Ulysses wins on the
        # XLA cost model at moderate seq (BASELINE.md); ring for seq >> 100k
        sp_impl = getattr(self.strategy, "sep_impl", "ulysses") \
            if self.strategy else "ulysses"
        mesh = self.mesh

        # strategy.amp: autocast the whole traced forward (the analogue of the
        # static amp_optimizer's program rewrite — here the cast happens at
        # trace time through the dispatch-level autocast). float16 is forced to
        # bfloat16: the fused step has no loss scaling, and bf16's f32 exponent
        # range makes scaling unnecessary — fp16 without scaling would silently
        # under/overflow.
        amp_cfg = getattr(self.strategy, "amp_configs", None) \
            if self.strategy is not None and getattr(self.strategy, "amp", False) else None

        def _amp_ctx():
            if amp_cfg is None:
                return contextlib.nullcontext()
            from ..amp import amp_guard_from_configs

            return amp_guard_from_configs(amp_cfg, force_bf16=True)

        def compute_loss(ps, key, *batch):
            state = dict(ps)
            for bn in buffer_names:
                state[bn] = buffers[bn]
            sp_ctx = (sequence_parallel_scope(mesh, "sp", sp_impl)
                      if sp_deg > 1 else contextlib.nullcontext())
            with sp_ctx, _amp_ctx(), random_mod.trace_key_scope(key):
                inputs = [Tensor(b, stop_gradient=True) for b in batch]
                if loss_fn is None:
                    out = functional_call(model, state, *inputs)
                else:
                    n_in = model_input_count(len(inputs), num_model_inputs)
                    out = functional_call(model, state, *inputs[:n_in])
                    outs = out if isinstance(out, (tuple, list)) else (out,)
                    out = loss_fn(*outs, *inputs[n_in:])
            loss = out[0] if isinstance(out, (tuple, list)) else out
            return loss._data if isinstance(loss, Tensor) else loss

        return compute_loss

    def analysis_loss(self, *batch):
        """Pure params -> scalar loss over a fixed batch, tracing the same
        program step() differentiates. For trace-level analyses only (e.g.
        the planner's jax saved_residuals remat accounting) — nothing is
        compiled or executed, training state is untouched."""
        compute = self._build_compute_loss()
        arrays = self._to_arrays(batch)
        key = jax.random.key(0)
        return lambda params: compute(params, key, *arrays)

    def _raw_step(self, health_stats=None):
        update = opt_funct.make_tree_update(
            self.optimizer, {n: self._state_refs[n] for n in self._param_names})
        clip = self.optimizer._grad_clip
        compute = self._build_compute_loss()

        # grads are pinned to the opt-state specs when ZeRO is active (plain
        # partition specs — the offload memory kind must NOT ride along:
        # grads live in HBM, only the persistent state is host-resident)
        zero_specs = (self.opt_specs
                      if self.hcg.degrees["sharding"] > 1 else None)
        param_specs_c = self.param_specs
        mesh = self.mesh

        def step(params, opt_state, lr, step_i, key, *batch):
            loss, grads = jax.value_and_grad(
                lambda ps: compute(ps, key, *batch))(params)
            raw_grads = grads  # pre-clip: what health attribution must see
            if zero_specs is not None:
                # ZeRO stage-1/2 boundary (reference group_sharded_optimizer_
                # stage2.py:48 semantics), in TWO chained constraints:
                # 1. grad at the PARAM spec — stops the optimizer-state
                #    sharding from propagating backward INTO the grad
                #    computation. Un-pinned, GSPMD pushes e.g. the embedding
                #    m/v spec ("mp","sharding") onto the wte grad
                #    scatter-add, which then demands its [b,s,h] update
                #    operand hidden-sharded — a batch->hidden reshard the
                #    partitioner can only do by full rematerialization
                #    (VERDICT r3 #4). At the param spec the scatter keeps
                #    batch-sharded updates and emits partial grads + psum.
                # 2. grad at the OPT spec — the explicit ZeRO transition,
                #    a composable subdivide that lowers to
                #    reduce-scatter/dynamic-slice, after which the update
                #    runs on the shard and only new params all-gather.
                grads = {n: jax.lax.with_sharding_constraint(
                    g, NamedSharding(mesh, param_specs_c[n]))
                    for n, g in grads.items()}
                grads = {n: jax.lax.with_sharding_constraint(
                    g, NamedSharding(mesh, zero_specs[n]))
                    for n, g in grads.items()}
            grads = opt_funct.clip_grads(grads, clip)
            new_params, new_opt = update(params, grads, opt_state, lr, step_i)
            if health_stats is None:
                return loss, new_params, new_opt
            return loss, new_params, new_opt, health_stats(
                raw_grads, params, new_params)

        return step

    def _build(self, batch_avals):
        health = self._health
        step = self._raw_step(
            health.make_packed_stats() if health is not None else None)
        param_shardings = {n: NamedSharding(self.mesh, s) for n, s in self.param_specs.items()}
        # the jitted step is all-device; offload transfers happen at the
        # python boundary in step() (jax 0.9 dropped in-jit memory transfers)
        opt_shardings = {
            n: tuple(NamedSharding(self.mesh, self.opt_specs[n])
                     for _ in self.opt_state[n])
            for n in self._param_names}
        batch_shardings = self._shardings_for(batch_avals)
        scalar = NamedSharding(self.mesh, P())
        out_sh = (scalar, param_shardings, opt_shardings)
        if health is not None:
            out_sh += (scalar,)  # packed f32 [4P] health buffer, replicated

        return jax.jit(
            step,
            in_shardings=(param_shardings, opt_shardings, scalar, scalar, scalar)
            + batch_shardings,
            out_shardings=out_sh,
            donate_argnums=(0, 1) if self._donate else (),
        )

    def _build_scan(self, batch_avals, fixed_batch):
        """K train steps fused into ONE compiled program via lax.scan.

        The analogue of the reference's fleet_executor running a whole section
        of iterations per dispatch (fleet_executor/compute_interceptor.cc's
        LoopCounter / max_run_times) instead of one step per Executor.run —
        on TPU it also collapses K PJRT execute round-trips into one, which
        matters through remote/tunneled backends where each execute pays
        network latency. With fixed_batch=False, batch arrays carry a leading
        [K] axis and the scan consumes one slice per step; with
        fixed_batch=True the same single batch feeds every step (scan
        xs=None — one device copy, not K). Per-step learning rates arrive as
        a [K] f32 array (schedules stay host-side).
        """
        step = self._raw_step()

        def multi(params, opt_state, lrs, step0, keys, *batch):
            # keys: [K] array of per-step subkeys, split HOST-side with the
            # exact split sequence step() uses — so dropout streams (and thus
            # losses) match a loop of K step() calls bit-for-bit
            def body(carry, xs):
                p, o, i = carry
                sub = xs[0]
                loss, p, o = step(p, o, lrs[i], step0 + i, sub,
                                  *(batch if fixed_batch else xs[1:]))
                return (p, o, i + jnp.int32(1)), loss

            (params, opt_state, _), losses = jax.lax.scan(
                body, (params, opt_state, jnp.int32(0)),
                (keys,) if fixed_batch else (keys,) + tuple(batch))
            return losses, params, opt_state

        param_shardings = {n: NamedSharding(self.mesh, s)
                           for n, s in self.param_specs.items()}
        opt_shardings = {
            n: tuple(NamedSharding(self.mesh, self.opt_specs[n])
                     for _ in self.opt_state[n])
            for n in self._param_names}
        if self.input_specs is not None:
            per_step = self.input_specs
        else:
            lead = 0 if fixed_batch else 1
            per_step = [_default_input_spec(a.shape[lead:], self.hcg)
                        for a in batch_avals]
        batch_shardings = tuple(
            NamedSharding(self.mesh, s if fixed_batch else P(None, *s))
            for s in per_step)
        scalar = NamedSharding(self.mesh, P())

        self._scan_batch_shardings[fixed_batch] = batch_shardings
        return jax.jit(
            multi,
            in_shardings=(param_shardings, opt_shardings, scalar, scalar,
                          scalar) + batch_shardings,
            out_shardings=(scalar, param_shardings, opt_shardings),
            donate_argnums=(0, 1) if self._donate else (),
        )

    # ---- microbatch gradient accumulation (grad_comm) ----
    def _batch_axes(self):
        return tuple(a for a in ("dp", "sharding")
                     if self.hcg.degrees[a] > 1)

    def _dp_pure(self) -> bool:
        """True when the mesh is pure data-parallel (dp and/or ZeRO sharding
        only) and every param is replicated — the shard_map deferred-reduce
        fast path (ONE fused gradient all-reduce independent of K)."""
        if any(self.hcg.degrees[a] > 1 for a in ("mp", "sp", "ep", "pp")):
            return False
        return all(all(e is None for e in tuple(s))
                   for s in self.param_specs.values())

    def _grad_comm_config(self):
        """(k, dtype, use_residual, chunk, zero) resolved from the engine +
        flags. The accumulation path engages when K > 1, a low-precision
        gradient collective is requested, or the ZeRO weight-update
        sharding is on; otherwise step() stays on the original
        (bit-identical) fused step."""
        k = max(1, int(self.microbatches))
        dtype = _gc.comm_dtype()
        if not self._dp_pure():
            if dtype != "f32" and not self._gspmd_warned:
                import warnings

                warnings.warn(
                    f"FLAGS_grad_comm_dtype={dtype} applies only to pure "
                    f"data-parallel meshes; topology {self.hcg.topology()} "
                    f"uses GSPMD collectives (f32)")
                self._gspmd_warned = True
            dtype = "f32"
        use_residual = (dtype != "f32" and self._dp_pure()
                        and _gc.error_feedback())
        return k, dtype, use_residual, _gc.chunk_size(), self._zero_on()

    # ---- ZeRO weight-update sharding (arXiv:2004.13336) ----
    # optimizer rules whose update is a uniform elementwise function of
    # (param, grad, state) — safe to run on an arbitrary contiguous slice
    # of the flat buffer. lamb/lars need per-parameter trust ratios.
    _ZERO_RULES = frozenset({"sgd", "momentum", "adam", "adamw", "adamax",
                             "adagrad", "adadelta", "rmsprop"})

    def _zero_requested(self) -> bool:
        return bool(self.zero_update or _flags.flag("zero_update"))

    def _zero_fallback_reason(self) -> Optional[str]:
        """None when the weight-update sharding can engage; otherwise a
        human-readable reason. Cached — every input is engine-lifetime
        static (mesh topology, optimizer rule/kwargs/clip, offload)."""
        if self._zero_reason != "unset":
            return self._zero_reason
        from ..nn.clip import ClipGradByGlobalNorm, ClipGradByValue

        opt = self.optimizer
        reason = None
        if not self._dp_pure():
            reason = (f"topology {self.hcg.topology()} is not pure "
                      "data-parallel; running the GSPMD accumulation path")
        elif not self._param_names:
            reason = "no trainable parameters"
        elif opt._rule not in self._ZERO_RULES:
            reason = (f"optimizer rule {opt._rule!r} is not uniform-"
                      "elementwise (needs per-parameter norms)")
        elif any(opt._rule_kwargs(self._state_refs[n]) !=
                 opt._rule_kwargs(self._state_refs[self._param_names[0]])
                 for n in self._param_names):
            reason = ("per-parameter rule kwargs differ (e.g. weight-decay "
                      "exclusions): the flat shard update needs ONE "
                      "uniform rule")
        elif not (opt._grad_clip is None or isinstance(
                opt._grad_clip, (ClipGradByGlobalNorm, ClipGradByValue))):
            reason = (f"grad clip {type(opt._grad_clip).__name__} needs "
                      "per-parameter norms")
        elif self._opt_memory_kind:
            reason = ("optimizer offload keeps the replicated host-"
                      "resident state")
        self._zero_reason = reason
        return reason

    def _zero_on(self) -> bool:
        """True when this step runs the ZeRO weight-update-sharded program
        (requested AND compatible). Incompatible configs warn ONCE and run
        the replicated (or GSPMD) update. Yields to fsdp — the fully
        sharded path subsumes the weight-update sharding."""
        if self._fsdp_on():
            return False
        if not self._zero_requested():
            return False
        reason = self._zero_fallback_reason()
        if reason is None:
            return True
        if not self._zero_warned:
            import warnings

            warnings.warn("zero_update requested but falling back to the "
                          f"replicated update: {reason}")
            self._zero_warned = True
        return False

    def _zero_n_slots(self) -> int:
        """Optimizer-state slots per parameter for the active rule (0 for
        sgd, 1 for momentum/adagrad, 2 for adam/adamw, ...)."""
        return len(opt_funct.init_state(self.optimizer._rule,
                                        np.zeros((1,), np.float32)))

    def _zero_layout(self):
        """(n, n_pad, shard, nrep) of the flat parameter/optimizer-state
        vector: n grad elements padded to a multiple of nrep*chunk, each
        replica owning the contiguous [r*shard, (r+1)*shard) slice."""
        nrep = _gc.replica_count(self.mesh, self._batch_axes())
        n = self._n_grad_elems()
        n_pad = _gc.zero_pad_elems(n, nrep, _gc.chunk_size())
        return n, n_pad, n_pad // max(1, nrep), nrep

    def _make_flat_update(self):
        """The ZeRO twin of opt_funct.make_tree_update: ONE uniform
        elementwise rule over flat f32 [shard] vectors. Uniformity of the
        per-param kwargs is guaranteed upstream by _zero_fallback_reason;
        pad slots (zero param, zero grad, zero state) stay exactly zero
        through every whitelisted rule."""
        rule = opt_funct.RULES[self.optimizer._rule]
        needs_step = self.optimizer._rule in opt_funct._NEEDS_STEP
        kw0 = dict(self.optimizer._rule_kwargs(
            self._state_refs[self._param_names[0]]))

        def flat_update(p_shard, g_shard, opt_shards, lr, step_i):
            kw = dict(kw0)
            if needs_step:
                kw["step"] = step_i
            new_p, new_state = rule(p_shard, g_shard, tuple(opt_shards),
                                    lr=lr, **kw)
            return new_p, tuple(new_state)

        return flat_update

    def _ensure_zero_opt(self):
        """Lazy ONE-WAY conversion of the replicated opt-state dict into
        flat f32 1/N shards (segment_layout / sorted-name order, zero pad
        tail). After the first sharded step self.opt_state is None — the
        flat shards ARE the state; _gather_zero_opt() reconstructs the
        dict form for checkpoints/debugging."""
        n, n_pad, shard, nrep = self._zero_layout()
        if self._zero_opt is not None:
            if self._zero_opt and self._zero_opt[0].shape != (n_pad,):
                raise ValueError(
                    "the flat sharded optimizer state was built for a "
                    f"different layout ({self._zero_opt[0].shape[0]} != "
                    f"{n_pad} elements) — FLAGS_grad_comm_chunk or the "
                    "mesh changed after the first ZeRO step; rebuild the "
                    "engine")
            return self._zero_opt
        sh = self._residual_sharding()
        names = sorted(self._param_names)
        flats = []
        for j in range(self._zero_n_slots()):
            buf = np.zeros((n_pad,), np.float32)
            off = 0
            for nm in names:
                size = int(np.prod(self._state_refs[nm].shape) or 1)
                buf[off:off + size] = np.asarray(
                    self.opt_state[nm][j], np.float32).reshape(-1)
                off += size
            flats.append(jax.device_put(buf, sh))
        self._zero_opt = tuple(flats)
        self.opt_state = None  # one-way: the flat shards are the state now
        return self._zero_opt

    def _gather_zero_opt(self):
        """Reconstruct the replicated {name: (slot, ...)} opt-state dict
        from the flat shards (host gather; checkpoint/debug convenience).
        Returns self.opt_state unchanged when ZeRO never engaged."""
        if self._zero_opt is None:
            return self.opt_state
        flats = [np.asarray(f) for f in self._zero_opt]
        out = {}
        off = 0
        for nm in sorted(self._param_names):
            shape = tuple(self._state_refs[nm].shape)
            size = int(np.prod(shape) or 1)
            out[nm] = tuple(f[off:off + size].reshape(shape)
                            for f in flats)
            off += size
        return out

    def zero_memory_model(self):
        """Analytic optimizer-state memory of the ZeRO path: replicated
        bytes per device vs flat-shard bytes per device (~1/N). The
        measured counterpart is introspect_executables() argument bytes."""
        n, n_pad, shard, nrep = self._zero_layout()
        slots = self._zero_n_slots()
        return {
            "opt_slots": slots,
            "replicas": nrep,
            "n_grad_elems": n,
            "n_pad": n_pad,
            "replicated_opt_bytes": slots * n * 4,
            "sharded_opt_bytes_per_device": slots * shard * 4,
        }

    # ---- FSDP: fully sharded parameters (arXiv:2004.13336, all the way) ----
    def _fsdp_requested(self) -> bool:
        return bool(self.fsdp or _flags.flag("fsdp"))

    def _fsdp_on(self) -> bool:
        """True when this step runs the fully-sharded program (requested
        AND compatible — the eligibility gate is exactly ZeRO's: pure-dp
        mesh, uniform elementwise rule, global-norm/value clip, no
        offload). Incompatible configs warn ONCE and run the replicated
        (or GSPMD) update. Supersedes zero_update when both are set."""
        if not self._fsdp_requested():
            return False
        reason = self._zero_fallback_reason()
        if reason is None:
            return True
        if not self._fsdp_warned:
            import warnings

            warnings.warn("fsdp requested but falling back to the "
                          f"replicated update: {reason}")
            self._fsdp_warned = True
        return False

    def _fsdp_layer_key(self):
        """The model's bucket-granularity hook (``fsdp_layer_key(name)``)
        or None for grad_comm.default_layer_key (one bucket per module)."""
        return getattr(self.model, "fsdp_layer_key", None)

    def _fsdp_layout(self):
        """Per-layer bucket metadata of the flat sorted-name parameter
        vector for the current mesh (cached per (nrep, chunk)): each
        bucket is a contiguous run of names sharing a layer key, padded
        to a multiple of nrep*chunk — these are the per-layer all-gather
        boundaries and the shard shapes of the resident state."""
        nrep = _gc.replica_count(self.mesh, self._batch_axes())
        chunk = _gc.chunk_size()
        if self._fsdp_cache is not None and \
                self._fsdp_cache[0] == (nrep, chunk):
            return self._fsdp_cache[1]
        buckets = _gc.fsdp_buckets(
            {n: tuple(self._state_refs[n].shape)
             for n in self._param_names},
            nrep, chunk, layer_key=self._fsdp_layer_key())
        self._fsdp_cache = ((nrep, chunk), buckets)
        return buckets

    def _fsdp_prefetch(self) -> int:
        """Resolved gather-prefetch window depth: FLAGS_fsdp_prefetch
        clamped against the current bucket layout so live-gathered bytes
        never exceed the two largest adjacent buckets (the double-buffer
        bound). Recomputed per step — reform_mesh() re-buckets, so the
        windowed step fns rebuild at the new topology's clamp."""
        return _gc.fsdp_prefetch_depth(self._fsdp_layout(),
                                       int(_flags.flag("fsdp_prefetch")))

    def fsdp_memory_model(self):
        """Analytic param+opt residency of the fsdp path: replicated
        bytes vs per-bucket flat-shard bytes per device (~1/N for BOTH
        params and optimizer state — ZeRO only shards the latter), plus
        the per-step wire bytes (L bucket weight gathers + one grad
        reduce-scatter). The measured counterpart is
        introspect_executables() argument bytes (tools/mem_report.py)."""
        buckets = self._fsdp_layout()
        nrep = _gc.replica_count(self.mesh, self._batch_axes())
        slots = self._zero_n_slots()
        n = self._n_grad_elems()
        shard_elems = [b["shard"] for b in buckets]
        rs_b, ag_b, per_layer = _gc.fsdp_payload_bytes(
            shard_elems, nrep, _gc.comm_dtype(), _gc.chunk_size())
        depth = self._fsdp_prefetch()
        return {
            "prefetch": depth,
            "window_bytes": _gc.fsdp_window_bytes(buckets, depth),
            "window_bytes_jit": _gc.fsdp_window_bytes(buckets, 0),
            "ahead_bytes": _gc.fsdp_prefetch_ahead_bytes(buckets, depth),
            "replicas": nrep,
            "n_grad_elems": n,
            "opt_slots": slots,
            "buckets": [{"key": b["key"], "n": b["n"], "pad": b["pad"],
                         "shard": b["shard"], "ag_bytes": ab}
                        for b, ab in zip(buckets, per_layer)],
            "replicated_param_bytes": n * 4,
            "sharded_param_bytes_per_device": sum(shard_elems) * 4,
            "replicated_opt_bytes": slots * n * 4,
            "sharded_opt_bytes_per_device": slots * sum(shard_elems) * 4,
            "rs_bytes": rs_b,
            "ag_bytes": ag_b,
        }

    def _encode_fsdp_state(self, params_src, opt_src, buckets, sh):
        """Encode replicated host-view params (+ opt-state dict) into the
        per-bucket flat f32 [pad] buffers placed with sharding ``sh``
        (sorted-name order within each bucket, zero pad tail). Returns
        (per-bucket param tuple, per-slot tuple of per-bucket tuples)."""
        n_slots = self._zero_n_slots()
        p_out = []
        o_cols = [[] for _ in range(n_slots)]
        for b in buckets:
            pbuf = np.zeros((b["pad"],), np.float32)
            obufs = [np.zeros((b["pad"],), np.float32)
                     for _ in range(n_slots)]
            off = 0
            for nm in b["names"]:
                size = int(np.prod(self._state_refs[nm].shape) or 1)
                pbuf[off:off + size] = np.asarray(
                    params_src[nm], np.float32).reshape(-1)
                if opt_src is not None:
                    for j in range(n_slots):
                        obufs[j][off:off + size] = np.asarray(
                            opt_src[nm][j], np.float32).reshape(-1)
                off += size
            p_out.append(jax.device_put(pbuf, sh))
            for j in range(n_slots):
                o_cols[j].append(jax.device_put(obufs[j], sh))
        return tuple(p_out), tuple(tuple(col) for col in o_cols)

    def _ensure_fsdp_state(self):
        """Lazy ONE-WAY conversion of the replicated params + opt state
        into per-bucket flat f32 1/N shards. After the first fsdp step
        self.params AND self.opt_state are None — the bucket shards ARE
        the state; _gather_fsdp_params()/_gather_fsdp_opt() reconstruct
        the replicated views for checkpoints/sync_to_model."""
        buckets = self._fsdp_layout()
        if self._fsdp_params is not None:
            if len(self._fsdp_params) != len(buckets) or any(
                    f.shape != (b["pad"],)
                    for f, b in zip(self._fsdp_params, buckets)):
                raise ValueError(
                    "the flat sharded parameter state was built for a "
                    "different bucket layout — FLAGS_grad_comm_chunk or "
                    "the mesh changed after the first fsdp step; rebuild "
                    "the engine")
            return self._fsdp_params, self._fsdp_opt
        self._param_dtypes = {n: np.dtype(self.params[n].dtype)
                              for n in self._param_names}
        opt_src = self._gather_zero_opt()  # dict view (handles prior ZeRO)
        self._fsdp_params, self._fsdp_opt = self._encode_fsdp_state(
            {n: np.asarray(self.params[n]) for n in self._param_names},
            opt_src, buckets, self._residual_sharding())
        self.params = None   # one-way: the bucket shards are the state now
        self.opt_state = None
        self._zero_opt = None
        return self._fsdp_params, self._fsdp_opt

    def _gather_fsdp_params(self):
        """Reconstruct the replicated {name: array} param dict from the
        bucket shards (host gather; checkpoint/sync convenience). Returns
        self.params unchanged when fsdp never engaged."""
        if self._fsdp_params is None:
            return self.params
        dts = self._param_dtypes or {}
        out = {}
        for b, f in zip(self._fsdp_layout(), self._fsdp_params):
            flat = np.asarray(f)
            off = 0
            for nm in b["names"]:
                shape = tuple(self._state_refs[nm].shape)
                size = int(np.prod(shape) or 1)
                out[nm] = flat[off:off + size].reshape(shape).astype(
                    dts.get(nm, np.float32), copy=False)
                off += size
        return out

    def _gather_fsdp_opt(self):
        """Replicated {name: (slot, ...)} opt-state dict decoded from the
        bucket shards; falls through to the ZeRO/replicated forms when
        fsdp never engaged."""
        if self._fsdp_params is None:
            return self._gather_zero_opt()
        cols = [[np.asarray(f) for f in col] for col in self._fsdp_opt]
        out = {}
        for bi, b in enumerate(self._fsdp_layout()):
            off = 0
            for nm in b["names"]:
                shape = tuple(self._state_refs[nm].shape)
                size = int(np.prod(shape) or 1)
                out[nm] = tuple(col[bi][off:off + size].reshape(shape)
                                for col in cols)
                off += size
        return out

    def _build_fsdp_accum(self, batch_avals, k, dtype, use_residual, chunk):
        """Jit the fully-sharded accumulation step: parameters enter AND
        leave as per-bucket flat f32 [pad] buffers sharded 1/N over the
        data axes (exactly like the ZeRO opt slots), each bucket
        all-gathers just before use inside the step, and ONE
        reduce-scatter lands the grads on the owning shard for the
        shard-local clip+update. No trailing parameter gather — that is
        the argument-bytes win over _build_zero_accum."""
        compute = self._build_compute_loss()
        health = self._health
        dts = self._param_dtypes or {}
        param_templates = {
            n: jax.ShapeDtypeStruct(
                tuple(self._state_refs[n].shape),
                self.params[n].dtype if self.params is not None
                else dts.get(n, np.dtype(np.float32)))
            for n in self._param_names}
        buckets = self._fsdp_layout()
        step = _gc.make_fsdp_accum_step(
            compute_loss=compute, flat_update=self._make_flat_update(),
            clip=self.optimizer._grad_clip, mesh=self.mesh,
            batch_axes=self._batch_axes(), k=k, dtype=dtype, chunk=chunk,
            use_residual=use_residual, param_templates=param_templates,
            buckets=buckets, prefetch=self._fsdp_prefetch(),
            health_partial=(health.make_sharded_stats()
                            if health is not None else None))
        batch_shardings = self._shardings_for(batch_avals)
        shard_sh = self._residual_sharding()
        p_sh = tuple(shard_sh for _ in buckets)
        opt_sh = tuple(p_sh for _ in range(self._zero_n_slots()))
        scalar = NamedSharding(self.mesh, P())
        in_sh = (p_sh, opt_sh)
        out_sh = (scalar, p_sh, opt_sh)
        donate = (0, 1)
        if use_residual:
            in_sh += (shard_sh,)
            out_sh += (shard_sh,)
            donate = (0, 1, 2)
        if health is not None:
            out_sh += (shard_sh,)  # [nrep, 4P] per-replica rows ride LAST
        return jax.jit(
            step,
            in_shardings=in_sh + (scalar, scalar, scalar) + batch_shardings,
            out_shardings=out_sh,
            donate_argnums=donate if self._donate else (),
        )

    def _build_zero_accum(self, batch_avals, k, dtype, use_residual, chunk):
        """Jit the ZeRO weight-update-sharded accumulation step: same scan
        as _build_accum, but the post-scan reduction is reduce-scatter ->
        shard-local clip+update -> all-gather of updated weights, and the
        optimizer state enters/leaves as flat [n_pad] f32 slot buffers
        sharded 1/N over the data axes."""
        compute = self._build_compute_loss()
        health = self._health
        param_templates = {
            n: jax.ShapeDtypeStruct(tuple(self._state_refs[n].shape),
                                    self.params[n].dtype)
            for n in self._param_names}
        step = _gc.make_zero_accum_step(
            compute_loss=compute, flat_update=self._make_flat_update(),
            clip=self.optimizer._grad_clip, mesh=self.mesh,
            batch_axes=self._batch_axes(), k=k, dtype=dtype, chunk=chunk,
            use_residual=use_residual, param_templates=param_templates,
            health_partial=(health.make_sharded_stats()
                            if health is not None else None))
        batch_shardings = self._shardings_for(batch_avals)
        param_shardings = {n: NamedSharding(self.mesh, s)
                           for n, s in self.param_specs.items()}
        shard_sh = self._residual_sharding()   # 1-D [n_pad] split over d0
        opt_shardings = tuple(shard_sh for _ in range(self._zero_n_slots()))
        scalar = NamedSharding(self.mesh, P())
        in_sh = (param_shardings, opt_shardings)
        out_sh = (scalar, param_shardings, opt_shardings)
        donate = (0, 1)
        if use_residual:
            res_sh = self._residual_sharding()
            in_sh += (res_sh,)
            out_sh += (res_sh,)
            donate = (0, 1, 2)
        if health is not None:
            out_sh += (scalar,)  # packed health buffer rides LAST
        return jax.jit(
            step,
            in_shardings=in_sh + (scalar, scalar, scalar) + batch_shardings,
            out_shardings=out_sh,
            donate_argnums=donate if self._donate else (),
        )

    def _n_grad_elems(self) -> int:
        return int(sum(int(np.prod(self._state_refs[n].shape) or 1)
                       for n in self._param_names))

    def _residual_sharding(self):
        axes = self._batch_axes()
        spec = P(axes if len(axes) > 1 else (axes[0] if axes else None))
        return NamedSharding(self.mesh, spec)

    def _ensure_residual(self):
        if self._grad_residual is None:
            nrep = _gc.replica_count(self.mesh, self._batch_axes())
            self._grad_residual = jax.device_put(
                np.zeros((nrep, self._n_grad_elems()), np.float32),
                self._residual_sharding())
        return self._grad_residual

    def _build_accum(self, batch_avals, k, dtype, use_residual, chunk):
        """Jit the K-microbatch accumulation step. The dp-pure fast path
        runs the scan + ONE deferred collective under shard_map
        (grad_comm.make_accum_step); hybrid meshes take the GSPMD
        accumulation scan fallback."""
        compute = self._build_compute_loss()
        update = opt_funct.make_tree_update(
            self.optimizer, {n: self._state_refs[n]
                             for n in self._param_names})
        clip = self.optimizer._grad_clip
        zero_specs = (self.opt_specs
                      if self.hcg.degrees["sharding"] > 1 else None)
        batch_shardings = self._shardings_for(batch_avals)
        health = self._health
        health_stats = (health.make_packed_stats()
                        if health is not None else None)
        if self._dp_pure():
            step = _gc.make_accum_step(
                compute_loss=compute, update=update, clip=clip,
                mesh=self.mesh, batch_axes=self._batch_axes(), k=k,
                dtype=dtype, chunk=chunk, use_residual=use_residual,
                param_specs=self.param_specs, zero_specs=zero_specs,
                health_stats=health_stats)
        else:
            step = _gc.make_accum_step_gspmd(
                compute_loss=compute, update=update, clip=clip,
                mesh=self.mesh, k=k,
                batch_specs=[s.spec for s in batch_shardings],
                param_specs=self.param_specs, zero_specs=zero_specs,
                health_stats=health_stats)
        param_shardings = {n: NamedSharding(self.mesh, s)
                           for n, s in self.param_specs.items()}
        opt_shardings = {
            n: tuple(NamedSharding(self.mesh, self.opt_specs[n])
                     for _ in self.opt_state[n])
            for n in self._param_names}
        scalar = NamedSharding(self.mesh, P())
        in_sh = (param_shardings, opt_shardings)
        out_sh = (scalar, param_shardings, opt_shardings)
        donate = (0, 1)
        if use_residual:
            res_sh = self._residual_sharding()
            in_sh += (res_sh,)
            out_sh += (res_sh,)
            donate = (0, 1, 2)  # the residual is carried state: donate it
        if health is not None:
            out_sh += (scalar,)  # packed health buffer rides LAST
        return jax.jit(
            step,
            in_shardings=in_sh + (scalar, scalar, scalar) + batch_shardings,
            out_shardings=out_sh,
            donate_argnums=donate if self._donate else (),
        )

    def _accum_step(self, arrays) -> Tensor:
        """One optimizer step over K in-program microbatches: the grad_comm
        twin of step() (same plumbing contract: telemetry, compile
        accounting, donation-safe rebind of params/opt state)."""
        k, dtype, use_residual, chunk, zero = self._grad_comm_config()
        self._check_batch(arrays)
        nrep = _gc.replica_count(self.mesh, self._batch_axes())
        for a in arrays:
            if a.ndim and a.shape[0] % (nrep * k) != 0:
                raise ValueError(
                    f"batch dim {a.shape[0]} is not divisible by "
                    f"microbatches*replicas = {k}*{nrep}; pad or resize "
                    f"the batch (topology: {self.hcg.topology()})")
        from ..core import autotune
        autotune.set_step(self._step_count + 1)
        health_on = self._health is not None
        fsdp = self._fsdp_on()
        # fsdp appends rather than widening the tuple so non-fsdp keys stay
        # identical to the PR 18 registry layout (pinned by test_zero_update);
        # the resolved prefetch depth rides the same append so flipping
        # FLAGS_fsdp_prefetch rebuilds the windowed step fn
        fsdp_pf = self._fsdp_prefetch() if fsdp else 0
        cache_key = (k, dtype, use_residual, chunk, health_on, zero) + \
            ((True, fsdp_pf) if fsdp else ())
        label = (f"train.fsdp_k{k}_{dtype}" if fsdp
                 else f"train.zero_k{k}_{dtype}" if zero
                 else f"train.accum_k{k}_{dtype}") + \
            ("_res" if use_residual else "")
        build = (self._build_fsdp_accum if fsdp
                 else self._build_zero_accum if zero
                 else self._build_accum)
        entry = self._execs.get_or_build(
            ("train.accum",) + cache_key,
            lambda: build(arrays, k, dtype, use_residual, chunk),
            label=label, pin=True)
        fn = entry.fn
        staged, self._pending_h2d = self._pending_h2d, None
        arrays, h2d_ms = self._place_batch(
            arrays, self._batch_shardings,
            timed=self.telemetry is not None and staged is None)
        prefetch_depth = None
        if staged is not None:
            h2d_ms, prefetch_depth = staged
        self._step_count += 1
        self.optimizer._step_count = self._step_count
        lr_val = self.optimizer.get_lr()
        if self._lr_cache[0] != lr_val:
            self._lr_cache = (lr_val, jnp.float32(lr_val))
        lr = self._lr_cache[1]
        self._key, sub = jax.random.split(self._key)
        tele = self.telemetry
        fr = _obs_flight.get()
        mreg = _obs_metrics.active_registry()
        n0 = _jit_cache_size(fn)
        p0 = _compile_cache.entries() if n0 == 0 else -1
        t0 = time.perf_counter()
        try:
            if fsdp:
                p_in, opt_in = self._ensure_fsdp_state()
            else:
                p_in = self.params
                opt_in = (self._ensure_zero_opt() if zero
                          else self._opt_to_hbm(self.opt_state))
            if use_residual:
                call_args = (p_in, opt_in,
                             self._ensure_residual(), lr,
                             jnp.int32(self._step_count), sub) + tuple(arrays)
                self._stash_exec(label, fn, call_args)
                outs = fn(*call_args)
                loss, new_p, new_opt, self._grad_residual = outs[:4]
            else:
                call_args = (p_in, opt_in,
                             lr, jnp.int32(self._step_count),
                             sub) + tuple(arrays)
                self._stash_exec(label, fn, call_args)
                outs = fn(*call_args)
                loss, new_p, new_opt = outs[:3]
            if fsdp:
                self._fsdp_params = tuple(new_p)
            else:
                self.params = new_p
            hbuf = outs[-1] if health_on else None
            if tele is not None or fr is not None or mreg is not None:
                jax.block_until_ready(loss)
        except Exception as e:
            if fr is not None:
                fr.dump("train_step_exception",
                        {"step": self._step_count, "error": repr(e)})
            raise
        t1 = time.perf_counter()
        compiled = self._execs.note_compiles(
            entry, n_before=n0, n_after=_jit_cache_size(fn), wall_s=t1 - t0,
            persistent_before=p0, engine_counters=True) > 0
        if fsdp:
            # L per-bucket weight gathers + one grad reduce-scatter; the
            # health partials ride a sharded output (no collective bytes)
            rs_b, ag_b = ((0, 0) if nrep <= 1 else _gc.fsdp_payload_bytes(
                [b["shard"] for b in self._fsdp_layout()], nrep, dtype,
                chunk)[:2])
            comm_bytes = rs_b + ag_b
            _gc.RS_BYTES.increase(rs_b)
            _gc.AG_BYTES.increase(ag_b)
        elif zero:
            rs_b, ag_b = ((0, 0) if nrep <= 1 else _gc.zero_payload_bytes(
                self._n_grad_elems(), nrep, dtype, chunk,
                4 * len(self._param_names) if health_on else 0))
            comm_bytes = rs_b + ag_b
            _gc.RS_BYTES.increase(rs_b)
            _gc.AG_BYTES.increase(ag_b)
        else:
            comm_bytes = (_gc.payload_bytes(self._n_grad_elems(), dtype,
                                            chunk) if nrep > 1 else 0)
        _gc.STEPS.increase()
        _gc.MICROBATCHES.increase(k)
        _gc.BYTES_MOVED.increase(comm_bytes)
        if dtype != "f32":
            _gc.LOWP_STEPS.increase()
        tr = _obs_tracer.get_tracer()
        if tr.enabled:
            tr.record_complete("engine.accum_step", t0, t1,
                               {"step": self._step_count, "compiled": compiled,
                                "microbatches": k, "grad_comm_dtype": dtype,
                                "zero_update": zero, "fsdp": fsdp})
        if fsdp:
            self._fsdp_opt = tuple(tuple(col) for col in new_opt)
        elif zero:
            self._zero_opt = tuple(new_opt)
        else:
            self.opt_state = self._opt_to_home(new_opt)
        if hbuf is not None:
            if fsdp:
                # per-replica [nrep, 4P] segment partials: the cross-shard
                # sum happens HERE (host-side) instead of as an in-program
                # all-reduce, and only on fetch steps — off-interval steps
                # skip the D2H entirely
                hbuf = (np.asarray(hbuf).sum(axis=0, dtype=np.float32)
                        if self._health.wants(self._step_count) else None)
            self._health.on_step(self._step_count, hbuf)
        self.last_loss = Tensor(loss)
        rec = None
        if tele is not None:
            samples, tokens = self._batch_stats(arrays)
            rec = tele.record_step(
                step=self._step_count, wall_time=t1 - t0, samples=samples,
                tokens=tokens, loss=float(jax.device_get(loss)),
                h2d_ms=h2d_ms, prefetch_depth=prefetch_depth,
                microbatches=k, grad_comm_dtype=dtype,
                grad_comm_bytes=comm_bytes,
                extra=({"fsdp": True, "fsdp_prefetch": fsdp_pf,
                        "fsdp_window_bytes": _gc.fsdp_window_bytes(
                            self._fsdp_layout(), fsdp_pf)} if fsdp
                       else {"zero_update": True} if zero else None))
        if fr is not None or mreg is not None:
            self._obs_step_tail(fr, mreg, rec, t0, t1, h2d_ms, compiled, loss)
        if self._ckpt is not None:
            self._ckpt.on_step(self, self._step_count, loss)
        return self.last_loss

    # ---- shared step plumbing ----
    def _shardings_for(self, arrays):
        """Per-position batch shardings (input_specs, or the default
        dp/sharding/sp layout from the first batch's shapes). Cached — the
        same tuple serves _build, step() placement, and the prefetcher."""
        if self._batch_shardings is None:
            if self.input_specs is not None:
                self._batch_shardings = tuple(
                    NamedSharding(self.mesh, s) for s in self.input_specs)
            else:
                self._batch_shardings = tuple(
                    NamedSharding(self.mesh,
                                  _default_input_spec(a.shape, self.hcg))
                    for a in arrays)
        return self._batch_shardings

    def _place_batch(self, arrays, shardings, timed=False):
        """Sharded host->device placement that SKIPS arrays already placed
        with a matching sharding (a prefetched batch pays no second
        device_put). Returns (arrays, h2d issue ms | None)."""
        t0 = time.perf_counter() if timed else None
        arrays = [a if _pf.is_placed(a, s) else jax.device_put(a, s)
                  for a, s in zip(arrays, shardings)]
        if timed:
            return arrays, (time.perf_counter() - t0) * 1000.0
        return arrays, None

    def _check_batch(self, arrays, lead_axes=0):
        """The dp*sharding divisibility guard, shared by step()/run_steps()."""
        batch_axes = self.hcg.degrees["dp"] * self.hcg.degrees["sharding"]
        for a in arrays:
            if a.ndim > lead_axes and a.shape[lead_axes] % batch_axes != 0:
                raise ValueError(
                    f"batch dim {a.shape[lead_axes]} is not divisible by "
                    f"dp*sharding = {batch_axes}; pad or resize the batch "
                    f"(topology: {self.hcg.topology()})")

    @staticmethod
    def _to_arrays(batch):
        return [b._data if isinstance(b, Tensor) else jnp.asarray(b)
                for b in batch]

    def _opt_to_hbm(self, opt_state):
        """Offload mode: stream host-resident optimizer state to HBM for the
        update (async device_put pipelines with dispatch). No-op otherwise."""
        if not self._opt_memory_kind:
            return opt_state
        return {
            n: tuple(jax.device_put(s, NamedSharding(self.mesh,
                                                     self.opt_specs[n]))
                     for s in st) for n, st in opt_state.items()}

    def _opt_to_home(self, opt_state):
        """Offload mode: move the fresh optimizer state back to host memory."""
        if not self._opt_memory_kind:
            return opt_state
        return {
            n: tuple(jax.device_put(s, self._opt_sharding(self.opt_specs[n]))
                     for s in st) for n, st in opt_state.items()}

    # ---- public API ----
    def run_steps(self, *batch, steps: Optional[int] = None):
        """Run K fused train steps in one dispatch; returns losses [K].

        Either pass batch arrays with a leading [K] step axis, or single-step
        arrays plus steps=K to reuse the same batch every step (benchmark /
        overfit loops; the batch is uploaded ONCE, not K times). Loss history
        comes back as one f32 array.

        Orthogonal to `microbatches`: run_steps fuses K OPTIMIZER STEPS into
        one dispatch (each over its full batch); the grad_comm accumulation
        path fuses K microbatches into ONE optimizer step. run_steps always
        runs the plain per-step program regardless of engine.microbatches.

        Health telemetry (enable_health) does NOT ride this path: the scan
        yields only losses, so per-step health stats would multiply the
        program's outputs by K. Use step()/_accum_step for monitored runs.

        zero_update does NOT compose either — the scan carries the
        replicated opt-state dict while the ZeRO path owns flat 1/N
        shards; silently running the replicated update here would diverge
        from step() semantics, so an active zero_update raises instead
        (pinned by tests/test_zero_update.py).
        """
        arrays = self._to_arrays(batch)
        if self._fsdp_on():
            raise ValueError(
                "run_steps (the fused K-step scan lane) does not compose "
                "with fsdp: the scan carries the replicated params/opt-"
                "state dicts while the fsdp path owns per-layer flat 1/N "
                "shards per data replica. Use step() (one dispatch per "
                "optimizer step, L bucket all-gathers + one reduce-"
                "scatter) or disable fsdp for this engine.")
        if self._zero_on():
            raise ValueError(
                "run_steps (the fused K-step scan lane) does not compose "
                "with zero_update: the scan carries the replicated "
                "opt-state dict while the ZeRO path owns flat 1/N shards "
                "per data replica. Use step() (one dispatch per optimizer "
                "step, one reduce-scatter + one all-gather) or disable "
                "zero_update for this engine.")
        fixed = steps is not None
        self._check_batch(arrays, lead_axes=0 if fixed else 1)
        k = steps if fixed else arrays[0].shape[0]
        if k < 1:
            raise ValueError(f"run_steps needs at least one step, got K={k}")
        from ..core import autotune
        autotune.set_step(self._step_count + k)
        scan_entry = self._execs.get_or_build(
            ("train.run_steps", fixed),
            lambda: self._build_scan(arrays, fixed),
            label="train.run_steps", pin=True)
        arrays, h2d_ms = self._place_batch(
            arrays, self._scan_batch_shardings[fixed],
            timed=self.telemetry is not None)
        # host-side schedule bookkeeping, mirroring step(): one lr per step
        step0 = self._step_count + 1
        lrs = []
        for _ in range(k):
            self._step_count += 1
            self.optimizer._step_count = self._step_count
            lrs.append(self.optimizer.get_lr())
        lrs = jnp.asarray(lrs, jnp.float32)
        # one subkey per step, advancing self._key exactly as K step() calls
        subs = []
        for _ in range(k):
            self._key, sub = jax.random.split(self._key)
            subs.append(sub)
        fn = scan_entry.fn
        tele = self.telemetry
        fr = _obs_flight.get()
        mreg = _obs_metrics.active_registry()
        n0 = _jit_cache_size(fn)
        p0 = _compile_cache.entries() if n0 == 0 else -1
        t0 = time.perf_counter()
        try:
            call_args = (self.params, self._opt_to_hbm(self.opt_state), lrs,
                         jnp.int32(step0), jnp.stack(subs)) + tuple(arrays)
            self._stash_exec("train.run_steps", fn, call_args)
            losses, self.params, new_opt = fn(*call_args)
            if tele is not None or fr is not None or mreg is not None:
                jax.block_until_ready(losses)  # honest wall: drain the K steps
        except Exception as e:
            if fr is not None:
                fr.dump("run_steps_exception",
                        {"step0": step0, "steps": k, "error": repr(e)})
            raise
        t1 = time.perf_counter()
        compiled = self._execs.note_compiles(
            scan_entry, n_before=n0, n_after=_jit_cache_size(fn),
            wall_s=t1 - t0, persistent_before=p0, engine_counters=True) > 0
        tr = _obs_tracer.get_tracer()
        if tr.enabled:
            tr.record_complete("engine.run_steps", t0, t1,
                               {"steps": k, "step0": step0,
                                "compiled": compiled})
        self.opt_state = self._opt_to_home(new_opt)
        self.last_loss = Tensor(losses[-1])
        rec = None
        if tele is not None:
            samples, tokens = self._batch_stats(
                arrays, lead_axes=0 if fixed else 1)
            rec = tele.record_step(
                step=self._step_count, wall_time=t1 - t0,
                samples=samples * k if samples else None,
                tokens=tokens * k if tokens else None,
                loss=float(jax.device_get(losses[-1])),
                h2d_ms=h2d_ms,
                extra={"steps_fused": k})
        if fr is not None or mreg is not None:
            self._obs_step_tail(fr, mreg, rec, t0, t1, h2d_ms, compiled,
                                losses[-1], hist="train.run_steps_ms")
        if self._ckpt is not None:
            # K fused steps = one hook call; window makes an interval that
            # fell INSIDE the scan still checkpoint at the scan boundary
            self._ckpt.on_step(self, self._step_count, losses[-1], window=k)
        return Tensor(losses)

    def warm_scan(self, *batch, steps: int):
        """Compile + device-warm the K-step scan program WITHOUT advancing
        training state: run_steps executes on copies (its donation consumes
        the originals; the copies made here survive and are restored). Use
        before timing a run_steps region so compile cost stays outside it."""
        saved = (jax.tree_util.tree_map(jnp.copy, self.params),
                 jax.tree_util.tree_map(jnp.copy, self.opt_state),
                 self._step_count, self._key, self.last_loss)
        tele, self.telemetry = self.telemetry, None  # warm run is not a step:
        #                         a compile-heavy record would poison the stream
        try:
            losses = self.run_steps(*batch, steps=steps)
            float(losses[-1].item())  # drain: the warm execution must not
            #                           queue into a caller's timed region
        finally:
            (self.params, self.opt_state, self._step_count, self._key,
             self.last_loss) = saved
            self.optimizer._step_count = self._step_count
            self.telemetry = tele

    def step(self, *batch) -> Tensor:
        arrays = self._to_arrays(batch)
        if (self.microbatches > 1 or _gc.comm_dtype() != "f32"
                or self._zero_on() or self._fsdp_on()):
            # grad_comm path: K in-program microbatches + one deferred fused
            # gradient all-reduce (and/or low-precision collectives, and/or
            # the ZeRO weight-update sharding). The default (K=1, f32, no
            # zero_update) stays below on the original step program —
            # bit-identical to pre-grad_comm behavior.
            return self._accum_step(arrays)
        self._check_batch(arrays)
        from ..core import autotune
        autotune.set_step(self._step_count + 1)
        step_entry = self._execs.get_or_build(
            ("train.step",), lambda: self._build(arrays),
            label="train.step", pin=True)
        # place batch according to specs (host->device with the right
        # sharding); arrays staged by prefetch() arrive already placed and
        # skip the put — their H2D stats were captured at issue time
        staged, self._pending_h2d = self._pending_h2d, None
        arrays, h2d_ms = self._place_batch(
            arrays, self._batch_shardings,
            timed=self.telemetry is not None and staged is None)
        if staged is not None:
            h2d_ms, prefetch_depth = staged
        else:
            prefetch_depth = None
        self._step_count += 1
        self.optimizer._step_count = self._step_count  # keep ckpt/resume consistent
        lr_val = self.optimizer.get_lr()
        if self._lr_cache[0] != lr_val:  # constant-lr steps reuse the device scalar
            self._lr_cache = (lr_val, jnp.float32(lr_val))
        lr = self._lr_cache[1]
        self._key, sub = jax.random.split(self._key)
        fn = step_entry.fn
        tele = self.telemetry
        fr = _obs_flight.get()
        mreg = _obs_metrics.active_registry()
        n0 = _jit_cache_size(fn)
        # persistent-store snapshot only around a first compile: one readdir,
        # and only when the fn has no executable yet (recompiles from shape
        # churn stay unclassified rather than taxing every steady-state step)
        p0 = _compile_cache.entries() if n0 == 0 else -1
        health_on = self._health is not None
        t0 = time.perf_counter()
        try:
            call_args = (self.params, self._opt_to_hbm(self.opt_state), lr,
                         jnp.int32(self._step_count), sub) + tuple(arrays)
            self._stash_exec("train.step", fn, call_args)
            outs = fn(*call_args)
            loss, self.params, new_opt = outs[:3]
            hbuf = outs[-1] if health_on else None
            if tele is not None or fr is not None or mreg is not None:
                jax.block_until_ready(loss)  # honest wall over async dispatch
        except Exception as e:
            if fr is not None:
                fr.dump("train_step_exception",
                        {"step": self._step_count, "error": repr(e)})
            raise
        t1 = time.perf_counter()
        compiled = self._execs.note_compiles(
            step_entry, n_before=n0, n_after=_jit_cache_size(fn),
            wall_s=t1 - t0, persistent_before=p0, engine_counters=True) > 0
        tr = _obs_tracer.get_tracer()
        if tr.enabled:
            tr.record_complete("engine.step", t0, t1,
                               {"step": self._step_count,
                                "compiled": compiled})
        self.opt_state = self._opt_to_home(new_opt)
        if hbuf is not None:
            self._health.on_step(self._step_count, hbuf)
        self.last_loss = Tensor(loss)
        rec = None
        if tele is not None:
            samples, tokens = self._batch_stats(arrays)
            rec = tele.record_step(
                step=self._step_count, wall_time=t1 - t0, samples=samples,
                tokens=tokens, loss=float(jax.device_get(loss)),
                h2d_ms=h2d_ms, prefetch_depth=prefetch_depth)
        if fr is not None or mreg is not None:
            self._obs_step_tail(fr, mreg, rec, t0, t1, h2d_ms, compiled, loss)
        if self._ckpt is not None:
            self._ckpt.on_step(self, self._step_count, loss)
        return self.last_loss

    train_batch = step

    def prefetch(self, loader, depth: int = 2):
        """Iterate `loader` as device-placed batches: the sharded H2D for the
        next `depth` batches is issued while the current step's program is
        still executing (JAX async dispatch), so transfer overlaps compute.

            for batch in engine.prefetch(loader):
                engine.step(*batch)

        step() skips its own device_put for the pre-placed arrays (one
        transfer per batch total) and records the prefetcher's per-batch
        h2d_ms / prefetch_depth in StepTelemetry. The loader may yield
        Tensors or raw arrays; batch layout must match step(*batch)."""
        pf = _pf.DevicePrefetcher(self._shardings_for, depth=depth)
        self.prefetcher = pf

        def arrays_iter():
            for batch in loader:
                if not isinstance(batch, (tuple, list)):
                    batch = (batch,)
                arrays = self._to_arrays(batch)
                self._check_batch(arrays)
                yield arrays

        def placed_iter():
            for placed in pf.iterate(arrays_iter()):
                self._pending_h2d = (pf.last_h2d_ms, pf.last_depth)
                yield placed

        return placed_iter()

    def sync_to_model(self):
        """Write engine-owned (possibly sharded) params back into the eager Layer."""
        params = (self.params if self.params is not None
                  else self._gather_fsdp_params())
        for n in self._param_names:
            # np.asarray gathers a sharded global array to host, then re-uploads dense
            self._state_refs[n]._data = jnp.asarray(np.asarray(params[n]))
        return self.model

    def state_dict(self):
        params = (self.params if self.params is not None
                  else self._gather_fsdp_params())
        out = {}
        for n in self._param_names:
            out[n] = Tensor(jnp.asarray(np.asarray(params[n])))
        for n in self._buffer_names:
            out[n] = Tensor(self.buffers[n])
        return out


def parallelize(model, optimizer, loss_fn=None, hcg=None, strategy=None, **kw):
    """Sugar: fleet-style entry returning a ready TrainStepEngine."""
    return TrainStepEngine(model, optimizer, loss_fn=loss_fn, hcg=hcg,
                           strategy=strategy, **kw)

"""auto_parallel.Engine: fit/evaluate/predict over an annotated model.

Reference: python/paddle/distributed/auto_parallel/engine.py:50 — prepare()
runs completion (dist-attr propagation), partition, reshard, then fit() drives
the distributed program. TPU-native: prepare() collects the user's shard_tensor
seeds into pjit in_shardings over the ProcessMesh; GSPMD performs completion/
partition/reshard inside XLA. One jitted step = forward+backward+update.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core import random as random_mod
from ...core.tensor import Tensor
from ...jit import functional_call_with_state
from ...optimizer import functional as opt_funct
from .process_mesh import ProcessMesh
from .strategy import Strategy


class Engine:
    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 strategy: Optional[Strategy] = None,
                 process_mesh: Optional[ProcessMesh] = None,
                 num_model_inputs: Optional[int] = None):
        self.model = model
        self.loss = loss
        self.num_model_inputs = num_model_inputs
        self.optimizer = optimizer
        self.metrics = metrics if isinstance(metrics, (list, tuple)) else \
            ([metrics] if metrics else [])
        self.strategy = strategy or Strategy()
        self._process_mesh = process_mesh
        self._prepared = False
        self._step_fn = None
        self._eval_fn = None
        self.history: List[float] = []

    # ---- completion seeds -> pjit shardings ----
    def _resolve_mesh(self) -> Mesh:
        pm = self._process_mesh
        if pm is None:
            # look for a mesh on any annotated parameter
            for p in self.model.parameters():
                if getattr(p, "process_mesh", None) is not None:
                    pm = p.process_mesh
                    break
        if pm is None:  # default: 1-D data-parallel mesh over all devices
            pm = ProcessMesh(list(range(jax.device_count())), ["dp"])
        self._process_mesh = pm
        return pm.to_jax_mesh()

    def prepare(self):
        assert self.model is not None and self.optimizer is not None
        self.mesh = self._resolve_mesh()
        state = self.model.state_dict(include_non_persistable_buffer=True)
        self._param_names = [n for n, t in state.items() if not t.stop_gradient]
        self._buffer_names = [n for n, t in state.items() if t.stop_gradient]
        self._state_refs = state

        self.param_specs: Dict[str, P] = {}
        self.params = {}
        for n in self._param_names:
            p = state[n]
            spec = getattr(p, "dist_attr", None) or P()
            self.param_specs[n] = spec
            self.params[n] = jax.device_put(p._data,
                                            NamedSharding(self.mesh, spec))
        self.buffers = {
            n: jax.device_put(state[n]._data, NamedSharding(self.mesh, P()))
            for n in self._buffer_names}

        rule = self.optimizer._rule
        self.opt_state = {
            n: tuple(jax.device_put(s, NamedSharding(self.mesh,
                                                     self.param_specs[n]))
                     for s in opt_funct.init_state(rule, self.params[n]))
            for n in self._param_names}
        self._key = jax.random.key(
            random_mod.default_generator().initial_seed() or 0)
        self._step_count = 0
        self._prepared = True
        return self

    def _data_spec(self, ndim: int) -> P:
        # completion default for inputs: batch dim split over the first mesh dim
        return P(self._process_mesh.dim_names[0]) if ndim >= 1 else P()

    def _build(self, train: bool):
        clip = self.optimizer._grad_clip
        model, loss_fn = self.model, self.loss
        buffer_names = self._buffer_names
        update = opt_funct.make_tree_update(
            self.optimizer, {n: self._state_refs[n] for n in self._param_names})

        def forward(params, buffers, key, *batch):
            state = dict(params)
            state.update(buffers)
            with random_mod.trace_key_scope(key):
                inputs = [Tensor(b, stop_gradient=True) for b in batch]
                from ..engine import model_input_count

                n_in = (model_input_count(len(inputs), self.num_model_inputs)
                        if loss_fn is not None else len(inputs))
                out, new_state = functional_call_with_state(
                    model, state, *inputs[:n_in])
                if loss_fn is not None:
                    outs = out if isinstance(out, (tuple, list)) else (out,)
                    out = loss_fn(*outs, *inputs[n_in:])
            loss = out[0] if isinstance(out, (tuple, list)) else out
            loss = loss._data if isinstance(loss, Tensor) else loss
            new_buffers = {bn: new_state[bn] for bn in buffer_names}
            return loss, new_buffers

        if not train:
            def eval_step(params, buffers, key, *batch):
                return forward(params, buffers, key, *batch)[0]
            return eval_step

        def step(params, buffers, opt_state, lr, step_i, key, *batch):
            (loss, new_buffers), grads = jax.value_and_grad(
                lambda ps: forward(ps, buffers, key, *batch), has_aux=True)(params)
            grads = opt_funct.clip_grads(grads, clip)
            new_params, new_opt = update(params, grads, opt_state, lr, step_i)
            return loss, new_params, new_buffers, new_opt

        param_sh = {n: NamedSharding(self.mesh, s)
                    for n, s in self.param_specs.items()}
        opt_sh = {n: tuple(NamedSharding(self.mesh, self.param_specs[n])
                           for _ in self.opt_state[n])
                  for n in self._param_names}
        scalar = NamedSharding(self.mesh, P())
        buf_sh = {n: NamedSharding(self.mesh, P()) for n in buffer_names}
        # inputs are committed arrays (device_put with their shardings above and
        # in _run_step), so jit infers in_shardings; out_shardings pin results
        return jax.jit(step,
                       out_shardings=(scalar, param_sh, buf_sh, opt_sh),
                       donate_argnums=(0, 1, 2))

    # ---- mesh-shape planning (reference planner.py dist-attr search) ----
    def plan_mesh(self, sample_batch, dim_names=None, verbose: bool = False):
        """Pick the mesh SHAPE by AOT cost: every factorization of the device
        count over the annotation dim names is compiled (never executed) and
        ranked on the planner's bandwidth-weighted proxy (planner.py —
        reference auto_parallel planner.py + cost_model.py)."""
        from .planner import factorizations, score_compiled

        n = jax.device_count()
        names = list(dim_names or (self._process_mesh.dim_names
                                   if self._process_mesh else ["dp", "mp"]))
        sample = [b._data if isinstance(b, Tensor) else jnp.asarray(b)
                  for b in (sample_batch if isinstance(sample_batch,
                                                       (list, tuple))
                            else [sample_batch])]
        best, best_score, table = None, float("inf"), []
        for shape in factorizations(n, len(names)):
            pm = ProcessMesh(np.arange(n).reshape(shape).tolist(), names)
            if sample and sample[0].ndim >= 1 and \
                    sample[0].shape[0] % shape[0] != 0:
                continue  # batch not divisible over the data axis
            try:
                self._process_mesh = pm
                self._prepared = False
                self.prepare()
                step = self._build(train=True)
                arrays = [jax.device_put(
                    a, NamedSharding(self.mesh, self._data_spec(a.ndim)))
                    for a in sample]
                comp = step.lower(self.params, self.buffers, self.opt_state,
                                  jnp.float32(1e-3), jnp.int32(1),
                                  jax.random.key(0), *arrays).compile()
                m = score_compiled(comp)
            except Exception as e:
                table.append({"shape": shape, "error": f"{type(e).__name__}"})
                continue
            table.append({"shape": shape, **{k: m[k] for k in
                                             ("score", "hbm_bytes",
                                              "ici_bytes", "peak_bytes")}})
            if verbose:
                print(f"  mesh {dict(zip(names, shape))}: "
                      f"score={m['score']:.3e} peak={m['peak_bytes']}")
            if m["score"] < best_score:
                best, best_score = pm, m["score"]
        if best is None:
            raise RuntimeError(f"plan_mesh: no feasible mesh shape: {table}")
        self._process_mesh = best
        self._prepared = False
        self._step_fn = None
        self.prepare()
        self.plan_table = table
        return best

    # ---- public API (reference engine.py fit/evaluate/predict) ----
    def fit(self, train_data, epochs: int = 1, batch_size: int = 1,
            steps_per_epoch: Optional[int] = None, verbose: int = 0,
            auto: bool = False):
        if auto and not self._prepared:
            from ...io import DataLoader, Dataset

            probe = train_data
            if isinstance(train_data, Dataset):
                probe = DataLoader(train_data, batch_size=batch_size,
                                   drop_last=len(train_data) >= batch_size)
            elif iter(probe) is probe:
                raise ValueError(
                    "fit(auto=True) needs a re-iterable data source to "
                    "probe one batch for planning — pass a Dataset (or "
                    "call plan_mesh(sample_batch) yourself) instead of a "
                    "one-shot generator")
            first = next(iter(probe))
            first = first if isinstance(first, (list, tuple)) else [first]
            self.plan_mesh(list(first), verbose=bool(verbose))
        if not self._prepared:
            self.prepare()
        from ...io import DataLoader, Dataset

        if isinstance(train_data, Dataset):
            # drop the indivisible tail batch, but never drop EVERYTHING: a
            # dataset smaller than batch_size trains on its single batch
            drop_last = len(train_data) >= batch_size
            loader = DataLoader(train_data, batch_size=batch_size, shuffle=True,
                                drop_last=drop_last)
        else:
            loader = train_data
        if self._step_fn is None:
            self._step_fn = self._build(train=True)
        history = []
        for epoch in range(epochs):
            losses = []
            for i, batch in enumerate(loader):
                if steps_per_epoch is not None and i >= steps_per_epoch:
                    break
                losses.append(self._run_step(batch))
            if not losses:
                raise ValueError(
                    "Engine.fit: the data loader yielded no batches "
                    f"(dataset smaller than batch_size={batch_size}?)")
            avg = float(np.mean(losses))
            history.append(avg)
            if verbose:
                print(f"epoch {epoch + 1}/{epochs} loss={avg:.4f}", flush=True)
        self.history = history
        self._write_back()
        return history

    def _run_step(self, batch) -> float:
        batch = batch if isinstance(batch, (list, tuple)) else [batch]
        dp_axis = self._process_mesh.dim_names[0]
        dp_size = self._process_mesh.get_dim_size(dp_axis)
        arrays = []
        for b in batch:
            a = b._data if isinstance(b, Tensor) else np.asarray(b)
            if getattr(a, "ndim", 0) >= 1 and a.shape[0] % dp_size != 0:
                raise ValueError(
                    f"batch dim {a.shape[0]} not divisible by the '{dp_axis}' "
                    f"mesh dim ({dp_size}); use a divisible batch_size and "
                    f"drop_last=True (partial last batch)")
            arrays.append(jax.device_put(
                a, NamedSharding(self.mesh,
                                 self._data_spec(getattr(a, "ndim", 0)))))
        self._step_count += 1
        self._key, sub = jax.random.split(self._key)
        lr = self.optimizer.get_lr()
        loss, self.params, self.buffers, self.opt_state = self._step_fn(
            self.params, self.buffers, self.opt_state, lr, self._step_count, sub,
            *arrays)
        self.optimizer._lr_step()
        return float(loss)

    def evaluate(self, eval_data, batch_size: int = 1):
        if not self._prepared:
            self.prepare()
        was_training = self.model.training
        self.model.eval()  # before tracing: eval-mode dropout/BN bake into the jit
        if self._eval_fn is None:
            self._eval_fn = jax.jit(self._build(train=False))
        from ...io import DataLoader, Dataset

        loader = eval_data if not isinstance(eval_data, Dataset) else \
            DataLoader(eval_data, batch_size=batch_size)
        losses = []
        for batch in loader:
            batch = batch if isinstance(batch, (list, tuple)) else [batch]
            arrays = [b._data if isinstance(b, Tensor) else np.asarray(b)
                      for b in batch]
            self._key, sub = jax.random.split(self._key)
            losses.append(float(self._eval_fn(self.params, self.buffers, sub,
                                              *arrays)))
        if was_training:
            self.model.train()
        return {"loss": float(np.mean(losses))}

    def predict(self, data, batch_size: int = 1):
        if not self._prepared:
            self.prepare()
        outs = []
        from ...io import DataLoader, Dataset

        loader = data if not isinstance(data, Dataset) else \
            DataLoader(data, batch_size=batch_size)
        model = self.model
        self._write_back()
        was_training = model.training
        model.eval()
        from ...core.autograd import no_grad

        for batch in loader:
            batch = batch if isinstance(batch, (list, tuple)) else [batch]
            n_in = max(1, len(batch) - 1) if self.loss is not None else len(batch)
            with no_grad():
                out = model(*[b if isinstance(b, Tensor) else
                              Tensor(np.asarray(b)) for b in batch[:n_in]])
            o = out[0] if isinstance(out, (tuple, list)) else out
            outs.append(o.numpy())
        if was_training:
            model.train()
        return outs

    def _write_back(self):
        """Sync trained arrays back into the eager model. COPIES (gather to host,
        re-upload dense): aliasing the engine-owned buffers would leave the model
        holding donated (deleted) arrays after the next step."""
        for n in self._param_names:
            self._state_refs[n]._data = jnp.asarray(np.asarray(self.params[n]))
        for n in self._buffer_names:
            self._state_refs[n]._data = jnp.asarray(np.asarray(self.buffers[n]))

    def save(self, path: str):
        self._write_back()
        from ...framework import io as fio

        fio.save(self.model.state_dict(), path + ".pdparams")

    def load(self, path: str):
        from ...framework import io as fio

        self.model.set_state_dict(fio.load(path + ".pdparams"))
        if self._prepared:
            self.prepare()  # re-shard the fresh params

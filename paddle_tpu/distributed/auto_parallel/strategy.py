"""auto_parallel.Strategy: the Engine's config bundle.

Reference: python/paddle/distributed/auto_parallel/strategy.py (amp/recompute/
sharding/gradient_merge sub-configs mirroring DistributedStrategy)."""
from __future__ import annotations


class _Config:
    def __init__(self, **defaults):
        self.enable = False
        for k, v in defaults.items():
            setattr(self, k, v)


class Strategy:
    def __init__(self):
        self.auto_mode = "semi"  # semi-auto: user seeds, GSPMD completes
        self.seed = None
        self.amp = _Config(dtype="bfloat16", level="O1")
        self.recompute = _Config()
        self.sharding = _Config(stage=1, degree=-1)
        self.gradient_merge = _Config(k_steps=1, avg=True)

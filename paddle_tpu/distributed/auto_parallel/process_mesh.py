"""ProcessMesh. Reference: python/paddle/distributed/auto_parallel/process_mesh.py
(an N-D array of ranks + dim names). TPU-native it materializes as a
jax.sharding.Mesh over the same device grid."""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np


class ProcessMesh:
    def __init__(self, mesh: Sequence, dim_names: Optional[List[str]] = None):
        self._mesh = np.asarray(mesh)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(self._mesh.ndim)]
        assert len(dim_names) == self._mesh.ndim, \
            f"{len(dim_names)} dim names for a {self._mesh.ndim}-D mesh"
        self._dim_names = list(dim_names)

    @property
    def shape(self):
        return list(self._mesh.shape)

    @property
    def ndim(self):
        return self._mesh.ndim

    @property
    def dim_names(self):
        return list(self._dim_names)

    @property
    def process_ids(self):
        return self._mesh.reshape(-1).tolist()

    @property
    def mesh(self):
        return self._mesh

    def get_dim_size(self, name: str) -> int:
        return self._mesh.shape[self._dim_names.index(name)]

    def to_jax_mesh(self, devices=None):
        """Materialize as a jax Mesh: rank ids index into the device list."""
        import jax
        from jax.sharding import Mesh

        devices = devices if devices is not None else jax.devices()
        flat_ids = self._mesh.reshape(-1)
        assert flat_ids.max() < len(devices), \
            f"mesh references rank {flat_ids.max()} but only " \
            f"{len(devices)} devices exist"
        grid = np.asarray([devices[i] for i in flat_ids]).reshape(self._mesh.shape)
        return Mesh(grid, axis_names=tuple(self._dim_names))

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and np.array_equal(self._mesh, other._mesh)
                and self._dim_names == other._dim_names)

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names})"

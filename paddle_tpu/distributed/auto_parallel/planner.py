"""Auto-parallel planner: search hybrid topologies on the XLA cost model.

Reference parity: python/paddle/distributed/auto_parallel/planner.py (870 LoC
dist-attr search) + cost_model.py (802 LoC op-level cost simulation). The
TPU-native version is radically cheaper because the compiler IS the cost
model: for each legal hybrid topology we AOT-compile the fused train step
(`jit(...).lower().compile()` — no execution, no weights touched) and read

  - per-device HBM traffic   (cost_analysis()["bytes accessed"])
  - per-device peak memory   (memory_analysis(): args + temps + out - aliased)
  - interconnect volume      (collective output bytes parsed from the
                               optimized HLO — all-reduce/all-gather/
                               reduce-scatter/all-to-all/collective-permute)

and rank by a bandwidth-weighted time proxy. ICI bytes are weighted ~20x HBM
bytes (v5e: ~800 GB/s HBM vs ~45 GB/s/link ICI), the same ratio logic the
reference encodes in its CommOpCost tables (cost_model.py beta/alpha).

Candidates whose peak exceeds the per-device memory budget are rejected —
the planner's answer is then the cheapest FEASIBLE topology, which is how
ZeRO/mp configs win for models that do not fit replicated.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

# bytes per element for HLO type tokens
_DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "bf16": 2,
                "f16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
                "f64": 8}

_COLL_RE = re.compile(
    r"=\s*(?P<type>.+?)\s+"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)"
    r"(?:-start)?\(")
_ARRAY_RE = re.compile(r"(?P<dt>[a-z]+\d*)\[(?P<dims>[\d,]*)\]")


def _type_bytes(type_str: str) -> int:
    """Bytes of an HLO result type (array or tuple of arrays)."""
    total = 0
    for m in _ARRAY_RE.finditer(type_str):
        bpe = _DTYPE_BYTES.get(m.group("dt"))
        if bpe is None:
            continue
        dims = m.group("dims")
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * bpe
    return total


def collective_bytes(hlo_text: str) -> Dict[str, int]:
    """Sum collective OUTPUT bytes per op kind from optimized HLO text."""
    out: Dict[str, int] = {}
    for line in hlo_text.splitlines():
        m = _COLL_RE.search(line)
        if m and "-done" not in line.split("=")[0]:
            out[m.group("op")] = out.get(m.group("op"), 0) + \
                _type_bytes(m.group("type"))
    return out


@dataclass
class PlanResult:
    config: Dict[str, int]
    feasible: bool
    score: float                 # time proxy, lower is better
    hbm_bytes: int               # per-device bytes accessed
    ici_bytes: int               # per-device collective bytes
    peak_bytes: int              # per-device live memory estimate
    flops: float
    detail: Dict = field(default_factory=dict)


def factorizations(n: int, k: int) -> List[tuple]:
    """All k-tuples of power-of-2 (or residual) factors with product n —
    shared by the hybrid-config and mesh-shape planners."""
    if k == 1:
        return [(n,)]
    out = []
    d = 1
    while d <= n:
        if n % d == 0:
            out += [(d,) + r for r in factorizations(n // d, k - 1)]
        d *= 2
    return out


def enumerate_topologies(n_devices: int,
                         axes=("dp", "mp", "sharding"),
                         max_mp: Optional[int] = None) -> List[Dict[str, int]]:
    """All factorizations of n_devices over the given axes (reference
    planner's enumerate over process meshes, planner.py:plan)."""
    cands = []
    for shape in factorizations(n_devices, len(axes)):
        c = dict(zip(axes, shape))
        if max_mp and c.get("mp", 1) > max_mp:
            continue
        # hybrid_configs spells the sp axis "sep_degree" (reference naming).
        # dp_degree is ALWAYS explicit, even at 1: omitted, the HCG's
        # dp_degree=-1 auto-fill would grow dp to consume every host device,
        # silently scoring the candidate on a different topology than its
        # label (e.g. {'sep_degree': 4} becoming dp2 x sp4 on an 8-device
        # host when n_devices=4 was asked for)
        cand = {("sep_degree" if k == "sp" else f"{k}_degree"): v
                for k, v in c.items() if v > 1}
        cand["dp_degree"] = c.get("dp", 1)  # even when dp is not an axis
        cands.append(cand)
    # dedupe (dict order-insensitive)
    seen, uniq = set(), []
    for c in cands:
        key = tuple(sorted(c.items()))
        if key not in seen:
            seen.add(key)
            uniq.append(c)
    return uniq


# v5e-flavored bandwidth ratio: one ICI byte costs ~20 HBM bytes of time
_ICI_WEIGHT = 20.0
# MXU flop per HBM byte at which compute and memory time break even (bf16
# v5e: 197e12 / 800e9 ≈ 250); used only to fold flops into the proxy
_FLOP_PER_BYTE = 250.0

# Stated resolution of the time-proxy model for single-chip variant ranking
# (fraction of predicted throughput). Grounded in the round-5 evidence:
# bench rows repeat within ~1.4% run-to-run, and the one confirmed
# structural mis-rank (b24 predicted over b16, measured 2.3% slower) sat on
# a predicted margin under 1% — the proxy scales bytes/flops ~linearly with
# batch, so batch-axis margins are structurally tiny while the real curve
# bends with per-step overhead and saturation. Margins inside this band are
# model noise, not signal (VERDICT r5 next #5).
PREDICTION_RESOLUTION = 0.03


def pair_verdict(pred_a, pred_b, batch_axis_only: bool,
                 resolution: float = PREDICTION_RESOLUTION):
    """Classify one predicted pairwise ranking: ("a" | "b" | "not_decidable",
    predicted margin). Batch-axis-only pairs (same program family, different
    batch size) are ABSTAINED inside `resolution` instead of ranked — the
    regime of the known b16/b24 mis-rank. Structurally different programs
    (remat, fused-CE chunk, topology changes) keep their full-margin
    ranking: their score deltas come from real byte/flop differences, not
    from the batch-linearity the model cannot resolve."""
    hi, lo = (pred_a, pred_b) if pred_a >= pred_b else (pred_b, pred_a)
    margin = (hi / lo - 1.0) if lo > 0 else float("inf")
    if batch_axis_only and margin < resolution:
        return "not_decidable", margin
    return ("a" if pred_a >= pred_b else "b"), margin


def score_compiled(comp) -> Dict:
    """Cost-model readout shared by the hybrid-config and mesh-shape
    planners: HBM traffic, ICI volume, peak memory, flops, time proxy."""
    from ...utils.hlo_inspect import cost_analysis_dict

    ca = cost_analysis_dict(comp)
    ma = comp.memory_analysis()
    coll = collective_bytes(comp.as_text())
    hbm = int(ca.get("bytes accessed", 0))
    ici = int(sum(coll.values()))
    flops = float(ca.get("flops", 0.0))
    peak = live = 0
    if ma is not None:
        live = int(ma.argument_size_in_bytes + ma.output_size_in_bytes
                   - ma.alias_size_in_bytes)
        peak = live + int(ma.temp_size_in_bytes)
    score = hbm + _ICI_WEIGHT * ici + flops / _FLOP_PER_BYTE
    return {"score": score, "hbm_bytes": hbm, "ici_bytes": ici,
            "peak_bytes": peak, "live_state_bytes": live, "flops": flops,
            "collectives": coll}


def saved_residual_bytes(f, *args) -> int:
    """Policy-aware autodiff residual bytes: what the backward pass will
    actually keep live between forward and backward, with jax.checkpoint
    policies APPLIED. This is the remat-sensitive peak component that XLA's
    AOT memory_analysis does not credit (it reported identical peaks with
    and without selective remat — BASELINE.md round-4 limitation (b)), so
    remat variants get distinct predicted peaks only through this term.
    Trace-level (jaxpr) analysis: nothing compiles or executes."""
    from jax._src.ad_checkpoint import saved_residuals

    res = saved_residuals(f, *args)
    return sum(int(np.prod(a.shape)) * a.dtype.itemsize
               for a, _ in res if hasattr(a, "shape"))


def policy_peak_bytes(metrics: Dict, residual_bytes: int,
                      activation_shards: int = 1) -> int:
    """Remat-corrected peak estimate: persistent live state (params + opt +
    outputs - donation aliasing, from the compiled module) plus the
    policy-aware residuals (divided by the degree the batch/seq dims shard
    over — activations split across dp/sharding/sp shards, the residual
    trace is global). NOTE this omits the transient working set (the one
    checkpoint block's activations alive during its backward recompute);
    feasibility gating must pad it — score_topology uses
    _POLICY_GATE_SAFETY."""
    return int(metrics["live_state_bytes"]
               + residual_bytes // max(1, activation_shards))


# headroom multiplier when the policy peak (no transient working set) is
# allowed to override the XLA peak (no checkpoint-policy credit) in the
# feasibility gate: 2x covers the one-block recompute working set by a wide
# margin for deep models while still separating remat variants from plans
# that genuinely cannot fit
_POLICY_GATE_SAFETY = 2.0


def score_topology(model_factory: Callable, optimizer_factory: Callable,
                   sample_batch, config: Dict[str, int],
                   loss_fn=None, memory_budget: Optional[int] = None,
                   strategy_extra: Optional[Dict] = None) -> PlanResult:
    """AOT-compile the fused step under `config` and read the cost model.

    model_factory/optimizer_factory: fresh instances per candidate (engines
    bind per-topology shardings at construction).
    """
    from .. import DistributedStrategy
    from ..fleet import fleet as fleet_singleton
    from ..mesh import get_hybrid_communicate_group, \
        set_hybrid_communicate_group
    from ..engine import TrainStepEngine

    prev_hcg = get_hybrid_communicate_group()
    prev_fleet = (fleet_singleton._hcg, fleet_singleton._strategy,
                  fleet_singleton._is_initialized)
    try:
        set_hybrid_communicate_group(None)
        strategy = DistributedStrategy()
        strategy.hybrid_configs = dict(config)
        if config.get("sharding_degree", 1) > 1:
            strategy.sharding = True
        for k, v in (strategy_extra or {}).items():
            setattr(strategy, k, v)
        fleet_singleton.init(is_collective=True, strategy=strategy)
        hcg = get_hybrid_communicate_group()

        model = model_factory()
        opt = optimizer_factory(model)
        eng = TrainStepEngine(model, opt, loss_fn=loss_fn, hcg=hcg,
                              strategy=strategy)
        arrays = [b._data if hasattr(b, "_data") else jnp.asarray(b)
                  for b in sample_batch]
        batch_axes = hcg.degrees["dp"] * hcg.degrees["sharding"]
        for a in arrays:
            if a.ndim >= 1 and a.shape[0] % batch_axes != 0:
                return PlanResult(config, False, float("inf"), 0, 0, 0, 0,
                                  {"reason": f"batch {a.shape[0]} % "
                                             f"dp*sharding {batch_axes} != 0"})
        jf = eng._build(arrays)
        comp = jf.lower(eng.params, eng.opt_state, jnp.float32(1e-3),
                        jnp.int32(1), jax.random.key(0), *arrays).compile()
        m = score_compiled(comp)
        # remat-corrected peak: XLA's AOT memory_analysis does not credit
        # jax.checkpoint policies (identical temp bytes with and without
        # selective remat), so recompute variants are additionally scored
        # by live state + policy-aware saved residuals. Feasibility takes
        # the MIN of the two estimates — but the policy estimate carries no
        # transient working set (the recompute-time block activations
        # saved_residuals cannot see), so the gate applies a 2x safety
        # factor to it before it may override the XLA number; a candidate
        # admitted that way is flagged speculative in detail. The residual
        # trace re-runs the whole forward, so it only happens when a
        # memory_budget makes feasibility a real question (plan_validate
        # computes its own peaks for reporting).
        peak_policy = gate_via = None
        peak_for_gate = m["peak_bytes"]
        if memory_budget is not None:
            try:
                act_shards = (hcg.degrees["dp"] * hcg.degrees["sharding"]
                              * hcg.degrees["sp"])
                res_b = saved_residual_bytes(eng.analysis_loss(*arrays),
                                             eng.params)
                peak_policy = policy_peak_bytes(m, res_b, act_shards)
                gated = int(_POLICY_GATE_SAFETY * peak_policy)
                if gated < peak_for_gate:
                    peak_for_gate = gated
                    gate_via = "policy_peak_with_safety"
            except Exception:
                pass  # analysis-only refinement: never fail the scoring
        feasible = memory_budget is None or peak_for_gate <= memory_budget
        return PlanResult(config, feasible, m["score"], m["hbm_bytes"],
                          m["ici_bytes"], m["peak_bytes"], m["flops"],
                          {"collectives": m["collectives"],
                           "peak_policy_bytes": peak_policy,
                           "feasibility_gate": gate_via})
    except Exception as e:  # infeasible lowering (e.g. indivisible shapes)
        return PlanResult(config, False, float("inf"), 0, 0, 0, 0,
                          {"reason": f"{type(e).__name__}: {e}"})
    finally:
        # restore BOTH topology globals: the module-level HCG and the Fleet
        # singleton (else fleet.get_hybrid_communicate_group() afterwards
        # describes the last scored candidate, not the user's config)
        set_hybrid_communicate_group(prev_hcg)
        (fleet_singleton._hcg, fleet_singleton._strategy,
         fleet_singleton._is_initialized) = prev_fleet


def plan(model_factory: Callable, optimizer_factory: Callable, sample_batch,
         n_devices: Optional[int] = None, loss_fn=None,
         memory_budget: Optional[int] = None, axes=("dp", "mp", "sharding"),
         verbose: bool = False) -> "tuple[Dict[str, int], List[PlanResult]]":
    """Pick the cheapest feasible hybrid topology for this model/batch.

    Returns (best_hybrid_configs, ranked results). Raises if nothing is
    feasible (memory budget too small for every topology).
    """
    n = n_devices or jax.device_count()
    results = [score_topology(model_factory, optimizer_factory, sample_batch,
                              c, loss_fn=loss_fn, memory_budget=memory_budget)
               for c in enumerate_topologies(n, axes=axes)]
    results.sort(key=lambda r: (not r.feasible, r.score))
    if verbose:
        for r in results:
            print(f"  {r.config}  feasible={r.feasible} "
                  f"score={r.score:.3e} hbm={r.hbm_bytes} ici={r.ici_bytes} "
                  f"peak={r.peak_bytes}")
    if not results or not results[0].feasible:
        reasons = {str(r.config): r.detail.get("reason", "over budget")
                   for r in results}
        raise RuntimeError(f"planner: no feasible topology: {reasons}")
    return results[0].config, results

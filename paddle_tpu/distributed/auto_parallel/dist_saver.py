"""Distributed checkpoint: shard-wise save + cross-layout restore.

Reference: python/paddle/distributed/auto_parallel/dist_saver.py (each rank
dumps its owned slice + dist_attr metadata) and converter.py (Converter:
merge saved slices with the OLD dist_attr, re-slice for the NEW dist_attr —
how checkpoints survive a change of parallel layout).

TPU-native: a sharded param is a jax global Array; `addressable_shards` gives
exactly the (index, data) pieces the reference's slice metadata describes.
Save writes one .npy per owned shard + a JSON manifest with global shapes and
index ranges; load merges shards into full host arrays and `device_put`s them
with the TARGET engine's shardings — the reshard is the device_put. Works
single-host (all shards addressable) and multi-host (each host writes its
shards; load merges whatever the filesystem holds, so a shared FS sees all).
"""
from __future__ import annotations

import json
import os
from typing import Dict

import numpy as np


def _atomic_save(path, arr):
    """np.save via temp-file + rename: a mid-write kill leaves either the
    previous file or nothing — never a torn .npy a loader half-reads.
    Returns the sha256 of the committed bytes (the manifest checksum)."""
    from ..elastic import file_sha256

    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.save(f, arr)
        f.flush()
        os.fsync(f.fileno())
    digest = file_sha256(tmp)
    os.replace(tmp, path)
    return digest


def _index_to_ranges(index, shape):
    """Normalize an addressable-shard index (tuple of slices) to start/stop."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def save_distributed_checkpoint(engine, dirname, extra_state: Dict = None,
                                rank: int = None):
    """Dump every param/opt-state shard this process owns + the manifest."""
    import jax

    os.makedirs(dirname, exist_ok=True)
    rank = jax.process_index() if rank is None else rank
    manifest = {"params": {}, "opt": {}, "step": int(engine._step_count)}

    def dump(kind, name, arr, comp=None):
        key = name if comp is None else f"{name}.{comp}"
        entry = {"shape": list(np.shape(arr)), "dtype": str(arr.dtype),
                 "shards": []}
        shards = getattr(arr, "addressable_shards", None)
        if shards is None:
            fn = f"{kind}__{key}__full.npy".replace("/", "_")
            digest = _atomic_save(os.path.join(dirname, fn), np.asarray(arr))
            entry["shards"].append({"file": fn,
                                    "checksum": digest,
                                    "ranges": _index_to_ranges(
                                        tuple(slice(0, d) for d in np.shape(arr)),
                                        np.shape(arr))})
        else:
            seen = set()
            for k, sh in enumerate(shards):
                ranges = tuple(map(tuple, _index_to_ranges(sh.index, arr.shape)))
                if ranges in seen:  # replicated copies: save once
                    continue
                seen.add(ranges)
                fn = f"{kind}__{key}__r{rank}s{k}.npy".replace("/", "_")
                digest = _atomic_save(os.path.join(dirname, fn),
                                      np.asarray(sh.data))
                entry["shards"].append({"file": fn,
                                        "checksum": digest,
                                        "ranges": [list(r) for r in ranges]})
        manifest[kind][key] = entry

    for n, arr in engine.params.items():
        dump("params", n, arr)
    # a ZeRO engine's opt_state is None (flat 1/N shards are the state);
    # this legacy dict-form saver gathers it back — elastic.py is the
    # format that keeps the flat layout on disk
    opt_state = (engine._gather_zero_opt()
                 if getattr(engine, "opt_state", None) is None
                 and hasattr(engine, "_gather_zero_opt")
                 else engine.opt_state)
    for n, states in opt_state.items():
        for ci, comp in enumerate(states):
            dump("opt", n, comp, comp=ci)

    # manifest LAST, committed by rename: its presence implies every shard
    # file above is complete and hashed
    mpath = os.path.join(dirname, f"manifest.rank{rank}.json")
    tmp = f"{mpath}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, mpath)


def _merge_entry(dirname, entry):
    from ..elastic import CheckpointCorrupt, file_sha256

    full = np.zeros(entry["shape"], dtype=np.dtype(entry["dtype"]))
    for sh in entry["shards"]:
        path = os.path.join(dirname, sh["file"])
        # pre-checksum checkpoints load unverified; hashed ones must match
        if sh.get("checksum") and file_sha256(path) != sh["checksum"]:
            raise CheckpointCorrupt(f"{path}: checksum mismatch")
        idx = tuple(slice(a, b) for a, b in sh["ranges"])
        full[idx] = np.load(path)
    return full


def load_distributed_state(dirname) -> Dict:
    """Merge every rank's manifest+shards into full host arrays
    (the Converter's merge step)."""
    manifests = [f for f in os.listdir(dirname) if f.startswith("manifest.")]
    if not manifests:
        raise FileNotFoundError(f"no distributed checkpoint in {dirname}")
    merged = {"params": {}, "opt": {}, "step": 0}
    entries = {"params": {}, "opt": {}}
    for mf in manifests:
        with open(os.path.join(dirname, mf)) as f:
            m = json.load(f)
        merged["step"] = max(merged["step"], m.get("step", 0))
        for kind in ("params", "opt"):
            for key, entry in m[kind].items():
                entries[kind].setdefault(key, {"shape": entry["shape"],
                                               "dtype": entry["dtype"],
                                               "shards": []})
                entries[kind][key]["shards"].extend(entry["shards"])
    for kind in ("params", "opt"):
        for key, entry in entries[kind].items():
            merged[kind][key] = _merge_entry(dirname, entry)
    return merged


def load_distributed_checkpoint(engine, dirname):
    """Restore into a (possibly differently-laid-out) engine: merged full
    arrays are device_put with the TARGET shardings — the reshard/slice step
    of the reference Converter collapses into XLA's layout transfer."""
    import jax
    from jax.sharding import NamedSharding

    state = load_distributed_state(dirname)
    for n in engine.params:
        if n not in state["params"]:
            raise KeyError(f"checkpoint missing param {n}")
        engine.params[n] = jax.device_put(
            state["params"][n],
            NamedSharding(engine.mesh, engine.param_specs[n]))
    new_opt = {}
    for n in engine.params:
        if engine.opt_state is not None:
            n_slots = len(engine.opt_state[n])
        else:  # ZeRO engine: slot count comes from the manifest keys
            n_slots = sum(1 for k in state["opt"] if k.startswith(f"{n}."))
        comps = []
        for ci in range(n_slots):
            key = f"{n}.{ci}"
            if key not in state["opt"]:
                raise KeyError(f"checkpoint missing optimizer state {key}")
            comps.append(jax.device_put(
                state["opt"][key],
                NamedSharding(engine.mesh, engine.opt_specs[n])))
        new_opt[n] = tuple(comps)
    engine.opt_state = new_opt
    if getattr(engine, "_zero_opt", None) is not None:
        engine._zero_opt = None  # dict restore: _ensure_zero_opt reconverts
    engine._step_count = state["step"]
    return engine


class Converter:
    """Reference converter.py parity: merge slices saved under one dist_attr,
    re-slice for another. Exposed for manual state-dict surgery; the engine
    path above uses device_put for the same effect."""

    def __init__(self, params_dict, pre_strategy=None, cur_strategy=None):
        self.params_dict = params_dict

    @staticmethod
    def merge_with_dist_attr(slices_with_ranges, shape, dtype="float32"):
        full = np.zeros(shape, np.dtype(dtype))
        for arr, ranges in slices_with_ranges:
            idx = tuple(slice(a, b) for a, b in ranges)
            full[idx] = arr
        return full

    @staticmethod
    def slice_with_dist_attr(full, ranges):
        return full[tuple(slice(a, b) for a, b in ranges)]

    def convert(self, strict=True):
        return self.params_dict

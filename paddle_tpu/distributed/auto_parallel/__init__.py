"""Semi-auto parallel: ProcessMesh + shard annotations + Engine.

Reference: python/paddle/distributed/auto_parallel/ (#38) — the user annotates a
few tensors with (ProcessMesh, shard_spec); `completion.py` (973 LoC) propagates
dist attrs over the whole graph, `partitioner.py` slices the program per rank and
`reshard.py` (1501 LoC) inserts cross-mesh communication; `engine.py` wraps it in
fit/evaluate/predict.

TPU-native: annotation maps to `jax.sharding.PartitionSpec` over a named Mesh,
and the ENTIRE completion/partition/reshard pipeline collapses into XLA's GSPMD
pass — pjit propagates shardings to every intermediate (completion), emits the
per-device program (partitioner), and inserts collectives where specs change
(reshard). The Engine here builds that pjit train step; `reshard()` is
`jax.device_put` with a new NamedSharding.
"""
from .process_mesh import ProcessMesh
from .api import shard_tensor, shard_op, reshard
from .resharder import Resharder, transfer_engine_state
from .engine import Engine
from .planner import (  # noqa: F401
    PlanResult, collective_bytes, enumerate_topologies, plan, score_topology,
)
from .strategy import Strategy
from .dist_saver import (  # noqa: F401
    Converter, load_distributed_checkpoint, load_distributed_state,
    save_distributed_checkpoint,
)

__all__ = ["ProcessMesh", "shard_tensor", "shard_op", "reshard", "Engine",
           "Strategy", "Resharder", "transfer_engine_state"]

"""Runtime cross-mesh resharding — the Resharder.

Reference: python/paddle/distributed/auto_parallel/reshard.py (1,501 LoC of
explicit slice/concat/send/recv insertion between process meshes). TPU-native:
a resharding is one `jax.device_put` onto the target NamedSharding — XLA/PJRT
plans the collective (same-mesh repartition rides ICI; disjoint device sets
bounce through hosts) — so the Resharder's job here is the parts device_put
does NOT do: classifying transfers, moving whole state pytrees with donation
(so HBM never holds both layouts), and switching a live training engine
between parallel topologies mid-run.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor

__all__ = ["Resharder", "transfer_engine_state"]


class Resharder:
    """Plans and applies array transfers onto a target mesh."""

    def __init__(self, target_mesh: Mesh):
        self.mesh = target_mesh
        self.stats = {"noop": 0, "repartition": 0, "cross_mesh": 0,
                      "bytes_moved": 0}

    def sharding(self, spec) -> NamedSharding:
        return NamedSharding(self.mesh, spec if isinstance(spec, P) else P(*spec))

    def plan(self, array, spec) -> str:
        """Classify the transfer: 'noop' (already equivalent), 'repartition'
        (same device set, new layout — XLA collective over ICI), 'cross_mesh'
        (different device set — host/DCN bounce)."""
        dst = self.sharding(spec)
        src = getattr(array, "sharding", None)
        if src is not None and src.is_equivalent_to(dst, array.ndim):
            return "noop"
        src_devs = set(getattr(src, "device_set", ())) if src is not None else set()
        if src_devs and src_devs == set(dst.device_set):
            return "repartition"
        return "cross_mesh"

    def apply(self, array, spec, donate: bool = False):
        """One array -> target sharding. donate=True frees the source layout's
        buffers as the transfer completes (both layouts never coexist);
        donate=False guarantees the RESULT never aliases the source, so a
        destination engine's donating step can't delete the source's buffers.
        """
        import jax.numpy as jnp

        data = array._data if isinstance(array, Tensor) else array
        kind = self.plan(data, spec)
        self.stats[kind] += 1
        if kind == "noop":
            if donate:
                return array  # caller surrendered the source: aliasing is fine
            out = jnp.copy(data)
            if isinstance(array, Tensor):
                t = Tensor(out, stop_gradient=array.stop_gradient)
                t.dist_attr = spec
                return t
            return out
        self.stats["bytes_moved"] += int(data.nbytes)
        out = jax.device_put(data, self.sharding(spec), donate=donate)
        if isinstance(array, Tensor):
            t = Tensor(out, stop_gradient=array.stop_gradient)
            t.dist_attr = spec
            return t
        return out

    def apply_pytree(self, tree, spec_tree, donate: bool = True):
        """Reshard a whole pytree; spec_tree is a matching pytree of
        PartitionSpecs (or one bare PartitionSpec broadcast to all leaves)."""
        if isinstance(spec_tree, P):  # a P is iterable: broadcast explicitly
            spec = spec_tree
            spec_tree = jax.tree_util.tree_map(lambda _: spec, tree)
        return jax.tree_util.tree_map(
            lambda a, s: self.apply(a, s, donate=donate), tree, spec_tree,
            is_leaf=lambda x: isinstance(x, P))


def transfer_engine_state(src_engine, dst_engine, donate: bool = True,
                          resharder: Optional[Resharder] = None) -> Dict:
    """Move a live TrainStepEngine's params + optimizer state onto another
    engine's mesh/topology — the runtime strategy-switch (scale-in re-layout,
    dp->mp migration) the reference Resharder performs between program
    partitions. Returns the resharder stats.

    Both engines must hold the same parameter names (same model). The
    destination's step counter is synced so schedules/Adam bias correction
    continue seamlessly.

    Note: when constructing the destination engine from the SAME eager Layer,
    call ``src_engine.sync_to_model()`` first — the source engine donates the
    layer's original buffers into its jitted step, so the layer must be
    refreshed before another engine initializes from it.
    """
    r = resharder or Resharder(dst_engine.mesh)
    src_names = set(src_engine._param_names)
    dst_names = set(dst_engine._param_names)
    if src_names != dst_names:
        raise ValueError(
            f"engines hold different parameters: only-src="
            f"{sorted(src_names - dst_names)[:5]} only-dst="
            f"{sorted(dst_names - src_names)[:5]}")
    for n in dst_engine._param_names:
        dst_engine.params[n] = r.apply(
            src_engine.params[n],
            dst_engine.param_specs[n], donate=donate)
    for n in dst_engine._param_names:
        dst_engine.opt_state[n] = tuple(
            r.apply(s, dst_engine.opt_specs[n], donate=donate)
            for s in src_engine.opt_state[n])
    for n, b in src_engine.buffers.items():
        if n in dst_engine.buffers:
            dst_engine.buffers[n] = r.apply(b, P(), donate=False)
    dst_engine._step_count = src_engine._step_count
    dst_engine.optimizer._step_count = src_engine._step_count
    dst_engine._key = src_engine._key
    # buffers are baked into the jitted step as closure constants: force a
    # rebuild so the transferred values (e.g. BatchNorm running stats) are
    # actually used, not the destination's init-time snapshot
    dst_engine._step_fn = None
    return r.stats

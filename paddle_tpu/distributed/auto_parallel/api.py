"""Annotation API: shard_tensor / shard_op / reshard.

Reference: python/paddle/distributed/auto_parallel/interface.py (shard_tensor
attaches a DistAttr {process_mesh, dims_mapping}); reshard.py inserts comm ops
when attrs disagree. TPU-native: the attr is a PartitionSpec naming mesh dims
(None = replicated along that tensor dim); reshard is jax.device_put."""
from __future__ import annotations

from typing import List, Optional, Sequence

from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from .process_mesh import ProcessMesh


def _to_spec(shard_spec: Optional[Sequence[Optional[str]]]) -> P:
    if shard_spec is None:
        return P()
    return P(*[s if s else None for s in shard_spec])


def shard_tensor(x, process_mesh: ProcessMesh = None, shard_spec=None):
    """Annotate a tensor/parameter: dim i of x is split over mesh dim
    shard_spec[i] (None = replicated). The annotation rides into the Engine's
    pjit step; GSPMD completes every un-annotated tensor from these seeds."""
    assert isinstance(x, Tensor), f"shard_tensor expects a Tensor, got {type(x)}"
    if shard_spec is not None:
        assert len(shard_spec) <= x.ndim, \
            f"shard_spec {shard_spec} longer than tensor rank {x.ndim}"
    x.dist_attr = _to_spec(shard_spec)
    x.process_mesh = process_mesh
    return x


def shard_op(op_fn, process_mesh: ProcessMesh = None, in_shard_specs=None,
             out_shard_specs=None):
    """Annotate an op call's inputs/outputs (reference interface.py shard_op).
    Inputs are constraint-annotated via jax.lax.with_sharding_constraint inside
    traced code; eagerly it annotates the output tensors' dist_attr."""

    def wrapper(*args, **kwargs):
        if in_shard_specs is not None:
            for a, spec in zip(args, in_shard_specs):
                if isinstance(a, Tensor) and spec is not None:
                    shard_tensor(a, process_mesh, spec)
        out = op_fn(*args, **kwargs)
        if out_shard_specs is not None:
            outs = out if isinstance(out, (list, tuple)) else [out]
            for o, spec in zip(outs, out_shard_specs):
                if isinstance(o, Tensor) and spec is not None:
                    shard_tensor(o, process_mesh, spec)
        return out

    return wrapper


def reshard(x: Tensor, process_mesh: ProcessMesh, shard_spec) -> Tensor:
    """Materialize x with a new sharding (reference reshard.py's cross-mesh comm
    insertion — here one device_put, XLA emits the collective)."""
    import jax

    mesh = process_mesh.to_jax_mesh()
    sharding = NamedSharding(mesh, _to_spec(shard_spec))
    out = Tensor(jax.device_put(x._data, sharding),
                 stop_gradient=x.stop_gradient)
    out.dist_attr = _to_spec(shard_spec)
    out.process_mesh = process_mesh
    return out

"""TCPStore: rendezvous key-value store for distributed bootstrap.

Reference: paddle/fluid/distributed/store/tcp_store.h:91 (C++ TCPStore with
set/get/wait/add); built here on the C++ backend in core/native/tcp_store.cc via
ctypes, with a pure-Python socket fallback implementing the same wire protocol
semantics. Rank 0 hosts the server; every rank (including 0) is a client —
exactly the reference's master-socket topology (tcp_utils.cc).
"""
from __future__ import annotations

import ctypes
import os
import random
import time
from typing import Dict, List, Optional

from ..core import monitor as _monitor
from ..core.native import load_library

_DEFAULT_TIMEOUT = 900.0  # seconds, matches the reference's default store timeout
RETRIES = _monitor.stat("store.retries")
LEASE_EXPIRIES = _monitor.stat("store.lease_expiries")
GC_KEYS = _monitor.stat("store.gc_keys")


class _StoreOps:
    """Shared high-level helpers over the primitive set/get/add/wait/
    delete_key/list_keys surface — mixed into TCPStore AND FileStore so the
    elastic membership coordinator runs identically on either backend.

    Generation scoping: a live mesh reformation (distributed/membership.py)
    bumps a world generation; every coordination key a generation touches
    (barrier rounds, member leases, join/leave announcements) lives under a
    ``gen<N>`` namespace so a re-formed world can never trip over counters
    or done-flags a dead generation left behind. ``gc_generation`` sweeps a
    retired generation's keys (counted in ``store.gc_keys``).
    """

    def barrier(self, name: str, world_size: Optional[int] = None,
                timeout: Optional[float] = None,
                generation: Optional[int] = None) -> None:
        """All ranks arrive, then all ranks proceed. Reusable: the round is
        derived from the arrival counter, so the same name synchronizes every
        call (reference uses add+wait loops the same way). ``generation``
        namespaces the round keys per world generation — barrier("resume",
        generation=3) can never consume an arrival generation 2 banked."""
        n = world_size or self.world_size
        ns = (f"__barrier__/gen{int(generation)}/{name}"
              if generation is not None else f"__barrier__/{name}")
        arrived = self.add(f"{ns}/count", 1)
        round_idx = (arrived - 1) // n
        done_key = f"{ns}/round{round_idx}/done"
        if arrived == (round_idx + 1) * n:
            self.set(done_key, b"1")
        self.wait([done_key], timeout)

    def gc_generation(self, generation: int) -> int:
        """Delete every key a retired world generation owned (membership
        leases, join/leave announcements, barrier rounds, fleet metric
        snapshots). Returns the number of keys removed; each removal
        counts in ``store.gc_keys``."""
        removed = 0
        for prefix in (f"__elastic__/gen{int(generation)}/",
                       f"__barrier__/gen{int(generation)}/",
                       f"__fleet__/gen{int(generation)}/"):
            for key in self.list_keys(prefix):
                if self.delete_key(key):
                    removed += 1
        if removed:
            GC_KEYS.increase(removed)
        return removed


def _connect_with_retry(connect, host, port, timeout,
                        max_attempts: Optional[int] = None,
                        base_delay: float = 0.05, max_delay: float = 2.0):
    """Bounded retry with exponential backoff + full jitter around a store
    connect. A rank that races its master (the normal elastic-restart case)
    sees ECONNREFUSED on the first attempts; previously that failed the job
    hard. `connect(per_attempt_timeout)` returns a client or None/raises
    OSError; retries are bounded by the store timeout (the rendezvous
    contract) and optionally by PADDLE_TPU_STORE_CONNECT_ATTEMPTS. Jitter
    decorrelates a pod of ranks hammering a just-restarted master. Every
    retry counts in `store.retries`."""
    if max_attempts is None:
        max_attempts = int(os.environ.get(
            "PADDLE_TPU_STORE_CONNECT_ATTEMPTS", "0") or 0) or None
    deadline = time.monotonic() + timeout
    delay = base_delay
    attempt = 0
    last_exc = None
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        attempt += 1
        try:
            client = connect(min(remaining, 5.0))
            if client:
                return client
            last_exc = None
        except OSError as e:  # includes TimeoutError / ConnectionRefused
            last_exc = e
        if max_attempts is not None and attempt >= max_attempts:
            break
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        RETRIES.increase()
        time.sleep(min(delay, max_delay, remaining)
                   * (0.5 + random.random() * 0.5))
        delay *= 2
    raise TimeoutError(
        f"TCPStore: cannot connect to {host}:{port} after {attempt} "
        f"attempt(s) within {timeout}s"
        + (f" (last error: {last_exc!r})" if last_exc is not None else ""))


def _lib():
    lib = load_library("tcp_store")
    if lib is None:
        return None
    lib.ts_server_start.restype = ctypes.c_void_p
    lib.ts_server_start.argtypes = [ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
    lib.ts_server_stop.argtypes = [ctypes.c_void_p]
    lib.ts_client_connect.restype = ctypes.c_void_p
    lib.ts_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.ts_client_free.argtypes = [ctypes.c_void_p]
    lib.ts_set.restype = ctypes.c_int
    lib.ts_set.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                           ctypes.c_int]
    lib.ts_get.restype = ctypes.c_int
    lib.ts_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                           ctypes.c_int, ctypes.POINTER(ctypes.c_int), ctypes.c_int]
    lib.ts_add.restype = ctypes.c_int64
    lib.ts_add.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.ts_wait.restype = ctypes.c_int
    lib.ts_wait.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64]
    lib.ts_num_keys.restype = ctypes.c_int64
    lib.ts_num_keys.argtypes = [ctypes.c_void_p]
    lib.ts_delete.restype = ctypes.c_int
    lib.ts_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.ts_list_prefix.restype = ctypes.c_int
    lib.ts_list_prefix.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                                   ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
    return lib


class TCPStore(_StoreOps):
    """paddle.distributed.TCPStore parity: TCPStore(host, port, is_master,
    world_size, timeout)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, world_size: int = 1,
                 timeout: float = _DEFAULT_TIMEOUT):
        self.host = host
        self.is_master = is_master
        self.world_size = world_size
        self.timeout = timeout
        self._server = None
        self._py_server = None
        lib = _lib()
        self._lib = lib
        if lib is not None:
            if is_master:
                got = ctypes.c_int(0)
                self._server = lib.ts_server_start(port, ctypes.byref(got))
                if not self._server:
                    raise RuntimeError(f"TCPStore: cannot bind port {port}")
                port = got.value
            self.port = port
            self._client = _connect_with_retry(
                lambda t: lib.ts_client_connect(
                    host.encode(), port, int(t * 1000)) or None,
                host, port, timeout)
        else:
            from . import _py_store

            if is_master:
                self._py_server = _py_store.PyStoreServer(port)
                port = self._py_server.port
            self.port = port
            self._client = _connect_with_retry(
                lambda t: _py_store.PyStoreClient(host, port, t),
                host, port, timeout)

    # ---- API (reference tcp_store.h: set/get/wait/add) ----
    def set(self, key: str, value) -> None:
        data = value if isinstance(value, bytes) else str(value).encode()
        if self._lib is not None:
            rc = self._lib.ts_set(self._client, key.encode(), data, len(data))
            if rc != 0:
                raise RuntimeError(f"TCPStore.set({key!r}) failed rc={rc}")
        else:
            self._client.set(key, data)

    def get(self, key: str, wait: bool = True) -> bytes:
        if self._lib is None:
            return self._client.get(key, wait,
                                    timeout=self.timeout if wait else 0.0)
        if wait:
            # wait+get (rather than the server's blocking kGet) so the store's
            # timeout applies — a never-set key raises instead of wedging the job
            self.wait([key])
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            needed = ctypes.c_int(0)
            rc = self._lib.ts_get(self._client, key.encode(), buf, cap,
                                  ctypes.byref(needed), 1)
            if rc >= 0:
                return buf.raw[:rc]
            if rc == -28:  # -ENOSPC: grow the buffer and retry
                cap = max(cap * 2, needed.value)
                continue
            if rc == -2:  # -ENOENT (nowait miss)
                raise KeyError(key)
            raise RuntimeError(f"TCPStore.get({key!r}) failed rc={rc}")

    def add(self, key: str, amount: int = 1) -> int:
        if self._lib is None:
            return self._client.add(key, amount)
        v = self._lib.ts_add(self._client, key.encode(), amount)
        if v == -(2 ** 63):
            raise RuntimeError(f"TCPStore.add({key!r}) failed")
        return int(v)

    def wait(self, keys, timeout: Optional[float] = None) -> None:
        if isinstance(keys, str):
            keys = [keys]
        tmo = self.timeout if timeout is None else timeout
        deadline = time.monotonic() + tmo
        for key in keys:
            remaining_ms = int(max(0.0, deadline - time.monotonic()) * 1000)
            if self._lib is None:
                self._client.wait(key, remaining_ms / 1000.0)
                continue
            rc = self._lib.ts_wait(self._client, key.encode(), remaining_ms)
            if rc == -1:
                raise TimeoutError(f"TCPStore.wait({key!r}): timed out after {tmo}s")
            if rc < -1:
                raise RuntimeError(f"TCPStore.wait({key!r}) failed rc={rc}")

    def num_keys(self) -> int:
        if self._lib is None:
            return self._client.num_keys()
        return int(self._lib.ts_num_keys(self._client))

    def delete_key(self, key: str) -> bool:
        if self._lib is None:
            return self._client.delete(key)
        return self._lib.ts_delete(self._client, key.encode()) > 0

    def list_keys(self, prefix: str = "") -> List[str]:
        """Keys with the given prefix (used by the elastic membership registry)."""
        if self._lib is None:
            return self._client.list_prefix(prefix)
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            needed = ctypes.c_int(0)
            rc = self._lib.ts_list_prefix(self._client, prefix.encode(), buf, cap,
                                          ctypes.byref(needed))
            if rc >= 0:
                raw = buf.raw[:rc].decode()
                return [k for k in raw.split("\n") if k]
            if rc == -28:
                cap = max(cap * 2, needed.value)
                continue
            raise RuntimeError(f"TCPStore.list_keys({prefix!r}) failed rc={rc}")

    def __del__(self):
        try:
            if getattr(self, "_lib", None) is not None:
                if getattr(self, "_client", None):
                    self._lib.ts_client_free(self._client)
                    self._client = None
                if getattr(self, "_server", None):
                    self._lib.ts_server_stop(self._server)
                    self._server = None
            elif getattr(self, "_py_server", None) is not None:
                self._py_server.stop()
                self._py_server = None
        except Exception:
            pass


class FileStore(_StoreOps):
    """Single-host fallback store over a shared directory (reference has a
    libuv-free file store for tests). Full TCPStore API parity — bounded
    ``wait``/``get`` timeouts, ``delete_key``/``list_keys``/``num_keys``,
    the generation-scoped ``barrier``/``gc_generation`` helpers — so the
    elastic membership coordinator runs on either backend, and multi-agent
    tests can rendezvous through a tmpdir instead of a socket."""

    def __init__(self, path: str, world_size: int = 1,
                 timeout: float = _DEFAULT_TIMEOUT):
        self.path = path
        self.world_size = world_size
        self.timeout = timeout
        os.makedirs(path, exist_ok=True)

    _LOCK = ".lock"

    def _p(self, key: str) -> str:
        return os.path.join(self.path, key.replace("/", "%2F"))

    def set(self, key: str, value) -> None:
        data = value if isinstance(value, bytes) else str(value).encode()
        tmp = self._p(key) + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, self._p(key))

    def get(self, key: str, wait: bool = True,
            timeout: Optional[float] = None) -> bytes:
        tmo = self.timeout if timeout is None else timeout
        deadline = time.monotonic() + tmo
        while True:
            try:
                with open(self._p(key), "rb") as f:
                    return f.read()
            except FileNotFoundError:
                if not wait:
                    raise KeyError(key) from None
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"FileStore.get({key!r}): not set within {tmo}s"
                    ) from None
                time.sleep(0.02)

    def add(self, key: str, amount: int = 1) -> int:
        import fcntl

        lockp = os.path.join(self.path, self._LOCK)
        with open(lockp, "w") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            try:
                cur = int(self.get(key, wait=False))
            except KeyError:
                cur = 0
            new = cur + amount
            self.set(key, str(new))
            return new

    def wait(self, keys, timeout: Optional[float] = None) -> None:
        """Block until every key exists; raises TimeoutError past the bound
        (the store timeout by default) instead of wedging the caller — the
        same contract as TCPStore.wait."""
        if isinstance(keys, str):
            keys = [keys]
        tmo = self.timeout if timeout is None else timeout
        deadline = time.monotonic() + tmo
        for k in keys:
            self.get(k, wait=True,
                     timeout=max(0.0, deadline - time.monotonic()))

    def delete_key(self, key: str) -> bool:
        try:
            os.remove(self._p(key))
            return True
        except FileNotFoundError:
            return False

    def list_keys(self, prefix: str = "") -> List[str]:
        """Keys with the given prefix (used by the elastic membership
        registry). Internal lock/tmp files are invisible by construction."""
        out = []
        try:
            names = os.listdir(self.path)
        except OSError:
            return out
        for name in names:
            if name == self._LOCK or ".tmp." in name:
                continue
            key = name.replace("%2F", "/")
            if key.startswith(prefix):
                out.append(key)
        return sorted(out)

    def num_keys(self) -> int:
        return len(self.list_keys())

"""Elastic membership: generation-scoped worker leases over the TCP/File store.

The live-autoscaling half of distributed/elastic.py (which owns the disk
path). A fleet member runs a :class:`WorkerAgent` — register + heartbeat
lease under the current *generation*, announce leave/preemption on the way
out — and the single-controller driver runs an :class:`ElasticCoordinator`
that polls membership at step boundaries and, when the live world changes,
pauses training, re-forms the mesh at the new world size via
``engine.reform_mesh`` (in-memory ``device_put`` redistribution of params +
flat ZeRO opt shards — PR 9's cross-mesh reslice math, no disk bounce), and
resumes. ``restore_latest`` remains the fallback for hard crashes only.

Store schema (all keys under one generation namespace, GC'd when the world
moves on — a re-formed world never trips over a dead generation's keys):

    __elastic__/gen                      current generation number (str int)
    __elastic__/gen.ctr                  add()-counter backing the bumps
    __elastic__/gen<g>/member/<wid>      lease JSON {wid, deadline, ts}
    __elastic__/gen<g>/leave/<wid>       leave JSON {wid, reason, ts}
    __elastic__/gen<g>/replica/<rid>     serving-replica lease (same JSON)
    __barrier__/gen<g>/...               generation-scoped barrier keys

Wall-clock (``time.time()``) lease deadlines, not monotonic: leases are
compared across processes. Counters: ``elastic.reformations``,
``elastic.preemptions``, ``elastic.joins``/``leaves``,
``elastic.lease_expiries``, ``elastic.resumed_steps``,
``elastic.reform_failures`` (core.monitor always; mirrored into the PR 6
metrics registry when one is enabled, plus ``elastic.pause_ms`` /
``elastic.drain_ms`` histograms and ``elastic.generation`` /
``elastic.world_size`` gauges). A failed reformation (lease timeout
mid-reshard, generation moved underneath us) dumps an
``elastic_reform_<gen>`` flight-recorder ring — membership state + last-N
step records — instead of hanging.
"""
from __future__ import annotations

import collections
import json
import signal
import threading
import time
from typing import Callable, Dict, List, Optional

from ..core import flags as _flags
from ..core import monitor as _monitor
from ..observability import flight_recorder as _obs_flight
from ..observability import metrics as _obs_metrics
from ..observability import tracer as _obs_tracer
from .mesh import HybridCommunicateGroup

GEN_KEY = "__elastic__/gen"
GEN_CTR = "__elastic__/gen.ctr"

REFORMATIONS = _monitor.stat("elastic.reformations")
REFORM_FAILURES = _monitor.stat("elastic.reform_failures")
PREEMPTIONS = _monitor.stat("elastic.preemptions")
JOINS = _monitor.stat("elastic.joins")
LEAVES = _monitor.stat("elastic.leaves")
LEASE_EXPIRIES = _monitor.stat("elastic.lease_expiries")
RESUMED_STEPS = _monitor.stat("elastic.resumed_steps")


def _reg_inc(name: str, n: float = 1.0) -> None:
    reg = _obs_metrics.active_registry()
    if reg is not None:
        reg.counter(name).inc(n)


def current_generation(store) -> int:
    """The fleet's generation number; 0 before any coordinator ran."""
    try:
        return int(store.get(GEN_KEY, wait=False))
    except KeyError:
        return 0


def bump_generation(store) -> int:
    """Atomically advance the generation. The add()-counter is the source
    of truth (two concurrent bumps can never mint the same number); the
    plain GEN_KEY mirror exists so readers never mix add() and get() on
    the same key (the C++ TCPStore stores add() values in binary)."""
    g = store.add(GEN_CTR, 1)
    store.set(GEN_KEY, str(g))
    return g


def member_key(generation: int, wid: str, kind: str = "member") -> str:
    return f"__elastic__/gen{int(generation)}/{kind}/{wid}"


def _parse_member(raw: bytes) -> dict:
    try:
        return json.loads(raw.decode())
    except (ValueError, UnicodeDecodeError):
        return {}


class WorkerAgent:
    """One fleet member's view of the membership protocol.

    ``register()`` writes a lease under the current generation;
    ``heartbeat()`` refreshes it (and follows generation bumps — after a
    reformation the next beat re-registers under the new namespace).
    ``announce_leave()`` posts a leave record and revokes the lease so the
    coordinator sees a graceful departure instead of waiting out the
    lease. ``install_sigterm_handler()`` turns SIGTERM into exactly that
    announcement (reason ``"sigterm"`` → ``elastic.preemptions``).

    ``kind="replica"`` registers under the serving-replica namespace —
    same protocol, separate member set (ServingEngine uses this).
    """

    def __init__(self, store, worker_id: str,
                 lease_s: Optional[float] = None, kind: str = "member"):
        self.store = store
        self.worker_id = str(worker_id)
        self.lease_s = float(lease_s if lease_s is not None
                             else _flags.flag("elastic_lease_s"))
        self.kind = kind
        self._registered_gen: Optional[int] = None
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._prev_sigterm = None
        self._lock = threading.Lock()

    # ---- lease lifecycle ----
    def generation(self) -> int:
        return current_generation(self.store)

    def _lease_record(self) -> bytes:
        now = time.time()
        return json.dumps({"wid": self.worker_id, "ts": now,
                           "deadline": now + self.lease_s}).encode()

    def register(self, generation: Optional[int] = None) -> int:
        g = self.generation() if generation is None else int(generation)
        with self._lock:
            self.store.set(member_key(g, self.worker_id, self.kind),
                           self._lease_record())
            fresh = self._registered_gen is None
            self._registered_gen = g
        if fresh:
            JOINS.increase()
            _reg_inc("elastic.joins")
        return g

    def heartbeat(self) -> int:
        """Refresh the lease; follows generation moves automatically."""
        return self.register()

    def start_heartbeat(self) -> None:
        if self._hb_thread is not None:
            return
        self._hb_stop.clear()
        interval = max(0.05, self.lease_s / 3.0)

        def _beat():
            while not self._hb_stop.wait(interval):
                try:
                    self.heartbeat()
                except Exception:
                    # a dead store ends the lease naturally; the
                    # coordinator treats the expiry as a departure
                    return

        self._hb_thread = threading.Thread(
            target=_beat, name=f"elastic-hb-{self.worker_id}", daemon=True)
        self._hb_thread.start()

    def stop_heartbeat(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
            self._hb_thread = None

    def announce_leave(self, reason: str = "leave") -> None:
        self.stop_heartbeat()
        with self._lock:
            # a reformation may have carried this lease into a newer
            # generation before our heartbeat followed it: revoke the
            # lease everywhere we might be registered, announce in the
            # newest namespace (where the coordinator looks next)
            gens = {g for g in (self._registered_gen, self.generation())
                    if g is not None}
            g = max(gens) if gens else 0
            now = time.time()
            self.store.set(
                member_key(g, self.worker_id, "leave"),
                json.dumps({"wid": self.worker_id, "reason": reason,
                            "ts": now}).encode())
            for gg in gens:
                self.store.delete_key(
                    member_key(gg, self.worker_id, self.kind))
            self._registered_gen = None
        LEAVES.increase()
        _reg_inc("elastic.leaves")
        if reason == "sigterm":
            PREEMPTIONS.increase()
            _reg_inc("elastic.preemptions")

    # ---- preemption ----
    def install_sigterm_handler(self) -> None:
        """SIGTERM → announce a preemption-leave, then chain the previous
        handler (so the process's own shutdown path still runs)."""
        def _on_sigterm(signum, frame):
            try:
                self.announce_leave("sigterm")
            finally:
                prev = self._prev_sigterm
                if callable(prev):
                    prev(signum, frame)

        self._prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)


class ElasticCoordinator:
    """Single-controller membership poller + live mesh re-former.

    ``maybe_reform(engine)`` reads the live member set (expired leases are
    evicted and counted), asks ``topology_for(n_live)`` for the hcg the
    fleet should run at, and — when that differs from the engine's current
    topology — bumps the generation, carries the live leases into the new
    namespace, re-forms the engine in memory, validates the generation
    didn't move underneath the reshard, and GCs the dead generation's
    keys. Failures dump ``elastic_reform_<gen>`` to the flight recorder
    and fall back to ``restore_latest`` when a checkpoint dir is
    configured; without one the error propagates (hard crash).

    ``topology_for(n) -> Optional[HybridCommunicateGroup]``: defaults to a
    pure dp-n mesh over the first n local devices; return None to keep the
    current topology (e.g. n has no valid mesh factorization yet).
    """

    def __init__(self, store,
                 topology_for: Optional[Callable[[int], Optional[
                     HybridCommunicateGroup]]] = None,
                 lease_s: Optional[float] = None,
                 ckpt_dir: Optional[str] = None,
                 check_interval: Optional[int] = None):
        self.store = store
        self.topology_for = topology_for or self._default_topology
        self.lease_s = float(lease_s if lease_s is not None
                             else _flags.flag("elastic_lease_s"))
        self.ckpt_dir = ckpt_dir
        self.check_interval = max(1, int(
            check_interval if check_interval is not None
            else _flags.flag("elastic_check_interval")))
        self.last_pause_ms: Optional[float] = None
        self.reformations = 0
        self._fault_hook: Optional[Callable[[], None]] = None
        # bounded tail of SLO alert transitions (note_alert, typically
        # wired as slo_engine.add_hook(coordinator.note_alert)): a failed
        # reformation's flight dump then shows what the SLO layer was
        # screaming about when the world changed
        self._alert_tail: collections.deque = collections.deque(maxlen=16)

    def note_alert(self, event: dict) -> None:
        """SLO-engine hook target: remember recent alert transitions for
        reformation postmortems (observability.slo.SloEngine.add_hook)."""
        self._alert_tail.append(dict(event))

    def recent_alerts(self) -> List[dict]:
        return list(self._alert_tail)

    @staticmethod
    def _default_topology(n: int) -> Optional[HybridCommunicateGroup]:
        import jax

        if n < 1 or n > len(jax.devices()):
            return None
        return HybridCommunicateGroup(dp_degree=n,
                                      devices=jax.devices()[:n])

    # ---- membership ----
    def generation(self) -> int:
        return current_generation(self.store)

    def live_members(self, generation: Optional[int] = None,
                     kind: str = "member") -> Dict[str, dict]:
        """Current holders of unexpired leases in a generation. Expired
        leases are evicted here (the poll IS the failure detector) and
        counted as ``elastic.lease_expiries`` + ``store.lease_expiries``."""
        from . import store as _store_mod

        g = self.generation() if generation is None else int(generation)
        now = time.time()
        out: Dict[str, dict] = {}
        prefix = f"__elastic__/gen{g}/{kind}/"
        for key in self.store.list_keys(prefix):
            try:
                rec = _parse_member(self.store.get(key, wait=False))
            except KeyError:
                continue
            wid = rec.get("wid") or key[len(prefix):]
            if float(rec.get("deadline", 0.0)) < now:
                self.store.delete_key(key)
                LEASE_EXPIRIES.increase()
                _store_mod.LEASE_EXPIRIES.increase()
                _reg_inc("elastic.lease_expiries")
                continue
            out[wid] = rec
        return out

    def _membership_snapshot(self, generation: int) -> dict:
        """Flight-dump payload: everything a postmortem needs to see why a
        reformation failed — who held leases, who announced leaving."""
        snap = {"generation": generation}
        for kind in ("member", "leave", "replica"):
            prefix = f"__elastic__/gen{generation}/{kind}/"
            recs = {}
            for key in self.store.list_keys(prefix):
                try:
                    recs[key[len(prefix):]] = _parse_member(
                        self.store.get(key, wait=False))
                except KeyError:
                    pass
            snap[kind + "s"] = recs
        if self._alert_tail:
            snap["slo_alerts"] = list(self._alert_tail)
        return snap

    # ---- reformation ----
    def maybe_reform(self, engine) -> bool:
        """Poll membership; re-form the engine's mesh when the live world
        size changed. Returns True when a reformation happened (the engine
        now runs at the new world size; committed steps are intact)."""
        old_gen = self.generation()
        members = self.live_members(old_gen)
        n_live = len(members)
        if n_live == 0:
            return False  # nothing registered yet — membership not in use
        new_hcg = self.topology_for(n_live)
        if new_hcg is None or new_hcg.topology() == engine.hcg.topology():
            return False

        t0 = time.perf_counter()
        tr = _obs_tracer.get_tracer()
        new_gen = bump_generation(self.store)
        if tr.enabled:
            # reformation lifecycle as first-class spans: bump (instant) ->
            # pause (whole stopped-world window) -> reshard (redistribution
            # only) -> commit (instant) — one per-generation fleet timeline
            tr.instant("elastic.generation_bump", generation=new_gen,
                       from_generation=old_gen, n_live=n_live)
        # carry live leases into the new namespace so the first
        # coordinator poll after the reshard doesn't see an empty world;
        # workers' own heartbeats take over the new keys at the next beat
        now = time.time()
        for wid, rec in members.items():
            self.store.set(
                member_key(new_gen, wid),
                json.dumps({"wid": wid, "ts": now,
                            "deadline": now + self.lease_s}).encode())
        try:
            if self._fault_hook is not None:
                self._fault_hook()
            from .elastic import live_reshard

            t_rs = time.perf_counter()
            live_reshard(engine, new_hcg)
            if tr.enabled:
                tr.record_complete(
                    "elastic.reshard", t_rs, time.perf_counter(),
                    {"generation": new_gen,
                     "to_topology": dict(new_hcg.degrees)})
            g_now = self.generation()
            if g_now != new_gen:
                raise RuntimeError(
                    f"generation moved mid-reshard ({new_gen} -> {g_now}); "
                    "membership changed under the reformation")
        except Exception as exc:
            REFORM_FAILURES.increase()
            _reg_inc("elastic.reform_failures")
            if tr.enabled:
                tr.instant("elastic.reform_failed", generation=new_gen,
                           error=f"{type(exc).__name__}: {exc}")
            fr = _obs_flight.get()
            if fr is not None:
                fr.dump(f"elastic_reform_{new_gen}", {
                    "error": f"{type(exc).__name__}: {exc}",
                    "from_topology": dict(engine.hcg.degrees),
                    "to_topology": dict(new_hcg.degrees),
                    "membership": self._membership_snapshot(old_gen),
                })
            if self.ckpt_dir:
                from .elastic import restore_latest

                restore_latest(engine, self.ckpt_dir)
                return False
            raise
        self.store.gc_generation(old_gen)

        t_end = time.perf_counter()
        self.last_pause_ms = (t_end - t0) * 1000.0
        if tr.enabled:
            tr.record_complete("elastic.pause", t0, t_end,
                               {"generation": new_gen,
                                "from_generation": old_gen,
                                "world_size": new_hcg.nranks})
            tr.instant("elastic.commit", generation=new_gen,
                       world_size=new_hcg.nranks,
                       pause_ms=round(self.last_pause_ms, 3))
        self.reformations += 1
        REFORMATIONS.increase()
        reg = _obs_metrics.active_registry()
        if reg is not None:
            reg.counter("elastic.reformations").inc()
            reg.histogram("elastic.pause_ms").observe(self.last_pause_ms)
            reg.gauge("elastic.generation").set(float(new_gen))
            reg.gauge("elastic.world_size").set(float(new_hcg.nranks))
        return True

    def on_step(self, engine, step: Optional[int] = None) -> bool:
        """Step-boundary hook for training loops: polls membership every
        ``check_interval`` steps; steps taken in a re-formed world count
        as ``elastic.resumed_steps``."""
        if self.reformations:
            RESUMED_STEPS.increase()
            _reg_inc("elastic.resumed_steps")
        s = engine._step_count if step is None else int(step)
        if s % self.check_interval:
            return False
        return self.maybe_reform(engine)

"""Distributed/IR pass plug-in surface: PassBase, PassManager, new_pass.

Reference: python/paddle/distributed/passes/pass_base.py — PassBase with
_check_self/_check_conflict, PassContext, the @register_pass decorator and
`new_pass(name, attrs)` factory that strategy code calls by name. TPU-native
altitude: heavy fusion/layout work is XLA's; passes here rewrite the OpDesc
list of a static Program (the part XLA cannot see) — the registry surface is
kept reference-shaped so DistributedStrategy / user code plugs in by name.

Built-ins: the static/passes.py trio (cse, dce, fuse_elementwise) plus
`delete_dropout` (inference cleanup) and `fuse_gemm_epilogue`
(matmul+add -> one op, the reference pass of the same name).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ...static import passes as _static_passes

__all__ = ["PassBase", "PassContext", "PassManager", "new_pass",
           "register_pass", "PASS_REGISTRY"]

PASS_REGISTRY: Dict[str, type] = {}


def register_pass(name: str):
    def deco(cls):
        cls.name = name
        PASS_REGISTRY[name] = cls
        return cls

    return deco


class PassContext:
    """Carries cross-pass state + per-pass results (reference PassContext)."""

    def __init__(self):
        self.attrs: Dict = {}
        self.results: Dict[str, object] = {}


class PassBase:
    name = "base"
    # reference semantics: passes of the same `type` conflict unless
    # explicitly compatible
    _type = "optimization"

    def __init__(self, attrs: Optional[Dict] = None):
        self.attrs = dict(attrs or {})

    def _check_self(self) -> bool:
        return True

    def _check_conflict(self, other: "PassBase") -> bool:
        return True  # compatible by default

    def apply(self, program, context: Optional[PassContext] = None):
        if not self._check_self():
            raise ValueError(f"pass {self.name}: invalid attrs {self.attrs}")
        result = self._apply_impl(program, context or PassContext())
        if context is not None:
            context.results[self.name] = result
        return program

    def _apply_impl(self, program, context):
        raise NotImplementedError


class PassManager:
    """Applies a pipeline of passes in order, checking pairwise conflicts
    (reference pass_base.PassManager)."""

    def __init__(self, passes: Sequence[PassBase]):
        self.passes: List[PassBase] = list(passes)
        for i, p in enumerate(self.passes):
            for q in self.passes[:i]:
                if not (p._check_conflict(q) and q._check_conflict(p)):
                    raise ValueError(
                        f"pass {p.name!r} conflicts with {q.name!r}")
        self.context = PassContext()

    def apply(self, programs):
        progs = programs if isinstance(programs, (list, tuple)) else [programs]
        for prog in progs:
            for p in self.passes:
                p.apply(prog, self.context)
        return programs

    @property
    def names(self):
        return [p.name for p in self.passes]


def new_pass(name: str, attrs: Optional[Dict] = None) -> PassBase:
    """Factory: build a registered pass by name (reference new_pass)."""
    if name not in PASS_REGISTRY:
        raise KeyError(
            f"unknown pass {name!r}; registered: {sorted(PASS_REGISTRY)}")
    return PASS_REGISTRY[name](attrs)


class _StaticPassAdapter(PassBase):
    """Bridges the function-style static/passes.py registry into PassBase."""

    _fn_name: str = ""

    def _apply_impl(self, program, context):
        fetch = list(self.attrs.get("fetch_names", ()))
        if not fetch:
            # no explicit fetches: keep every LEAF output (an output no op
            # consumes) live, so a standalone PassManager run can't eliminate
            # the whole forward as dead code
            block = program.global_block()
            consumed = {n for op in block.ops for n in op.input_names}
            fetch = [o for op in block.ops for o in op.output_names
                     if o not in consumed]
        return _static_passes.PASS_REGISTRY[self._fn_name](program, fetch)


def _adapt(name):
    cls = type(f"_{name}_pass", (_StaticPassAdapter,), {"_fn_name": name})
    return register_pass(name)(cls)


for _n in ("dead_code_elimination", "common_subexpression_elimination",
           "fuse_elementwise"):
    _adapt(_n)


@register_pass("delete_dropout")
class DeleteDropoutPass(PassBase):
    """Inference cleanup: dropout is identity at predict time — drop the op
    and alias its output to its input (reference delete_dropout_op_pass)."""

    def _apply_impl(self, program, context):
        block = program.global_block()
        rename: Dict[str, str] = {}
        kept = []
        removed = 0
        for op in block.ops:
            if rename:
                op.input_names = [rename.get(n, n) for n in op.input_names]
            if op.type == "dropout":
                rename[op.output_names[0]] = op.input_names[0]
                removed += 1
                continue
            kept.append(op)
        block.ops = kept
        aliases = getattr(program, "_var_aliases", {})
        aliases.update(rename)
        program._var_aliases = aliases
        return removed


@register_pass("fuse_gemm_epilogue")
class FuseGemmEpiloguePass(PassBase):
    """matmul followed by a single-consumer bias add -> one fused op
    (reference fuse_gemm_epilogue_pass; on TPU XLA fuses the epilogue into
    the MXU matmul anyway — this shrinks the op list the per-op debug
    interpreter walks and keeps the pass name addressable)."""

    def _apply_impl(self, program, context):
        from ...static.framework import OpDesc

        block = program.global_block()
        consumers: Dict[str, int] = {}
        for op in block.ops:
            for n in op.input_names:
                consumers[n] = consumers.get(n, 0) + 1
        # fetched intermediates must survive: callers fetching the matmul
        # output pass fetch_names (as the static-pass adapter does)
        protected = set(self.attrs.get("fetch_names", ()))
        kept = []
        fused = 0
        i, ops = 0, block.ops
        while i < len(ops):
            op = ops[i]
            nxt = ops[i + 1] if i + 1 < len(ops) else None
            if (op.type in ("matmul", "matmul_v2", "mul") and nxt is not None
                    and nxt.type in ("add", "elementwise_add")
                    and len(op.output_names) == 1
                    and op.output_names[0] not in protected
                    and op.output_names[0] in nxt.input_names
                    and consumers.get(op.output_names[0], 0) == 1):
                mm_out = op.output_names[0]
                bias = [n for n in nxt.input_names if n != mm_out]
                mm_kernel, add_kernel = op.kernel, nxt.kernel
                mm_nin = len(op.input_names)
                out_first = nxt.input_names[0] == mm_out

                def fused_kernel(*args, _mm=mm_kernel, _add=add_kernel,
                                 _n=mm_nin, _of=out_first):
                    y = _mm(*args[:_n])
                    rest = args[_n:]
                    return _add(y, *rest) if _of else _add(*rest, y)

                kept.append(OpDesc("fused_gemm_epilogue", fused_kernel,
                                   list(op.input_names) + bias,
                                   nxt.output_names, {}))
                fused += 1
                i += 2
                continue
            kept.append(op)
            i += 1
        block.ops = kept
        return fused


@register_pass("int8_fake_quantize")
class FakeQuantizePass(PassBase):
    """Static-graph quantization pass (reference slim
    quantization_pass.py QuantizationTransformPass): inserts
    fake_quantize_dequantize ops in front of the activation/weight inputs
    of the quantizable ops, so a Program trains/evaluates with int8 grid
    noise. Biases stay unquantized (real int8 deployments keep them
    f32/s32, and the reference pass does the same).
    attrs: quantizable_op_types (default {"linear", "matmul", "mul"}),
    bits (default 8). The inserted op is a real OpDesc — it shows in the
    program text and lowers through the one-XLA-computation executor like
    any other op. Idempotent: already-quantized inputs are skipped, and
    two quantization-_type passes conflict in one PassManager.
    """

    _type = "quantization"
    _FQ = "fake_quantize_dequantize"

    def _check_conflict(self, other):
        return getattr(other, "_type", None) != self._type

    def _apply_impl(self, program, context):
        import jax.numpy as jnp

        from ...incubate.quantization import fake_quant_array

        targets = set(self.attrs.get("quantizable_op_types",
                                     ("linear", "matmul", "mul")))
        bits = int(self.attrs.get("bits", 8))

        def fq_kernel(a):
            if not hasattr(a, "dtype") or not jnp.issubdtype(
                    jnp.asarray(a).dtype, jnp.floating):
                return a
            return fake_quant_array(a, bits)

        from ...static.framework import OpDesc as op_cls

        block = program.global_block()
        new_ops = []
        n_inserted = 0
        quantized = {}  # var name -> its fake-quant output name
        for op in block.ops:
            if op.type in targets and op.type != self._FQ:
                new_inputs = []
                for i, name in enumerate(op.input_names):
                    # skip the bias operand of linear (x, w, bias), and
                    # anything already on the int8 grid (idempotency)
                    is_bias = op.type == "linear" and \
                        len(op.input_names) == 3 and i == 2
                    if is_bias or name.endswith("@fake_quant"):
                        new_inputs.append(name)
                        continue
                    if name not in quantized:
                        qname = f"{name}@fake_quant"
                        block.create_var(qname)
                        new_ops.append(op_cls(
                            self._FQ, fq_kernel, [name], [qname],
                            {"bits": bits}))
                        quantized[name] = qname
                        n_inserted += 1
                    new_inputs.append(quantized[name])
                # a NEW OpDesc, never in-place mutation: Program.clone()
                # copies share op objects, and a clone must keep seeing its
                # own (un-quantized) wiring
                op = op_cls(op.type, op.kernel, new_inputs,
                            op.output_names, op.attrs)
            new_ops.append(op)
        block.ops[:] = new_ops
        program._version += 1
        return {"inserted": n_inserted}

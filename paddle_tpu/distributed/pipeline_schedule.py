"""SPMD pipeline-parallel schedule: scan + ppermute over the 'pp' mesh axis.

Reference analogue: fleet/meta_parallel/pipeline_parallel.py:31 (PipelineParallel,
forward_backward_pipeline:81 — host-driven 1F1B over NCCL p2p with SendRecvMeta shape
negotiation, p2p_communication.py:26,39,217) and the static-graph SectionWorker
(device_worker.h:615) running micro-batch sections in per-device threads.

TPU-native redesign: the whole pipeline is ONE XLA computation. Each pp rank holds its
stage's parameters (leading stage dim sharded over 'pp'); micro-batches rotate through
the stages with `jax.lax.ppermute` (ICI neighbor hop) inside a `lax.scan` over
M + S - 1 "clock ticks" (GPipe fill/steady/drain). There is no Python scheduler, no
shape handshake (shapes are static in the traced program), and no separate comm stream
(XLA overlaps the permute with the next tick's compute). The backward schedule is not
hand-written: `jax.vjp` through scan+ppermute replays the ring in reverse, which is
exactly the reference's backward pass ordering, and XLA pipelines it the same way.

Cost model: bubble fraction = (S-1)/(M+S-1), same as GPipe/1F1B; activation working set
is one micro-batch per stage plus the scan residuals (use jax.checkpoint in the body to
trade FLOPs for HBM, the recompute_interval analogue).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def spmd_pipeline(body_fn, stage_params, x_mb, mesh, axis: str = "pp"):
    """Run a homogeneous pipeline over the `axis` mesh dimension.

    body_fn(stage_params_local, x) -> y
        one stage's compute; x and y must share shape/dtype (activation shape is
        uniform across stages, as in the reference's SendRecvMeta contract).
    stage_params: pytree whose leaves have leading dim S (= mesh.shape[axis]); leaf i
        along that dim is stage i's parameters. Sharded over `axis` by this call.
    x_mb: [M, micro_batch, ...] micro-batched activations, replicated over `axis`
        (other mesh axes — dp/mp/sp — stay under GSPMD auto sharding).
    Returns [M, micro_batch, ...] outputs of the last stage, replicated over `axis`.

    Differentiable: reverse-mode AD through the scan gives the backward pipeline.
    """
    S = int(mesh.shape[axis])
    if S == 1:
        squeezed = jax.tree.map(lambda l: jnp.squeeze(l, 0), stage_params)
        return jax.vmap(lambda x: body_fn(squeezed, x))(x_mb)

    param_specs = jax.tree.map(lambda _: P(axis), stage_params)
    xspec = P()

    def local(params, mb):
        params = jax.tree.map(lambda l: jnp.squeeze(l, 0), params)
        stage = jax.lax.axis_index(axis)
        M = mb.shape[0]
        n_ticks = M + S - 1
        state = jnp.zeros_like(mb[0])
        out = jnp.zeros_like(mb)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            state, out = carry
            # stage 0 ingests micro-batch t (clamped reads past the end are
            # discarded: their outputs never land in a valid out slot)
            inp = jax.lax.dynamic_index_in_dim(mb, jnp.clip(t, 0, M - 1), 0,
                                               keepdims=False)
            cur = jnp.where(stage == 0, inp, state)
            y = body_fn(params, cur)
            # last stage emits micro-batch t-(S-1) once the pipe is full
            oidx = t - (S - 1)
            upd = jax.lax.dynamic_update_index_in_dim(
                out, y.astype(out.dtype), jnp.clip(oidx, 0, M - 1), 0)
            out = jnp.where(jnp.logical_and(stage == S - 1, oidx >= 0), upd, out)
            # rotate activations one hop along the ring (stage s -> s+1)
            state = jax.lax.ppermute(y, axis, perm)
            return (state, out), None

        (_, out), _ = jax.lax.scan(tick, (state, out), jnp.arange(n_ticks))
        # replicate the result over the pp axis (only the last stage holds it)
        return jax.lax.psum(jnp.where(stage == S - 1, out, jnp.zeros_like(out)), axis)

    return jax.shard_map(local, mesh=mesh, in_specs=(param_specs, xspec),
                         out_specs=xspec, axis_names={axis},
                         check_vma=False)(stage_params, x_mb)


def microbatch_split(x, num_micro: int):
    """[B, ...] -> [M, B/M, ...]; B must divide by num_micro."""
    b = x.shape[0]
    if b % num_micro != 0:
        raise ValueError(f"batch {b} not divisible by {num_micro} micro-batches")
    return x.reshape((num_micro, b // num_micro) + tuple(x.shape[1:]))


def microbatch_merge(x):
    """[M, mb, ...] -> [M*mb, ...]."""
    return x.reshape((x.shape[0] * x.shape[1],) + tuple(x.shape[2:]))

"""SPMD pipeline-parallel schedule: scan + ppermute over the 'pp' mesh axis.

Reference analogue: fleet/meta_parallel/pipeline_parallel.py:31 (PipelineParallel,
forward_backward_pipeline:81 — host-driven 1F1B over NCCL p2p with SendRecvMeta shape
negotiation, p2p_communication.py:26,39,217) and the static-graph SectionWorker
(device_worker.h:615) running micro-batch sections in per-device threads.

TPU-native redesign: the whole pipeline is ONE XLA computation. Each pp rank holds its
stage's parameters (leading stage dim sharded over 'pp'); micro-batches rotate through
the stages with `jax.lax.ppermute` (ICI neighbor hop) inside a `lax.scan` over
M + S - 1 "clock ticks" (GPipe fill/steady/drain). There is no Python scheduler, no
shape handshake (shapes are static in the traced program), and no separate comm stream
(XLA overlaps the permute with the next tick's compute). The backward schedule is not
hand-written: `jax.vjp` through scan+ppermute replays the ring in reverse, which is
exactly the reference's backward pass ordering, and XLA pipelines it the same way.

Cost model: bubble fraction = (S-1)/(M+S-1), same as GPipe/1F1B; activation working set
is one micro-batch per stage plus the scan residuals (use jax.checkpoint in the body to
trade FLOPs for HBM, the recompute_interval analogue).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.jax_compat import shard_map


def spmd_pipeline(body_fn, stage_params, x_mb, mesh, axis: str = "pp"):
    """Run a homogeneous pipeline over the `axis` mesh dimension.

    body_fn(stage_params_local, x) -> y
        one stage's compute; x and y must share shape/dtype (activation shape is
        uniform across stages, as in the reference's SendRecvMeta contract).
    stage_params: pytree whose leaves have leading dim S (= mesh.shape[axis]); leaf i
        along that dim is stage i's parameters. Sharded over `axis` by this call.
    x_mb: [M, micro_batch, ...] micro-batched activations, replicated over `axis`
        (other mesh axes — dp/mp/sp — stay under GSPMD auto sharding).
    Returns [M, micro_batch, ...] outputs of the last stage, replicated over `axis`.

    Differentiable: reverse-mode AD through the scan gives the backward pipeline.
    """
    S = int(mesh.shape[axis])
    if S == 1:
        squeezed = jax.tree.map(lambda l: jnp.squeeze(l, 0), stage_params)
        return jax.vmap(lambda x: body_fn(squeezed, x))(x_mb)

    param_specs = jax.tree.map(lambda _: P(axis), stage_params)
    xspec = P()

    def local(params, mb):
        params = jax.tree.map(lambda l: jnp.squeeze(l, 0), params)
        stage = jax.lax.axis_index(axis)
        M = mb.shape[0]
        n_ticks = M + S - 1
        state = jnp.zeros_like(mb[0])
        out = jnp.zeros_like(mb)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            state, out = carry
            # stage 0 ingests micro-batch t (clamped reads past the end are
            # discarded: their outputs never land in a valid out slot)
            inp = jax.lax.dynamic_index_in_dim(mb, jnp.clip(t, 0, M - 1), 0,
                                               keepdims=False)
            cur = jnp.where(stage == 0, inp, state)
            y = body_fn(params, cur)
            # last stage emits micro-batch t-(S-1) once the pipe is full
            oidx = t - (S - 1)
            upd = jax.lax.dynamic_update_index_in_dim(
                out, y.astype(out.dtype), jnp.clip(oidx, 0, M - 1), 0)
            out = jnp.where(jnp.logical_and(stage == S - 1, oidx >= 0), upd, out)
            # rotate activations one hop along the ring (stage s -> s+1)
            state = jax.lax.ppermute(y, axis, perm)
            return (state, out), None

        (_, out), _ = jax.lax.scan(tick, (state, out), jnp.arange(n_ticks))
        # replicate the result over the pp axis (only the last stage holds it)
        return jax.lax.psum(jnp.where(stage == S - 1, out, jnp.zeros_like(out)), axis)

    return shard_map(local, mesh=mesh, in_specs=(param_specs, xspec),
                         out_specs=xspec, axis_names={axis},
                         check_vma=False)(stage_params, x_mb)


def _interleaved_schedule(P_: int, V: int, M: int):
    """Static interleaved (circular/virtual-stage) schedule.

    Logical stage s = v*P + r lives on rank r = s % P; an activation leaving
    rank P-1 at chunk v re-enters rank 0 as chunk v+1. Each tick every rank
    processes at most ONE (chunk, microbatch); arrivals it cannot process yet
    wait in a buffer. Work-conserving, higher-chunk-first priority (drain the
    deep end — the 1F1B-flavored order). Returns per-rank int arrays, each
    [P, T]:

      v_sel      chunk whose params to apply (0 when idle)
      ingest     microbatch index to read from x_mb (rank0/chunk0), else -1
      buf_read   buffer slot holding the input activation, else -1
      buf_write  slot where THIS tick's arriving activation is stored, -1
      out_write  output microbatch index emitted this tick, else -1
      valid      1 when the rank does real work this tick

    plus (T, buf_slots). The simulator mirrors the reference's interleaved
    SectionWorker schedule (device_worker.h:615) in tick-synchronous form;
    total ticks ~ M*V + (V-1) + 2*(P-1) vs the sequential stacking's
    V*(M + P - 1) — the bubble shrinks by ~V.
    """
    ingest_next = 0
    # per-rank waiting queues of (v, m, slot); slot == -1 means "from mb"
    waiting = [[] for _ in range(P_)]
    free_slots = [list(range(64)) for _ in range(P_)]  # generous; trimmed below
    arrivals = [dict() for _ in range(P_)]  # tick -> (v, m)
    rows = {k: [[] for _ in range(P_)]
            for k in ("v_sel", "ingest", "buf_read", "buf_write", "out_write",
                      "valid")}
    max_slot = -1
    done = 0
    t = 0
    while done < M:
        if t > 4 * (M * V + P_ * V + 8):
            raise RuntimeError("interleaved schedule did not converge")
        sent = []  # (dst_rank, v, m) arriving at t+1
        for r in range(P_):
            # 1. store this tick's arrival into a buffer slot
            bw = -1
            if t in arrivals[r]:
                v, m = arrivals[r].pop(t)
                bw = free_slots[r].pop(0)
                max_slot = max(max_slot, bw)
                waiting[r].append((v, m, bw))
            rows["buf_write"][r].append(bw)
            # 2. pick work: highest chunk first, then lowest microbatch
            choice = None
            if waiting[r]:
                choice = max(waiting[r], key=lambda it: (it[0], -it[1]))
            if choice is None and r == 0 and ingest_next < M:
                choice = (0, ingest_next, -1)
                ingest_next += 1
            if choice is None:
                rows["v_sel"][r].append(0)
                rows["ingest"][r].append(-1)
                rows["buf_read"][r].append(-1)
                rows["out_write"][r].append(-1)
                rows["valid"][r].append(0)
                continue
            v, m, slot = choice
            if slot >= 0:
                waiting[r].remove(choice)
                free_slots[r].insert(0, slot)
            rows["v_sel"][r].append(v)
            rows["ingest"][r].append(m if slot == -1 else -1)
            rows["buf_read"][r].append(slot)
            rows["valid"][r].append(1)
            if r == P_ - 1 and v == V - 1:
                rows["out_write"][r].append(m)
                done += 1
            else:
                rows["out_write"][r].append(-1)
                nxt_v = v if r < P_ - 1 else v + 1
                sent.append(((r + 1) % P_, nxt_v, m))
        for dst, v, m in sent:
            arrivals[dst][t + 1] = (v, m)
        t += 1
    T = t
    import numpy as np

    return ({k: np.asarray(rows[k], np.int32) for k in rows}, T,
            max(max_slot + 1, 1))


def spmd_pipeline_interleaved(body_fn, stage_params, x_mb, mesh,
                              axis: str = "pp", num_chunks: int = 2):
    """Interleaved virtual-stage pipeline (reference SectionWorker's
    interleaved 1F1B, device_worker.h:615) as ONE tick-synchronous SPMD
    scan: each rank holds `num_chunks` stage chunks (logical stage
    v*P + rank), activations ride `ppermute` around the ring V times, and a
    static host-computed schedule (buffer slots, chunk selection, emission
    ticks) resolves the arrival/processing order — so the pipeline bubble
    is ~(P-1) ticks TOTAL instead of the V*(P-1) that stacking chunks
    sequentially pays. Reverse-mode AD through the scan replays the
    mirrored schedule as the backward pipeline.

    stage_params: pytree whose leaves have leading dims [V, P] — leaf
    [v, r] is the parameters of logical stage v*P + r (chunk-major), so a
    plain NamedSharding P(None, axis) puts each rank's V chunks where they
    execute. x_mb: [M, micro_batch, ...].
    """
    P_ = int(mesh.shape[axis])
    V = int(num_chunks)
    if P_ == 1:
        # degenerate ring: run the V chunks sequentially (spmd_pipeline's
        # S==1 squeeze path would choke on the V-sized stage dim)
        chunks = jax.tree.map(lambda l: jnp.squeeze(l, 1), stage_params)
        out = x_mb
        for v in range(V):
            pv = jax.tree.map(lambda l: l[v], chunks)
            out = jax.vmap(lambda x, pv=pv: body_fn(pv, x))(out)
        return out
    if V == 1:
        merged = jax.tree.map(lambda l: jnp.squeeze(l, 0), stage_params)
        return spmd_pipeline(body_fn, merged, x_mb, mesh, axis)
    M = int(x_mb.shape[0])
    sched, T, n_slots = _interleaved_schedule(P_, V, M)

    vp_params = stage_params
    jax.tree.map(lambda l: None if l.shape[:2] == (V, P_) else
                 (_ for _ in ()).throw(ValueError(
                     f"interleaved stage leaf needs leading dims "
                     f"[{V}, {P_}], got {l.shape}")), vp_params)
    param_specs = jax.tree.map(lambda _: P(None, axis), vp_params)
    xspec = P()
    sspec = P(axis)

    def local(params, mb, v_sel, ingest, buf_read, buf_write, out_write,
              valid):
        # drop the sharded rank dim (size 1 per shard)
        params = jax.tree.map(lambda l: jnp.squeeze(l, 1), params)
        for a in (v_sel, ingest, buf_read, buf_write, out_write, valid):
            assert a.shape[0] == 1
        v_sel, ingest, buf_read, buf_write, out_write, valid = (
            a[0] for a in (v_sel, ingest, buf_read, buf_write, out_write,
                           valid))
        rank = jax.lax.axis_index(axis)
        out = jnp.zeros_like(mb)
        # +1 dummy slot: buf_write == -1 parks the (masked) arrival there
        buf = jnp.zeros((n_slots + 1,) + mb.shape[1:], mb.dtype)
        state = jnp.zeros_like(mb[0])
        perm = [(i, (i + 1) % P_) for i in range(P_)]

        def tick(carry, t):
            state, buf, out = carry
            bw = buf_write[t]
            buf = jax.lax.dynamic_update_index_in_dim(
                buf, state.astype(buf.dtype),
                jnp.where(bw >= 0, bw, n_slots), 0)
            from_mb = jax.lax.dynamic_index_in_dim(
                mb, jnp.clip(ingest[t], 0, M - 1), 0, keepdims=False)
            from_buf = jax.lax.dynamic_index_in_dim(
                buf, jnp.clip(buf_read[t], 0, n_slots), 0, keepdims=False)
            cur = jnp.where(ingest[t] >= 0, from_mb, from_buf)
            p_v = jax.tree.map(
                lambda l: jax.lax.dynamic_index_in_dim(
                    l, v_sel[t], 0, keepdims=False), params)
            y = body_fn(p_v, cur)
            # only real work may land anywhere: idle ticks emit zeros
            y = jnp.where(valid[t] > 0, y, jnp.zeros_like(y))
            oidx = out_write[t]
            upd = jax.lax.dynamic_update_index_in_dim(
                out, y.astype(out.dtype), jnp.clip(oidx, 0, M - 1), 0)
            out = jnp.where(oidx >= 0, upd, out)
            state = jax.lax.ppermute(y, axis, perm)
            return (state, buf, out), None

        (_, _, out), _ = jax.lax.scan(tick, (state, buf, out),
                                      jnp.arange(T))
        last = rank == P_ - 1
        return jax.lax.psum(jnp.where(last, out, jnp.zeros_like(out)), axis)

    sch_args = tuple(jnp.asarray(sched[k]) for k in
                     ("v_sel", "ingest", "buf_read", "buf_write",
                      "out_write", "valid"))
    return shard_map(
        local, mesh=mesh,
        in_specs=(param_specs, xspec) + (sspec,) * 6,
        out_specs=xspec, axis_names={axis},
        check_vma=False)(vp_params, x_mb, *sch_args)


def microbatch_split(x, num_micro: int):
    """[B, ...] -> [M, B/M, ...]; B must divide by num_micro."""
    b = x.shape[0]
    if b % num_micro != 0:
        raise ValueError(f"batch {b} not divisible by {num_micro} micro-batches")
    return x.reshape((num_micro, b // num_micro) + tuple(x.shape[1:]))


def microbatch_merge(x):
    """[M, mb, ...] -> [M*mb, ...]."""
    return x.reshape((x.shape[0] * x.shape[1],) + tuple(x.shape[2:]))

"""Distributed environment contract.

Reference env-var contract (launch/controllers/collective.py, parallel.py:185-189):
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS / PADDLE_CURRENT_ENDPOINT /
MASTER_ADDR / MASTER_PORT. TPU multi-controller: one process per host, all local TPU chips
belong to this process; jax.distributed.initialize is the rendezvous (coordinator = rank 0's
endpoint — the TCPStore analogue).
"""
from __future__ import annotations

import os


class ParallelEnv:
    def __init__(self):
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self.trainer_endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        self.current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        self.device_id = int(os.environ.get("FLAGS_selected_tpus",
                                            os.environ.get("FLAGS_selected_gpus", "0")).split(",")[0])
        self.master_addr = os.environ.get("MASTER_ADDR", "")
        self.master_port = os.environ.get("MASTER_PORT", "")

    @property
    def local_rank(self):
        return self.rank

    @property
    def nranks(self):
        return self.world_size

    @property
    def dev_id(self):
        return self.device_id


_initialized = False


def init_parallel_env():
    """Multi-controller bootstrap: hand rendezvous to jax.distributed (PJRT coordination
    service plays the TCPStore role; reference parallel.py:235 builds core.TCPStore here)."""
    global _initialized
    env = ParallelEnv()
    if env.world_size > 1 and not _initialized:
        import jax

        try:
            # CPU hosts join cross-process collectives through gloo — the
            # reference's CPU backend (ProcessGroupGloo); TPU slices use ICI
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass
        coordinator = env.master_addr and f"{env.master_addr}:{env.master_port}"
        if not coordinator and env.trainer_endpoints and env.trainer_endpoints[0]:
            coordinator = env.trainer_endpoints[0]
        jax.distributed.initialize(
            coordinator_address=coordinator or None,
            num_processes=env.world_size,
            process_id=env.rank,
        )
    _initialized = True
    return env


def get_rank(group=None):
    if group is not None:
        return group.rank
    try:
        import jax

        return jax.process_index()
    except Exception:
        return ParallelEnv().rank


def get_world_size(group=None):
    if group is not None:
        return group.world_size
    try:
        import jax

        return jax.process_count()
    except Exception:
        return ParallelEnv().world_size


def is_initialized():
    return _initialized

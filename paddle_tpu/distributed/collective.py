"""Collective communication API — the `xccl` backend.

Reference: python/paddle/distributed/collective.py (all_reduce/all_gather/... over
ProcessGroupNCCL, #20/#27 in SURVEY.md §2) and the static-graph c_* op family (#22).

TPU-native semantics: a communicator is a named mesh axis; collectives lower to
`jax.lax.{psum, all_gather, psum_scatter, ppermute, all_to_all}` inside `shard_map`.
Two call modes, mirroring the reference's eager-vs-graph split:

1. **Eager on sharded data**: the tensor is a global array sharded over the group axis
   ("each shard = one rank's tensor"); the collective runs one compiled shard_map program.
2. **Traced** (inside a pjit/shard_map program built by the engine): the same functions
   detect they are under a mesh trace and emit the lax collective directly.

Single-process single-device groups (world_size 1) are identity — matching the
reference's fast path when a group has one rank.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .mesh import CommGroup, fleet_default_mesh, get_hybrid_communicate_group

# Reference ReduceOp enum (distributed/collective/Types.h)
class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


_group_counter = [0]
_group_registry = {}


def new_group(ranks=None, backend=None, timeout=None):
    """Reference collective.py:325 — on TPU a subgroup over explicit ranks maps to a
    sub-axis when the ranks align with one; arbitrary subsets keep the rank list and
    use gather-style emulation (sufficient for the CPU-mesh test harness)."""
    _group_counter[0] += 1
    mesh = fleet_default_mesh()
    if ranks is None:
        ranks = list(range(int(np.prod(list(mesh.shape.values())))))
    g = CommGroup(None, list(ranks), mesh, id=_group_counter[0])
    _group_registry[g.id] = g
    return g


def get_group(gid=0):
    if gid == 0 and gid not in _group_registry:
        hcg = get_hybrid_communicate_group()
        if hcg is not None:
            return hcg.get_check_parallel_group()
    return _group_registry.get(gid)


def _in_trace(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _axis_in_scope(axis: str) -> bool:
    """True when `axis` is a bound axis name in the current trace (inside shard_map)."""
    try:
        jax.lax.axis_index(axis)
        return True
    except Exception:
        return False


def spec_has_axis(spec, axis_name) -> bool:
    """Axis membership in a PartitionSpec (flattening tuple entries)."""
    if spec is None:
        return False
    flat = []
    for e in spec:
        if e is None:
            continue
        if isinstance(e, tuple):
            flat.extend(e)
        else:
            flat.append(e)
    return axis_name in flat


def _sharded_over(data, axis_name):
    """Check if a global array is sharded over the given mesh axis."""
    sharding = getattr(data, "sharding", None)
    if sharding is None or not hasattr(sharding, "spec"):
        return False
    return spec_has_axis(sharding.spec, axis_name)


def _eager_axis_collective(x, axis, fn_traced):
    """Run a collective over a mesh axis on an axis-sharded global array via shard_map."""
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = fleet_default_mesh()
    spec = x.sharding.spec if hasattr(x.sharding, "spec") else P()
    # check_vma=False: ops like broadcast (all_gather + index) produce values
    # that ARE replicated but can't be statically inferred as such
    f = shard_map(fn_traced, mesh=mesh, in_specs=(spec,), out_specs=spec,
                  check_vma=False)
    return f(x)


def _resolve(tensor, group, op_name):
    """Common preamble: unwrap, decide identity/traced/eager-sharded path."""
    x = tensor._data if isinstance(tensor, Tensor) else tensor
    axis = getattr(group, "axis", None) if group is not None else None
    if axis is None:
        hcg = get_hybrid_communicate_group()
        if hcg is None or hcg.nranks == 1:
            return x, None, "identity"
        raise ValueError(
            f"{op_name}: pass a CommGroup bound to a mesh axis (e.g. "
            f"hcg.get_model_parallel_group()) — arbitrary-rank groups only support "
            f"point-to-point emulation")
    hcg = get_hybrid_communicate_group()
    if hcg is not None and hcg.degrees.get(axis, 1) == 1:
        return x, axis, "identity"
    if _in_trace(x):
        return x, axis, "traced"
    return x, axis, "eager"


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    x, axis, mode = _resolve(tensor, group, "all_reduce")
    if mode == "identity":
        return tensor
    def _pprod(v, a):
        # no pprod primitive in lax: gather then multiply (rare op; fine off hot path)
        return jnp.prod(jax.lax.all_gather(v, a, axis=0), axis=0)

    red = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
           ReduceOp.MIN: jax.lax.pmin, ReduceOp.PROD: _pprod,
           ReduceOp.AVG: lambda v, a: jax.lax.pmean(v, a)}[op]
    if mode == "traced":
        out = red(x, axis)
    else:
        out = _eager_axis_collective(x, axis, lambda v: red(v, axis))
    if isinstance(tensor, Tensor):
        tensor._data = out
        return tensor
    return out


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    x, ax, mode = _resolve(tensor, group, "all_gather")
    if mode == "identity":
        if tensor_list is not None:
            tensor_list.append(tensor)
            return tensor_list
        return tensor
    if mode == "traced":
        out = jax.lax.all_gather(x, ax, axis=0, tiled=False)
    else:
        out = _eager_axis_collective(x, ax, lambda v: jax.lax.all_gather(v, ax, axis=0))
    if tensor_list is not None:
        n = out.shape[0] if mode == "traced" else get_hybrid_communicate_group().degrees[ax]
        for i in range(n):
            tensor_list.append(Tensor(out[i]))
        return tensor_list
    return Tensor(out) if isinstance(tensor, Tensor) else out


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    """Eager contract (rank-major): input global [n, n*k, ...] sharded over the axis —
    row i is rank i's tensor; output global [n, k, ...] — row i is rank i's reduced
    shard. Traced: plain lax.psum_scatter on the local value."""
    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        from ..ops.manipulation import concat

        src = concat(list(src), axis=0)
    x, ax, mode = _resolve(src, group, "reduce_scatter")
    if mode == "identity":
        out = x
    elif mode == "traced":
        out = jax.lax.psum_scatter(x, ax, scatter_dimension=0, tiled=True)
    else:
        def rs(v):  # v local [1, n*k, ...]
            red = jax.lax.psum_scatter(v[0], ax, scatter_dimension=0, tiled=True)
            return red[None]

        out = _eager_axis_collective(x, ax, rs)
    if isinstance(tensor, Tensor):
        tensor._data = out
        return tensor
    return Tensor(out)


def broadcast(tensor, src=0, group=None, sync_op=True):
    x, ax, mode = _resolve(tensor, group, "broadcast")
    if mode == "identity":
        return tensor
    src_local = group.get_group_rank(src) if group is not None and src in group.ranks else src

    def bcast(v):
        return jax.lax.all_gather(v, ax, axis=0)[src_local]

    if mode == "traced":
        out = bcast(x)
    else:
        out = _eager_axis_collective(x, ax, bcast)
    if isinstance(tensor, Tensor):
        tensor._data = out
        return tensor
    return out


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # on a mesh axis, reduce == all_reduce (every shard gets the result; the dst
    # distinction is meaningless under SPMD — reference ranks other than dst simply
    # ignore their copy)
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    x, ax, mode = _resolve(tensor, group, "scatter")
    if mode == "identity":
        if tensor_list:
            tensor._data = tensor_list[0]._data
        return tensor
    if tensor_list is not None:
        stacked = jnp.stack([t._data if isinstance(t, Tensor) else t for t in tensor_list])

        def sc(v):
            return stacked[jax.lax.axis_index(ax)]

        if mode == "traced":
            out = sc(x)
        else:
            out = _eager_axis_collective(x, ax, sc)
        tensor._data = out
    return tensor


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    """MoE dispatch primitive (reference global_scatter/global_gather use this)."""
    from ..ops.manipulation import concat

    src = in_tensor_list
    if isinstance(src, (list, tuple)):
        src = concat(list(src), axis=0)
    x, ax, mode = _resolve(src, group, "all_to_all")
    if mode == "identity":
        if out_tensor_list is not None and isinstance(in_tensor_list, (list, tuple)):
            out_tensor_list.extend(in_tensor_list)
        return out_tensor_list
    n = get_hybrid_communicate_group().degrees[ax]

    def a2a_local(v):  # v: one rank's tensor [n*chunk, ...]
        chunk = v.shape[0] // n
        vr = v.reshape((n, chunk) + v.shape[1:])
        return jax.lax.all_to_all(vr, ax, split_axis=0, concat_axis=0, tiled=False).reshape(
            (n * chunk,) + v.shape[1:])

    if mode == "traced":
        out = a2a_local(x)
    else:
        out = _eager_axis_collective(x, ax, lambda v: a2a_local(v[0])[None])
    if out_tensor_list is not None:
        chunk = out.shape[0] // n
        for i in range(n):
            out_tensor_list.append(Tensor(out[i * chunk:(i + 1) * chunk]))
        return out_tensor_list
    return Tensor(out)


alltoall = all_to_all


def send(tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "p2p send/recv map to ppermute inside pipeline schedules "
        "(meta_parallel/pp_layers); standalone eager p2p lands with multi-controller")


def recv(tensor, src=0, group=None, sync_op=True):
    raise NotImplementedError(
        "p2p send/recv map to ppermute inside pipeline schedules "
        "(meta_parallel/pp_layers); standalone eager p2p lands with multi-controller")


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group)


def barrier(group=None):
    # single-controller: all local devices are driven by this process; only
    # multi-host needs an actual sync
    import jax as _j

    try:
        from jax.experimental import multihost_utils

        if _j.process_count() > 1:
            multihost_utils.sync_global_devices("paddle_tpu_barrier")
    except Exception:
        pass


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        tensor._data.block_until_ready()
    return tensor


# ---- traced-mode helpers used by meta_parallel layers ----

def p_split(x, axis_name: str, dim: int):
    """c_split analogue: take this shard's slice along `dim` (traced mode)."""
    idx = jax.lax.axis_index(axis_name)
    hcg = get_hybrid_communicate_group()
    n = hcg.degrees[axis_name]
    size = x.shape[dim] // n
    return jax.lax.dynamic_slice_in_dim(x, idx * size, size, axis=dim)


def p_concat(x, axis_name: str, dim: int):
    """c_concat analogue: all_gather along `dim` (traced mode)."""
    return jax.lax.all_gather(x, axis_name, axis=dim, tiled=True)

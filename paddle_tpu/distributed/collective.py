"""Collective communication API — the `xccl` backend.

Reference: python/paddle/distributed/collective.py (all_reduce/all_gather/... over
ProcessGroupNCCL, #20/#27 in SURVEY.md §2) and the static-graph c_* op family (#22).

TPU-native semantics: a communicator is a named mesh axis; collectives lower to
`jax.lax.{psum, all_gather, psum_scatter, ppermute, all_to_all}` inside `shard_map`.
Two call modes, mirroring the reference's eager-vs-graph split:

1. **Eager on sharded data**: the tensor is a global array sharded over the group axis
   ("each shard = one rank's tensor"); the collective runs one compiled shard_map program.
2. **Traced** (inside a pjit/shard_map program built by the engine): the same functions
   detect they are under a mesh trace and emit the lax collective directly.

Single-process single-device groups (world_size 1) are identity — matching the
reference's fast path when a group has one rank.
"""
from __future__ import annotations

import functools as _functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from .mesh import CommGroup, fleet_default_mesh, get_hybrid_communicate_group

# Reference ReduceOp enum (distributed/collective/Types.h)
class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


_group_counter = [0]
_group_registry = {}


def new_group(ranks=None, backend=None, timeout=None):
    """Reference collective.py:325 — on TPU a subgroup over explicit ranks maps to a
    sub-axis when the ranks align with one; arbitrary subsets keep the rank list and
    use gather-style emulation (sufficient for the CPU-mesh test harness)."""
    _group_counter[0] += 1
    mesh = fleet_default_mesh()
    if ranks is None:
        ranks = list(range(int(np.prod(list(mesh.shape.values())))))
    g = CommGroup(None, list(ranks), mesh, id=_group_counter[0])
    _group_registry[g.id] = g
    return g


def get_group(gid=0):
    if gid == 0 and gid not in _group_registry:
        hcg = get_hybrid_communicate_group()
        if hcg is not None:
            return hcg.get_check_parallel_group()
    return _group_registry.get(gid)


def _in_trace(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _axis_in_scope(axis: str) -> bool:
    """True when `axis` is a bound axis name in the current trace (inside shard_map)."""
    try:
        jax.lax.axis_index(axis)
        return True
    except Exception:
        return False


def spec_has_axis(spec, axis_name) -> bool:
    """Axis membership in a PartitionSpec (flattening tuple entries)."""
    if spec is None:
        return False
    flat = []
    for e in spec:
        if e is None:
            continue
        if isinstance(e, tuple):
            flat.extend(e)
        else:
            flat.append(e)
    return axis_name in flat


def _sharded_over(data, axis_name):
    """Check if a global array is sharded over the given mesh axis."""
    sharding = getattr(data, "sharding", None)
    if sharding is None or not hasattr(sharding, "spec"):
        return False
    return spec_has_axis(sharding.spec, axis_name)


def _eager_axis_collective(x, axis, fn_traced):
    """Run a collective over a mesh axis on an axis-sharded global array via shard_map."""
    from ..core.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = fleet_default_mesh()
    spec = x.sharding.spec if hasattr(x.sharding, "spec") else P()
    # check_vma=False: ops like broadcast (all_gather + index) produce values
    # that ARE replicated but can't be statically inferred as such
    f = shard_map(fn_traced, mesh=mesh, in_specs=(spec,), out_specs=spec,
                  check_vma=False)
    return f(x)


def _resolve(tensor, group, op_name):
    """Common preamble: unwrap, decide identity/traced/eager-sharded path."""
    x = tensor._data if isinstance(tensor, Tensor) else tensor
    axis = getattr(group, "axis", None) if group is not None else None
    if axis is None:
        hcg = get_hybrid_communicate_group()
        if hcg is None or hcg.nranks == 1:
            return x, None, "identity"
        raise ValueError(
            f"{op_name}: pass a CommGroup bound to a mesh axis (e.g. "
            f"hcg.get_model_parallel_group()) — arbitrary-rank groups only support "
            f"point-to-point emulation")
    hcg = get_hybrid_communicate_group()
    if hcg is not None and hcg.degrees.get(axis, 1) == 1:
        return x, axis, "identity"
    if _in_trace(x):
        return x, axis, "traced"
    return x, axis, "eager"


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    x, axis, mode = _resolve(tensor, group, "all_reduce")
    if mode == "identity":
        return tensor
    def _pprod(v, a):
        # no pprod primitive in lax: gather then multiply (rare op; fine off hot path)
        return jnp.prod(jax.lax.all_gather(v, a, axis=0), axis=0)

    red = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
           ReduceOp.MIN: jax.lax.pmin, ReduceOp.PROD: _pprod,
           ReduceOp.AVG: lambda v, a: jax.lax.pmean(v, a)}[op]
    if mode == "traced":
        out = red(x, axis)
    else:
        out = _eager_axis_collective(x, axis, lambda v: red(v, axis))
    if isinstance(tensor, Tensor):
        tensor._data = out
        return tensor
    return out


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    x, ax, mode = _resolve(tensor, group, "all_gather")
    if mode == "identity":
        if tensor_list is not None:
            tensor_list.append(tensor)
            return tensor_list
        return tensor
    if mode == "traced":
        out = jax.lax.all_gather(x, ax, axis=0, tiled=False)
    else:
        out = _eager_axis_collective(x, ax, lambda v: jax.lax.all_gather(v, ax, axis=0))
    if tensor_list is not None:
        n = out.shape[0] if mode == "traced" else get_hybrid_communicate_group().degrees[ax]
        for i in range(n):
            tensor_list.append(Tensor(out[i]))
        return tensor_list
    return Tensor(out) if isinstance(tensor, Tensor) else out


def reduce_scatter(tensor, tensor_or_tensor_list, op=ReduceOp.SUM, group=None, sync_op=True):
    """Eager contract (rank-major): input global [n, n*k, ...] sharded over the axis —
    row i is rank i's tensor; output global [n, k, ...] — row i is rank i's reduced
    shard. Traced: plain lax.psum_scatter on the local value."""
    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        from ..ops.manipulation import concat

        src = concat(list(src), axis=0)
    x, ax, mode = _resolve(src, group, "reduce_scatter")
    if mode == "identity":
        out = x
    elif mode == "traced":
        out = jax.lax.psum_scatter(x, ax, scatter_dimension=0, tiled=True)
    else:
        def rs(v):  # v local [1, n*k, ...]
            red = jax.lax.psum_scatter(v[0], ax, scatter_dimension=0, tiled=True)
            return red[None]

        out = _eager_axis_collective(x, ax, rs)
    if isinstance(tensor, Tensor):
        tensor._data = out
        return tensor
    return Tensor(out)


def broadcast(tensor, src=0, group=None, sync_op=True):
    x, ax, mode = _resolve(tensor, group, "broadcast")
    if mode == "identity":
        return tensor
    src_local = group.get_group_rank(src) if group is not None and src in group.ranks else src

    def bcast(v):
        return jax.lax.all_gather(v, ax, axis=0)[src_local]

    if mode == "traced":
        out = bcast(x)
    else:
        out = _eager_axis_collective(x, ax, bcast)
    if isinstance(tensor, Tensor):
        tensor._data = out
        return tensor
    return out


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # on a mesh axis, reduce == all_reduce (every shard gets the result; the dst
    # distinction is meaningless under SPMD — reference ranks other than dst simply
    # ignore their copy)
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    x, ax, mode = _resolve(tensor, group, "scatter")
    if mode == "identity":
        if tensor_list:
            tensor._data = tensor_list[0]._data
        return tensor
    if tensor_list is not None:
        stacked = jnp.stack([t._data if isinstance(t, Tensor) else t for t in tensor_list])

        def sc(v):
            return stacked[jax.lax.axis_index(ax)]

        if mode == "traced":
            out = sc(x)
        else:
            out = _eager_axis_collective(x, ax, sc)
        tensor._data = out
    return tensor


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    """MoE dispatch primitive (reference global_scatter/global_gather use this)."""
    from ..ops.manipulation import concat

    src = in_tensor_list
    if isinstance(src, (list, tuple)):
        src = concat(list(src), axis=0)
    x, ax, mode = _resolve(src, group, "all_to_all")
    if mode == "identity":
        if out_tensor_list is not None and isinstance(in_tensor_list, (list, tuple)):
            out_tensor_list.extend(in_tensor_list)
        return out_tensor_list
    n = get_hybrid_communicate_group().degrees[ax]

    def a2a_local(v):  # v: one rank's tensor [n*chunk, ...]
        chunk = v.shape[0] // n
        vr = v.reshape((n, chunk) + v.shape[1:])
        return jax.lax.all_to_all(vr, ax, split_axis=0, concat_axis=0, tiled=False).reshape(
            (n * chunk,) + v.shape[1:])

    if mode == "traced":
        out = a2a_local(x)
    else:
        out = _eager_axis_collective(x, ax, lambda v: a2a_local(v[0])[None])
    if out_tensor_list is not None:
        chunk = out.shape[0] // n
        for i in range(n):
            out_tensor_list.append(Tensor(out[i * chunk:(i + 1) * chunk]))
        return out_tensor_list
    return Tensor(out)


alltoall = all_to_all


# ---- eager point-to-point (ProcessGroup::Send/Recv,
# /root/reference/paddle/fluid/distributed/collective/ProcessGroup.h:104,110) ----
#
# TPU-native design: the payload moves DEVICE-to-device through a ppermute
# program compiled over a 2-row submesh containing ONLY the two endpoints'
# devices — uninvolved processes never participate (no world-sized barrier),
# and on a TPU slice the permute rides ICI exactly like the reference's NCCL
# send/recv rides NVLink. Only shape/dtype metadata goes through the
# coordinator KV service (the TCPStore analogue), which is how recv
# "negotiates" when its buffer is not preallocated. Per-(src,dst) sequence
# numbers keep transfers matched; programs on the same endpoint pair must be
# issued in the same order on both processes (SPMD launch-order rule — the
# same constraint NCCL puts on a stream). For bidirectional/neighbor
# exchange use batch_isend_irecv, which fuses all ops into ONE program.

_p2p_seq = {}


def _kv_client():
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client
    except Exception:
        return None


def _p2p_pair_program(src: int, dst: int, shape, dtype_str: str):
    """Compiled single-direction transfer over the {src, dst} pair submesh.

    Cached per (pair, direction, shape, dtype): pipeline loops re-issuing
    same-shape transfers must not pay a retrace per call."""
    return _p2p_program_cached(src, dst, tuple(shape), dtype_str)


@_functools.lru_cache(maxsize=256)
def _p2p_program_cached(src, dst, shape, dtype_str):
    from ..core.jax_compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    # one device per endpoint process (rank = process; a multi-chip host
    # stages its payload on its first device — a local D2D move at most)
    def first_dev(proc):
        return min((d for d in jax.devices() if d.process_index == proc),
                   key=lambda d: d.id)

    mesh = jax.sharding.Mesh(np.array([first_dev(src), first_dev(dst)]),
                             ("pair",))
    sharding = NamedSharding(mesh, P("pair"))

    def f(v):  # v: [1, *shape] — this endpoint's row; src=pair-index 0
        moved = jax.lax.ppermute(v, "pair", [(0, 1)])
        keep = jax.lax.axis_index("pair") == 1
        return jnp.where(keep, moved, v)

    fn = jax.jit(shard_map(f, mesh=mesh, in_specs=(P("pair"),),
                           out_specs=P("pair"), check_vma=False))
    return fn, mesh, sharding


def _p2p_local_row(x, sharding):
    """This process's [1, *shape] shard on its endpoint device, avoiding a
    host round-trip when the payload is already a device array."""
    dev = next(d for d in sharding.mesh.devices.flat
               if d.process_index == jax.process_index())
    row = jax.device_put(jnp.asarray(x)[None], jax.sharding.SingleDeviceSharding(dev))
    return row


def _p2p_transfer(x, src: int, dst: int):
    """Run the pair program; returns this process's (post-transfer) row."""
    fn, mesh, sharding = _p2p_pair_program(src, dst, x.shape, str(x.dtype))
    row = _p2p_local_row(x, sharding)
    glob = jax.make_array_from_single_device_arrays(
        (2,) + tuple(x.shape), sharding, [row])
    out = fn(glob)
    shard = out.addressable_shards[0]
    return jnp.asarray(shard.data)[0]


def _p2p_rank_bounds(rank: int, other: int, op: str):
    world = jax.process_count()
    if world <= 1:
        raise ValueError(
            f"{op}: point-to-point needs a multi-process environment "
            f"(init_parallel_env/launch); within one controller move data "
            f"with reshard()/ppermute instead")
    if not 0 <= other < world:
        raise ValueError(f"{op}: peer rank {other} out of range [0, {world})")
    if other == rank:
        raise ValueError(f"{op}: peer rank {other} is this process")


def _p2p_meta_key(src: int, dst: int, seq: int) -> str:
    return f"paddle_tpu_p2p/{src}->{dst}/{seq}"


def _p2p_get_meta(src: int, rank: int, seq: int, timeout_ms: int = 60_000):
    """Blocking metadata fetch; returns None only when no coordinator KV
    service exists. Timeouts and malformed values raise — silently skipping
    negotiation converts shape mismatches into undebuggable hangs."""
    client = _kv_client()
    if client is None:
        return None
    raw = client.blocking_key_value_get(_p2p_meta_key(src, rank, seq),
                                        timeout_ms)
    shape_s, dtype_s = raw.split("|")
    return tuple(int(s) for s in shape_s.split(",") if s), dtype_s


class P2POp:
    """Transfer handle (paddle isend/irecv contract). The SPMD program has
    already synchronized both endpoints by construction, so wait() is a
    no-op; the class also serves as the op descriptor for batch_isend_irecv
    (op="isend"/"irecv")."""

    def __init__(self, op, tensor=None, peer=None, group=None):
        # descriptor form: P2POp(dist.isend | "isend", tensor, peer) — op is
        # a string/callable, never a Tensor (Tensor.__eq__ is elementwise)
        if isinstance(op, str) or callable(op):
            self.op = getattr(op, "__name__", op)
            self.tensor = tensor
            self.peer = peer
            self.group = group
        else:  # completed-handle form: P2POp(result_tensor)
            self.op = "done"
            self.tensor = op

    def wait(self):
        return self.tensor

    def is_completed(self):
        return True


def send(tensor, dst=0, group=None, sync_op=True):
    rank = jax.process_index()
    _p2p_rank_bounds(rank, dst, "send")
    x = tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    seq = _p2p_seq.get((rank, dst), 0) + 1
    client = _kv_client()
    if client is not None:
        client.key_value_set(
            _p2p_meta_key(rank, dst, seq),
            f"{','.join(map(str, x.shape))}|{x.dtype}")
    _p2p_seq[(rank, dst)] = seq  # committed: the transfer WILL be dispatched
    _p2p_transfer(x, rank, dst)
    return tensor


def recv(tensor, src=0, group=None, sync_op=True):
    rank = jax.process_index()
    _p2p_rank_bounds(rank, src, "recv")
    seq = _p2p_seq.get((src, rank), 0) + 1
    meta = _p2p_get_meta(src, rank, seq)  # raises on timeout: seq NOT consumed,
    #                                       a retried recv still matches the sender
    if tensor is None:
        if meta is None:
            raise ValueError(
                "recv: pass a preallocated tensor (metadata negotiation "
                "needs the jax coordinator KV service)")
        local = jnp.zeros(meta[0], dtype=meta[1])
    else:
        local = tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)
        if meta is not None and (tuple(local.shape) != meta[0]
                                 or str(local.dtype) != meta[1]):
            raise ValueError(
                f"recv: buffer {tuple(local.shape)}/{local.dtype} does not "
                f"match sent {meta[0]}/{meta[1]} (negotiated via coordinator)")
    _p2p_seq[(src, rank)] = seq
    got = _p2p_transfer(local, src, rank)
    if isinstance(tensor, Tensor):
        tensor._data = got
        return tensor
    return Tensor(got)


def isend(tensor, dst=0, group=None):
    return P2POp(send(tensor, dst, group))


def irecv(tensor, src=0, group=None):
    return P2POp(recv(tensor, src, group))


_p2p_batch_counter = [0]


def batch_isend_irecv(p2p_op_list):
    """Fuse P2POp("isend"/"irecv") descriptors into ONE world collective —
    the reference's batch_isend_irecv (communication/batch_isend_irecv.py).

    Contract (matches the reference's NCCL-group requirement): EVERY process
    in the job calls this at the same point, with its own (possibly empty)
    op list. Each rank publishes its send pairs through the coordinator KV
    service; the union forms one ppermute over a world mesh, so asymmetric
    neighbor topologies (pipeline lines) compile the SAME program on every
    process — per-pair local derivations cannot deadlock-by-disagreement.
    Limits: at most one isend and one irecv per rank per batch (one mesh
    row each way), all tensors one shape/dtype.
    """
    from ..core.jax_compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    rank = jax.process_index()
    world = jax.process_count()
    if world <= 1:
        raise ValueError("batch_isend_irecv: needs a multi-process "
                         "environment (init_parallel_env/launch)")
    sends = [op for op in p2p_op_list if op.op == "isend"]
    recvs = [op for op in p2p_op_list if op.op == "irecv"]
    if len(sends) + len(recvs) != len(p2p_op_list):
        bad = [op.op for op in p2p_op_list
               if op.op not in ("isend", "irecv")]
        raise ValueError(f"batch_isend_irecv: bad op(s) {bad!r}")
    if len(sends) > 1 or len(recvs) > 1:
        raise ValueError(
            "batch_isend_irecv: at most one isend and one irecv per rank "
            "per batch (one ppermute row each way); split into several "
            "batches for multi-peer fan-out")
    for op in sends + recvs:
        _p2p_rank_bounds(rank, op.peer, "batch_isend_irecv")

    client = _kv_client()
    if client is None:
        raise RuntimeError(
            "batch_isend_irecv: the jax coordinator KV service is required "
            "to agree on the global pair list")
    # every process calls every batch, so a local counter is globally
    # consistent — it names this batch's KV namespace
    _p2p_batch_counter[0] += 1
    bidx = _p2p_batch_counter[0]
    my_pair = f"{rank}->{sends[0].peer}" if sends else ""
    client.key_value_set(f"paddle_tpu_p2p_batch/{bidx}/{rank}", my_pair)
    perm = set()
    for r in range(world):
        raw = client.blocking_key_value_get(
            f"paddle_tpu_p2p_batch/{bidx}/{r}", 60_000)
        if raw:
            a, b = raw.split("->")
            perm.add((int(a), int(b)))
    perm = sorted(perm)

    # payload prototype: my tensors, else negotiated from any sender's
    # metadata (all tensors in a batch share shape/dtype)
    protos = [op.tensor._data if isinstance(op.tensor, Tensor)
              else jnp.asarray(op.tensor) for op in sends + recvs]
    if any(p.shape != protos[0].shape or p.dtype != protos[0].dtype
           for p in protos):
        raise ValueError("batch_isend_irecv: all tensors must share one "
                         "shape/dtype in a batch")

    def first_dev(proc):
        return min((d for d in jax.devices() if d.process_index == proc),
                   key=lambda d: d.id)

    mesh = jax.sharding.Mesh(np.array([first_dev(r) for r in range(world)]),
                             ("p",))
    sharding = NamedSharding(mesh, P("p"))
    if protos:
        shape, dtype = tuple(protos[0].shape), protos[0].dtype
    else:  # pure bystander: learn the payload shape from any sender
        if not perm:
            return []
        src0 = perm[0][0]
        seqs = client.blocking_key_value_get(
            f"paddle_tpu_p2p_batch_meta/{bidx}/{src0}", 60_000)
        shape_s, dtype_s = seqs.split("|")
        shape = tuple(int(s) for s in shape_s.split(",") if s)
        dtype = dtype_s
    if sends:
        client.key_value_set(
            f"paddle_tpu_p2p_batch_meta/{bidx}/{rank}",
            f"{','.join(map(str, protos[0].shape))}|{protos[0].dtype}")
    if recvs:
        if (recvs[0].peer, rank) not in perm:
            raise ValueError(
                f"batch_isend_irecv: irecv from {recvs[0].peer} has no "
                f"matching isend in this batch (pairs: {perm})")
        raw = client.blocking_key_value_get(
            f"paddle_tpu_p2p_batch_meta/{bidx}/{recvs[0].peer}", 60_000)
        shape_s, dtype_s = raw.split("|")
        sent = (tuple(int(s) for s in shape_s.split(",") if s), dtype_s)
        if tuple(shape) != sent[0] or str(dtype) != sent[1]:
            raise ValueError(
                f"batch_isend_irecv: recv buffer {tuple(shape)}/{dtype} "
                f"does not match sent {sent[0]}/{sent[1]}")
    local = (sends[0].tensor._data if sends and isinstance(sends[0].tensor,
                                                           Tensor)
             else jnp.asarray(sends[0].tensor) if sends
             else jnp.zeros(shape, dtype))
    row = jax.device_put(jnp.asarray(local)[None],
                         jax.sharding.SingleDeviceSharding(first_dev(rank)))
    glob = jax.make_array_from_single_device_arrays(
        (world,) + tuple(shape), sharding, [row])

    def f(v):
        return jax.lax.ppermute(v, "p", perm)

    out = shard_map(f, mesh=mesh, in_specs=(P("p"),), out_specs=P("p"),
                    check_vma=False)(glob)
    my_row = jnp.asarray(out.addressable_shards[0].data)[0]
    results = []
    for op in p2p_op_list:
        if op.op == "irecv":
            if isinstance(op.tensor, Tensor):
                op.tensor._data = my_row
                results.append(P2POp(op.tensor))
            else:  # raw-array buffer: hand back the received Tensor
                results.append(P2POp(Tensor(my_row)))
        else:
            results.append(P2POp(op.tensor))
    return results


def barrier(group=None):
    # single-controller: all local devices are driven by this process; only
    # multi-host needs an actual sync
    import jax as _j

    try:
        from jax.experimental import multihost_utils

        if _j.process_count() > 1:
            multihost_utils.sync_global_devices("paddle_tpu_barrier")
    except Exception:
        pass


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        tensor._data.block_until_ready()
    return tensor


# ---- traced-mode helpers used by meta_parallel layers ----

def p_split(x, axis_name: str, dim: int):
    """c_split analogue: take this shard's slice along `dim` (traced mode)."""
    idx = jax.lax.axis_index(axis_name)
    hcg = get_hybrid_communicate_group()
    n = hcg.degrees[axis_name]
    size = x.shape[dim] // n
    return jax.lax.dynamic_slice_in_dim(x, idx * size, size, axis=dim)


def p_concat(x, axis_name: str, dim: int):
    """c_concat analogue: all_gather along `dim` (traced mode)."""
    return jax.lax.all_gather(x, axis_name, axis=dim, tiled=True)

"""PSServer/PSClient ctypes bindings over core/native/ps_table.cc.

Reference: PSClient::PullSparse/PushSparse (ps/service/ps_client.h:128+),
BrpcPsServer (ps/service/brpc_ps_server.cc). The client fans requests out across
all server instances (ids partitioned by id % n_servers; dense tables replicated
config-wise but each lives on server `table_id % n_servers`).
"""
from __future__ import annotations

import ctypes
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from ...core.native import load_library

_OPTS = {"sgd": 0, "adagrad": 1, "adam": 2}


def _lib():
    lib = load_library("ps_table")
    if lib is None:
        raise RuntimeError("parameter server requires the native ps_table library "
                           "(g++ not available)")
    lib.ps_server_start.restype = ctypes.c_void_p
    lib.ps_server_start.argtypes = [ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
    lib.ps_server_add_sparse_table.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int, ctypes.c_int,
        ctypes.c_float, ctypes.c_float, ctypes.c_int]
    lib.ps_server_add_dense_table.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int, ctypes.c_int,
        ctypes.c_float]
    lib.ps_server_sparse_size.restype = ctypes.c_int64
    lib.ps_server_sparse_size.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.ps_server_stop.argtypes = [ctypes.c_void_p]
    lib.ps_server_stop_requested.restype = ctypes.c_int
    lib.ps_server_stop_requested.argtypes = [ctypes.c_void_p]
    lib.ps_client_connect.restype = ctypes.c_void_p
    lib.ps_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.ps_client_free.argtypes = [ctypes.c_void_p]
    for name, argtypes in [
        ("ps_pull_sparse", [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
                            ctypes.c_int, ctypes.c_void_p, ctypes.c_int]),
        ("ps_push_sparse", [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
                            ctypes.c_int, ctypes.c_void_p, ctypes.c_int]),
        ("ps_pull_dense", [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
                           ctypes.c_int]),
        ("ps_push_dense", [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
                           ctypes.c_int]),
        ("ps_push_dense_param", [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
                                 ctypes.c_int]),
        ("ps_save", [ctypes.c_void_p, ctypes.c_char_p]),
        ("ps_load", [ctypes.c_void_p, ctypes.c_char_p]),
        ("ps_barrier", [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int]),
        ("ps_stop_server", [ctypes.c_void_p]),
    ]:
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int
        fn.argtypes = argtypes
    return lib


@dataclass
class SparseTableConfig:
    table_id: int
    dim: int
    optimizer: str = "sgd"     # server-side sparse SGD rule (reference sparse_sgd_rule.cc)
    learning_rate: float = 0.01
    initial_range: float = 0.1
    shard_num: int = 8


@dataclass
class DenseTableConfig:
    table_id: int
    dim: int
    optimizer: str = "sgd"
    learning_rate: float = 0.01


class PSServer:
    """One PS server instance hosting its shard of every configured table."""

    def __init__(self, port: int = 0,
                 sparse_tables: Sequence[SparseTableConfig] = (),
                 dense_tables: Sequence[DenseTableConfig] = ()):
        self._lib = _lib()
        got = ctypes.c_int(0)
        self._handle = self._lib.ps_server_start(port, ctypes.byref(got))
        if not self._handle:
            raise RuntimeError(f"PSServer: cannot bind port {port}")
        self.port = got.value
        for t in sparse_tables:
            self.add_sparse_table(t)
        for t in dense_tables:
            self.add_dense_table(t)

    def add_sparse_table(self, cfg: SparseTableConfig):
        self._lib.ps_server_add_sparse_table(
            self._handle, cfg.table_id, cfg.dim, _OPTS[cfg.optimizer],
            cfg.learning_rate, cfg.initial_range, cfg.shard_num)

    def add_dense_table(self, cfg: DenseTableConfig):
        self._lib.ps_server_add_dense_table(
            self._handle, cfg.table_id, cfg.dim, _OPTS[cfg.optimizer],
            cfg.learning_rate)

    def sparse_size(self, table_id: int) -> int:
        return int(self._lib.ps_server_sparse_size(self._handle, table_id))

    def stop_requested(self) -> bool:
        """True once a client sent the stop command (fleet.stop_worker)."""
        return bool(self._handle and
                    self._lib.ps_server_stop_requested(self._handle))

    def stop(self):
        if self._handle:
            self._lib.ps_server_stop(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class PSClient:
    """Client fanning out over all servers; ids partitioned by id % n_servers."""

    def __init__(self, endpoints: List[str], timeout: float = 60.0):
        self._lib = _lib()
        self._conns = []
        for ep in endpoints:
            host, port = ep.rsplit(":", 1)
            h = self._lib.ps_client_connect(host.encode(), int(port),
                                            int(timeout * 1000))
            if not h:
                raise TimeoutError(f"PSClient: cannot connect to {ep}")
            self._conns.append(h)
        self.n_servers = len(self._conns)
        self._dims: Dict[int, int] = {}

    def register_table_dim(self, table_id: int, dim: int):
        self._dims[table_id] = dim

    def _dim(self, table_id: int, dim: Optional[int]) -> int:
        d = dim or self._dims.get(table_id)
        assert d, f"dim unknown for table {table_id}; call register_table_dim"
        return d

    # ---- sparse (reference ps_client.h PullSparse/PushSparse) ----
    def pull_sparse(self, table_id: int, ids: np.ndarray,
                    dim: Optional[int] = None) -> np.ndarray:
        d = self._dim(table_id, dim)
        flat = np.ascontiguousarray(ids, dtype=np.uint64).reshape(-1)
        out = np.empty((flat.size, d), dtype=np.float32)
        for s in range(self.n_servers):
            mask = (flat % self.n_servers) == s
            if not mask.any():
                continue
            sub = np.ascontiguousarray(flat[mask])
            rows = np.empty((sub.size, d), dtype=np.float32)
            rc = self._lib.ps_pull_sparse(
                self._conns[s], table_id, sub.ctypes.data, sub.size,
                rows.ctypes.data, d)
            if rc != 0:
                raise RuntimeError(f"pull_sparse(table={table_id}) rc={rc}")
            out[mask] = rows
        return out.reshape(*ids.shape, d)

    def push_sparse(self, table_id: int, ids: np.ndarray, grads: np.ndarray,
                    dim: Optional[int] = None) -> None:
        d = self._dim(table_id, dim)
        flat = np.ascontiguousarray(ids, dtype=np.uint64).reshape(-1)
        g = np.ascontiguousarray(grads, dtype=np.float32).reshape(flat.size, d)
        for s in range(self.n_servers):
            mask = (flat % self.n_servers) == s
            if not mask.any():
                continue
            sub = np.ascontiguousarray(flat[mask])
            gsub = np.ascontiguousarray(g[mask])
            rc = self._lib.ps_push_sparse(
                self._conns[s], table_id, sub.ctypes.data, sub.size,
                gsub.ctypes.data, d)
            if rc != 0:
                raise RuntimeError(f"push_sparse(table={table_id}) rc={rc}")

    # ---- dense: table lives on server table_id % n ----
    def _dense_conn(self, table_id: int):
        return self._conns[table_id % self.n_servers]

    def pull_dense(self, table_id: int, dim: Optional[int] = None) -> np.ndarray:
        d = self._dim(table_id, dim)
        out = np.empty(d, dtype=np.float32)
        rc = self._lib.ps_pull_dense(self._dense_conn(table_id), table_id,
                                     out.ctypes.data, d)
        if rc != 0:
            raise RuntimeError(f"pull_dense(table={table_id}) rc={rc}")
        return out

    def push_dense(self, table_id: int, grads: np.ndarray) -> None:
        g = np.ascontiguousarray(grads, dtype=np.float32).reshape(-1)
        rc = self._lib.ps_push_dense(self._dense_conn(table_id), table_id,
                                     g.ctypes.data, g.size)
        if rc != 0:
            raise RuntimeError(f"push_dense(table={table_id}) rc={rc}")

    def push_dense_param(self, table_id: int, values: np.ndarray) -> None:
        v = np.ascontiguousarray(values, dtype=np.float32).reshape(-1)
        rc = self._lib.ps_push_dense_param(self._dense_conn(table_id), table_id,
                                           v.ctypes.data, v.size)
        if rc != 0:
            raise RuntimeError(f"push_dense_param(table={table_id}) rc={rc}")

    # ---- control ----
    def save(self, path: str) -> None:
        for s, conn in enumerate(self._conns):
            rc = self._lib.ps_save(conn, f"{path}.part{s}".encode())
            if rc != 0:
                raise RuntimeError(f"save rc={rc}")

    def load(self, path: str) -> None:
        for s, conn in enumerate(self._conns):
            rc = self._lib.ps_load(conn, f"{path}.part{s}".encode())
            if rc != 0:
                raise RuntimeError(f"load rc={rc}")

    def barrier(self, generation: int, world: int) -> None:
        rc = self._lib.ps_barrier(self._conns[0], generation, world)
        if rc != 0:
            raise RuntimeError(f"barrier rc={rc}")

    def stop_servers(self) -> None:
        for conn in self._conns:
            self._lib.ps_stop_server(conn)

    def close(self):
        for conn in self._conns:
            self._lib.ps_client_free(conn)
        self._conns = []

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

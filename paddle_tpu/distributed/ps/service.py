"""PSServer/PSClient ctypes bindings over core/native/ps_table.cc.

Reference: PSClient::PullSparse/PushSparse (ps/service/ps_client.h:128+),
BrpcPsServer (ps/service/brpc_ps_server.cc). The client fans requests out across
all server instances (ids partitioned by id % n_servers; dense tables replicated
config-wise but each lives on server `table_id % n_servers`).
"""
from __future__ import annotations

import ctypes
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np

from ...core.native import load_library

_OPTS = {"sgd": 0, "adagrad": 1, "adam": 2}


def _lib():
    lib = load_library("ps_table")
    if lib is None:
        raise RuntimeError("parameter server requires the native ps_table library "
                           "(g++ not available)")
    lib.ps_server_start.restype = ctypes.c_void_p
    lib.ps_server_start.argtypes = [ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
    lib.ps_server_add_sparse_table.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int, ctypes.c_int,
        ctypes.c_float, ctypes.c_float, ctypes.c_int]
    lib.ps_server_add_dense_table.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int, ctypes.c_int,
        ctypes.c_float]
    lib.ps_server_add_graph_table.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int, ctypes.c_int]
    lib.ps_server_sparse_size.restype = ctypes.c_int64
    lib.ps_server_sparse_size.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.ps_server_stop.argtypes = [ctypes.c_void_p]
    lib.ps_server_stop_requested.restype = ctypes.c_int
    lib.ps_server_stop_requested.argtypes = [ctypes.c_void_p]
    lib.ps_client_connect.restype = ctypes.c_void_p
    lib.ps_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.ps_client_free.argtypes = [ctypes.c_void_p]
    for name, argtypes in [
        ("ps_pull_sparse", [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
                            ctypes.c_int, ctypes.c_void_p, ctypes.c_int]),
        ("ps_push_sparse", [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
                            ctypes.c_int, ctypes.c_void_p, ctypes.c_int]),
        ("ps_pull_dense", [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
                           ctypes.c_int]),
        ("ps_push_dense", [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
                           ctypes.c_int]),
        ("ps_push_dense_param", [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
                                 ctypes.c_int]),
        ("ps_push_dense_delta", [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
                                 ctypes.c_int]),
        ("ps_push_sparse_delta", [ctypes.c_void_p, ctypes.c_uint32,
                                  ctypes.c_void_p, ctypes.c_int,
                                  ctypes.c_void_p, ctypes.c_int]),
        ("ps_graph_add_edges", [ctypes.c_void_p, ctypes.c_uint32,
                                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int]),
        ("ps_graph_degree", [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
                             ctypes.c_int, ctypes.c_void_p]),
        ("ps_graph_sample", [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_void_p,
                             ctypes.c_int, ctypes.c_int, ctypes.c_uint32,
                             ctypes.c_void_p]),
        ("ps_graph_set_feat", [ctypes.c_void_p, ctypes.c_uint32,
                               ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
                               ctypes.c_int]),
        ("ps_graph_get_feat", [ctypes.c_void_p, ctypes.c_uint32,
                               ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p,
                               ctypes.c_int]),
        ("ps_save", [ctypes.c_void_p, ctypes.c_char_p]),
        ("ps_load", [ctypes.c_void_p, ctypes.c_char_p]),
        ("ps_barrier", [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int]),
        ("ps_stop_server", [ctypes.c_void_p]),
    ]:
        fn = getattr(lib, name)
        fn.restype = ctypes.c_int
        fn.argtypes = argtypes
    return lib


@dataclass
class SparseTableConfig:
    table_id: int
    dim: int
    optimizer: str = "sgd"     # server-side sparse SGD rule (reference sparse_sgd_rule.cc)
    learning_rate: float = 0.01
    initial_range: float = 0.1
    shard_num: int = 8


@dataclass
class DenseTableConfig:
    table_id: int
    dim: int
    optimizer: str = "sgd"
    learning_rate: float = 0.01


@dataclass
class GraphTableConfig:
    """GNN graph store (reference common_graph_table.cc): id-sharded
    adjacency + per-node features behind the PS wire protocol."""
    table_id: int
    feat_dim: int = 0
    shard_num: int = 8


class PSServer:
    """One PS server instance hosting its shard of every configured table."""

    def __init__(self, port: int = 0,
                 sparse_tables: Sequence[SparseTableConfig] = (),
                 dense_tables: Sequence[DenseTableConfig] = (),
                 graph_tables: Sequence[GraphTableConfig] = ()):
        self._lib = _lib()
        got = ctypes.c_int(0)
        self._handle = self._lib.ps_server_start(port, ctypes.byref(got))
        if not self._handle:
            raise RuntimeError(f"PSServer: cannot bind port {port}")
        self.port = got.value
        for t in sparse_tables:
            self.add_sparse_table(t)
        for t in dense_tables:
            self.add_dense_table(t)
        for t in graph_tables:
            self.add_graph_table(t)

    def add_sparse_table(self, cfg: SparseTableConfig):
        self._lib.ps_server_add_sparse_table(
            self._handle, cfg.table_id, cfg.dim, _OPTS[cfg.optimizer],
            cfg.learning_rate, cfg.initial_range, cfg.shard_num)

    def add_dense_table(self, cfg: DenseTableConfig):
        self._lib.ps_server_add_dense_table(
            self._handle, cfg.table_id, cfg.dim, _OPTS[cfg.optimizer],
            cfg.learning_rate)

    def add_graph_table(self, cfg: GraphTableConfig):
        self._lib.ps_server_add_graph_table(
            self._handle, cfg.table_id, cfg.feat_dim, cfg.shard_num)

    def sparse_size(self, table_id: int) -> int:
        return int(self._lib.ps_server_sparse_size(self._handle, table_id))

    def stop_requested(self) -> bool:
        """True once a client sent the stop command (fleet.stop_worker)."""
        return bool(self._handle and
                    self._lib.ps_server_stop_requested(self._handle))

    def stop(self):
        if self._handle:
            self._lib.ps_server_stop(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class PSClient:
    """Client fanning out over all servers; ids partitioned by id % n_servers."""

    def __init__(self, endpoints: List[str], timeout: float = 60.0):
        self._lib = _lib()
        self._conns = []
        for ep in endpoints:
            host, port = ep.rsplit(":", 1)
            h = self._lib.ps_client_connect(host.encode(), int(port),
                                            int(timeout * 1000))
            if not h:
                raise TimeoutError(f"PSClient: cannot connect to {ep}")
            self._conns.append(h)
        self.n_servers = len(self._conns)
        self._dims: Dict[int, int] = {}

    def register_table_dim(self, table_id: int, dim: int):
        self._dims[table_id] = dim

    def _dim(self, table_id: int, dim: Optional[int]) -> int:
        d = dim or self._dims.get(table_id)
        assert d, f"dim unknown for table {table_id}; call register_table_dim"
        return d

    def _shards(self, ids: np.ndarray):
        """Route ids to their owning server (the ONE partitioning rule:
        id % n_servers). Yields (server_conn, mask, contiguous_ids)."""
        flat = np.ascontiguousarray(ids, dtype=np.uint64).reshape(-1)
        for s in range(self.n_servers):
            mask = (flat % self.n_servers) == s
            if mask.any():
                yield self._conns[s], mask, np.ascontiguousarray(flat[mask])

    # ---- sparse (reference ps_client.h PullSparse/PushSparse) ----
    def pull_sparse(self, table_id: int, ids: np.ndarray,
                    dim: Optional[int] = None) -> np.ndarray:
        d = self._dim(table_id, dim)
        n = int(np.asarray(ids).size)
        out = np.empty((n, d), dtype=np.float32)
        for conn, mask, sub in self._shards(ids):
            rows = np.empty((sub.size, d), dtype=np.float32)
            rc = self._lib.ps_pull_sparse(conn, table_id, sub.ctypes.data,
                                          sub.size, rows.ctypes.data, d)
            if rc != 0:
                raise RuntimeError(f"pull_sparse(table={table_id}) rc={rc}")
            out[mask] = rows
        return out.reshape(*np.asarray(ids).shape, d)

    def push_sparse(self, table_id: int, ids: np.ndarray, grads: np.ndarray,
                    dim: Optional[int] = None) -> None:
        d = self._dim(table_id, dim)
        n = int(np.asarray(ids).size)
        g = np.ascontiguousarray(grads, dtype=np.float32).reshape(n, d)
        for conn, mask, sub in self._shards(ids):
            gsub = np.ascontiguousarray(g[mask])
            rc = self._lib.ps_push_sparse(conn, table_id, sub.ctypes.data,
                                          sub.size, gsub.ctypes.data, d)
            if rc != 0:
                raise RuntimeError(f"push_sparse(table={table_id}) rc={rc}")

    # ---- dense: table lives on server table_id % n ----
    def _dense_conn(self, table_id: int):
        return self._conns[table_id % self.n_servers]

    def pull_dense(self, table_id: int, dim: Optional[int] = None) -> np.ndarray:
        d = self._dim(table_id, dim)
        out = np.empty(d, dtype=np.float32)
        rc = self._lib.ps_pull_dense(self._dense_conn(table_id), table_id,
                                     out.ctypes.data, d)
        if rc != 0:
            raise RuntimeError(f"pull_dense(table={table_id}) rc={rc}")
        return out

    def push_dense(self, table_id: int, grads: np.ndarray) -> None:
        g = np.ascontiguousarray(grads, dtype=np.float32).reshape(-1)
        rc = self._lib.ps_push_dense(self._dense_conn(table_id), table_id,
                                     g.ctypes.data, g.size)
        if rc != 0:
            raise RuntimeError(f"push_dense(table={table_id}) rc={rc}")

    def push_dense_param(self, table_id: int, values: np.ndarray) -> None:
        v = np.ascontiguousarray(values, dtype=np.float32).reshape(-1)
        rc = self._lib.ps_push_dense_param(self._dense_conn(table_id), table_id,
                                           v.ctypes.data, v.size)
        if rc != 0:
            raise RuntimeError(f"push_dense_param(table={table_id}) rc={rc}")

    # ---- geo-SGD deltas (reference memory_sparse_geo_table.cc): the server
    # ADDS trainer deltas; aggregation across trainers is the sum ----
    def push_dense_delta(self, table_id: int, delta: np.ndarray) -> None:
        v = np.ascontiguousarray(delta, dtype=np.float32).reshape(-1)
        rc = self._lib.ps_push_dense_delta(self._dense_conn(table_id), table_id,
                                           v.ctypes.data, v.size)
        if rc != 0:
            raise RuntimeError(f"push_dense_delta(table={table_id}) rc={rc}")

    def push_sparse_delta(self, table_id: int, ids: np.ndarray,
                          deltas: np.ndarray,
                          dim: Optional[int] = None) -> None:
        d = self._dim(table_id, dim)
        n = int(np.asarray(ids).size)
        g = np.ascontiguousarray(deltas, dtype=np.float32).reshape(n, d)
        for conn, mask, sub in self._shards(ids):
            gsub = np.ascontiguousarray(g[mask])
            rc = self._lib.ps_push_sparse_delta(conn, table_id,
                                                sub.ctypes.data, sub.size,
                                                gsub.ctypes.data, d)
            if rc != 0:
                raise RuntimeError(
                    f"push_sparse_delta(table={table_id}) rc={rc}")

    # ---- graph (reference common_graph_table.cc): nodes shard by id ----
    def graph_add_edges(self, table_id: int, src: np.ndarray,
                        dst: np.ndarray) -> None:
        d_flat = np.ascontiguousarray(dst, dtype=np.uint64).reshape(-1)
        assert np.asarray(src).size == d_flat.size
        for conn, mask, ss in self._shards(src):  # edges live with their src
            dd = np.ascontiguousarray(d_flat[mask])
            rc = self._lib.ps_graph_add_edges(conn, table_id, ss.ctypes.data,
                                              dd.ctypes.data, ss.size)
            if rc != 0:
                raise RuntimeError(f"graph_add_edges rc={rc}")

    def graph_degree(self, table_id: int, ids: np.ndarray) -> np.ndarray:
        out = np.zeros(int(np.asarray(ids).size), dtype=np.int64)
        for conn, mask, sub in self._shards(ids):
            deg = np.empty(sub.size, dtype=np.int64)
            rc = self._lib.ps_graph_degree(conn, table_id, sub.ctypes.data,
                                           sub.size, deg.ctypes.data)
            if rc != 0:
                raise RuntimeError(f"graph_degree rc={rc}")
            out[mask] = deg
        return out.reshape(np.asarray(ids).shape)

    def graph_sample_neighbors(self, table_id: int, ids: np.ndarray, k: int,
                               seed: int = 0) -> np.ndarray:
        """k uniform samples (with replacement) per id; UINT64_MAX marks
        neighborless nodes."""
        out = np.full((int(np.asarray(ids).size), k),
                      np.iinfo(np.uint64).max, dtype=np.uint64)
        for conn, mask, sub in self._shards(ids):
            smp = np.empty((sub.size, k), dtype=np.uint64)
            rc = self._lib.ps_graph_sample(conn, table_id, sub.ctypes.data,
                                           sub.size, k, seed & 0xFFFFFFFF,
                                           smp.ctypes.data)
            if rc != 0:
                raise RuntimeError(f"graph_sample rc={rc}")
            out[mask] = smp
        return out.reshape(*np.asarray(ids).shape, k)

    def graph_set_feat(self, table_id: int, ids: np.ndarray,
                       feats: np.ndarray, dim: Optional[int] = None) -> None:
        d = self._dim(table_id, dim)
        f = np.ascontiguousarray(feats, dtype=np.float32).reshape(
            int(np.asarray(ids).size), d)
        for conn, mask, sub in self._shards(ids):
            fsub = np.ascontiguousarray(f[mask])
            rc = self._lib.ps_graph_set_feat(conn, table_id, sub.ctypes.data,
                                             sub.size, fsub.ctypes.data, d)
            if rc != 0:
                raise RuntimeError(f"graph_set_feat rc={rc}")

    def graph_get_feat(self, table_id: int, ids: np.ndarray,
                       dim: Optional[int] = None) -> np.ndarray:
        d = self._dim(table_id, dim)
        out = np.zeros((int(np.asarray(ids).size), d), dtype=np.float32)
        for conn, mask, sub in self._shards(ids):
            rows = np.empty((sub.size, d), dtype=np.float32)
            rc = self._lib.ps_graph_get_feat(conn, table_id, sub.ctypes.data,
                                             sub.size, rows.ctypes.data, d)
            if rc != 0:
                raise RuntimeError(f"graph_get_feat rc={rc}")
            out[mask] = rows
        return out.reshape(*np.asarray(ids).shape, d)

    # ---- control ----
    def save(self, path: str) -> None:
        for s, conn in enumerate(self._conns):
            rc = self._lib.ps_save(conn, f"{path}.part{s}".encode())
            if rc != 0:
                raise RuntimeError(f"save rc={rc}")

    def load(self, path: str) -> None:
        for s, conn in enumerate(self._conns):
            rc = self._lib.ps_load(conn, f"{path}.part{s}".encode())
            if rc != 0:
                raise RuntimeError(f"load rc={rc}")

    def barrier(self, generation: int, world: int) -> None:
        rc = self._lib.ps_barrier(self._conns[0], generation, world)
        if rc != 0:
            raise RuntimeError(f"barrier rc={rc}")

    def stop_servers(self) -> None:
        for conn in self._conns:
            self._lib.ps_stop_server(conn)

    def close(self):
        for conn in self._conns:
            self._lib.ps_client_free(conn)
        self._conns = []

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

"""TheOnePSRuntime: role-aware PS bootstrap facade.

Reference: python/paddle/distributed/ps/the_one_ps.py:816 — _init_server builds
C++ tables from the program's table configs (:1049), _init_worker creates the
brpc client (:903), run_server blocks, stop_worker tears down, barriers keep
sync-mode trainers aligned. Env contract comes from the launcher's PS controller
(TRAINING_ROLE / PADDLE_PSERVERS_IP_PORT_LIST / PADDLE_PORT / PADDLE_PSERVER_ID,
launch/main.py ps mode).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .service import DenseTableConfig, PSClient, PSServer, SparseTableConfig


class TheOnePSRuntime:
    def __init__(self, sparse_tables: Sequence[SparseTableConfig] = (),
                 dense_tables: Sequence[DenseTableConfig] = ()):
        self.sparse_tables = list(sparse_tables)
        self.dense_tables = list(dense_tables)
        self.role = os.environ.get("TRAINING_ROLE", "TRAINER")
        self.server_endpoints = [e for e in os.environ.get(
            "PADDLE_PSERVERS_IP_PORT_LIST", "").split(",") if e]
        self.trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._server: Optional[PSServer] = None
        self._client: Optional[PSClient] = None
        self._stop_evt = threading.Event()

    def is_server(self) -> bool:
        return self.role == "PSERVER"

    def is_worker(self) -> bool:
        return not self.is_server()

    # ---- server side (the_one_ps.py:1049 _init_server) ----
    def init_server(self) -> PSServer:
        port = int(os.environ.get("PADDLE_PORT", "0"))
        self._server = PSServer(port, self.sparse_tables, self.dense_tables)
        return self._server

    def run_server(self) -> None:
        """Block serving until a client sends stop (reference fleet.run_server)."""
        assert self._server is not None, "call init_server() first"
        while not self._server.stop_requested() and not self._stop_evt.wait(0.2):
            pass
        self._server.stop()

    # ---- worker side (the_one_ps.py:903 _init_worker) ----
    def init_worker(self, model=None) -> PSClient:
        assert self.server_endpoints, \
            "PADDLE_PSERVERS_IP_PORT_LIST is empty — launch with --run_mode ps"
        self._client = PSClient(self.server_endpoints)
        for t in self.sparse_tables + self.dense_tables:
            self._client.register_table_dim(t.table_id, t.dim)
        if model is not None:
            self.bind_model(model)
        return self._client

    def bind_model(self, model) -> None:
        """Wire every DistributedEmbedding sublayer to the PS client."""
        from .layers import DistributedEmbedding

        for layer in model.sublayers(include_self=True):
            if isinstance(layer, DistributedEmbedding):
                layer.set_client(self._client)

    def barrier_worker(self, generation: int = 0) -> None:
        if self._client is not None and self.trainers_num > 1:
            self._client.barrier(generation, self.trainers_num)

    def stop_worker(self) -> None:
        if self._client is not None and self.trainer_id == 0:
            self._client.stop_servers()

    # ---- persistence (fleet.save_persistables -> table dump, the_one_ps.py) ----
    def save_persistables(self, path: str) -> None:
        assert self._client is not None
        self._client.save(path)

    def load_persistables(self, path: str) -> None:
        assert self._client is not None
        self._client.load(path)


class DenseSync:
    """Async/sync dense-parameter flow for PS training: trainer pushes dense
    grads to the server-side optimizer and pulls fresh params back (reference
    Communicator send/recv threads, ps/service/communicator/). `pull_interval`
    > 1 approximates geo-async: params refresh every k steps."""

    def __init__(self, client: PSClient, params: Dict[int, "object"],
                 pull_interval: int = 1):
        # params: table_id -> Parameter tensor (trainer-side mirror)
        self.client = client
        self.params = params
        self.pull_interval = pull_interval
        self._step = 0
        for tid, p in params.items():
            self.client.register_table_dim(tid, int(np.prod(p.shape)))
            self.client.push_dense_param(tid, p.numpy().reshape(-1))

    def step(self) -> None:
        """Push this step's dense grads; pull params on the refresh interval."""
        self._step += 1
        for tid, p in self.params.items():
            if p.grad is not None:
                self.client.push_dense(tid, np.asarray(p.grad.numpy()).reshape(-1))
                p.clear_grad() if hasattr(p, "clear_grad") else None
        if self._step % self.pull_interval == 0:
            self.pull()

    def pull(self) -> None:
        from ...core.tensor import Tensor

        for tid, p in self.params.items():
            vals = self.client.pull_dense(tid).reshape(p.shape)
            p._data = Tensor(vals.astype(p.numpy().dtype))._data

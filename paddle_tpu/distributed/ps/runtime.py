"""TheOnePSRuntime: role-aware PS bootstrap facade.

Reference: python/paddle/distributed/ps/the_one_ps.py:816 — _init_server builds
C++ tables from the program's table configs (:1049), _init_worker creates the
brpc client (:903), run_server blocks, stop_worker tears down, barriers keep
sync-mode trainers aligned. Env contract comes from the launcher's PS controller
(TRAINING_ROLE / PADDLE_PSERVERS_IP_PORT_LIST / PADDLE_PORT / PADDLE_PSERVER_ID,
launch/main.py ps mode).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .service import (DenseTableConfig, GraphTableConfig, PSClient, PSServer,
                      SparseTableConfig)


class TheOnePSRuntime:
    def __init__(self, sparse_tables: Sequence[SparseTableConfig] = (),
                 dense_tables: Sequence[DenseTableConfig] = ()):
        self.sparse_tables = list(sparse_tables)
        self.dense_tables = list(dense_tables)
        self.role = os.environ.get("TRAINING_ROLE", "TRAINER")
        self.server_endpoints = [e for e in os.environ.get(
            "PADDLE_PSERVERS_IP_PORT_LIST", "").split(",") if e]
        self.trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._server: Optional[PSServer] = None
        self._client: Optional[PSClient] = None
        self._stop_evt = threading.Event()

    def is_server(self) -> bool:
        return self.role == "PSERVER"

    def is_worker(self) -> bool:
        return not self.is_server()

    # ---- server side (the_one_ps.py:1049 _init_server) ----
    def init_server(self) -> PSServer:
        port = int(os.environ.get("PADDLE_PORT", "0"))
        self._server = PSServer(port, self.sparse_tables, self.dense_tables)
        return self._server

    def run_server(self) -> None:
        """Block serving until a client sends stop (reference fleet.run_server)."""
        assert self._server is not None, "call init_server() first"
        while not self._server.stop_requested() and not self._stop_evt.wait(0.2):
            pass
        self._server.stop()

    # ---- worker side (the_one_ps.py:903 _init_worker) ----
    def init_worker(self, model=None) -> PSClient:
        assert self.server_endpoints, \
            "PADDLE_PSERVERS_IP_PORT_LIST is empty — launch with --run_mode ps"
        self._client = PSClient(self.server_endpoints)
        for t in self.sparse_tables + self.dense_tables:
            self._client.register_table_dim(t.table_id, t.dim)
        if model is not None:
            self.bind_model(model)
        return self._client

    def bind_model(self, model) -> None:
        """Wire every DistributedEmbedding sublayer to the PS client."""
        from .layers import DistributedEmbedding

        for layer in model.sublayers(include_self=True):
            if isinstance(layer, DistributedEmbedding):
                layer.set_client(self._client)

    def barrier_worker(self, generation: int = 0) -> None:
        if self._client is not None and self.trainers_num > 1:
            self._client.barrier(generation, self.trainers_num)

    def stop_worker(self) -> None:
        if self._client is not None and self.trainer_id == 0:
            self._client.stop_servers()

    # ---- persistence (fleet.save_persistables -> table dump, the_one_ps.py) ----
    def save_persistables(self, path: str) -> None:
        assert self._client is not None
        self._client.save(path)

    def load_persistables(self, path: str) -> None:
        assert self._client is not None
        self._client.load(path)


class DenseSync:
    """Async/sync dense-parameter flow for PS training: trainer pushes dense
    grads to the server-side optimizer and pulls fresh params back (reference
    Communicator send/recv threads, ps/service/communicator/). For geo-SGD
    (local training + delta aggregation) use GeoSync below."""

    def __init__(self, client: PSClient, params: Dict[int, "object"],
                 pull_interval: int = 1):
        # params: table_id -> Parameter tensor (trainer-side mirror)
        self.client = client
        self.params = params
        self.pull_interval = pull_interval
        self._step = 0
        for tid, p in params.items():
            self.client.register_table_dim(tid, int(np.prod(p.shape)))
            self.client.push_dense_param(tid, p.numpy().reshape(-1))

    def step(self) -> None:
        """Push this step's dense grads; pull params on the refresh interval."""
        self._step += 1
        for tid, p in self.params.items():
            if p.grad is not None:
                self.client.push_dense(tid, np.asarray(p.grad.numpy()).reshape(-1))
                p.clear_grad() if hasattr(p, "clear_grad") else None
        if self._step % self.pull_interval == 0:
            self.pull()

    def pull(self) -> None:
        from ...core.tensor import Tensor

        for tid, p in self.params.items():
            vals = self.client.pull_dense(tid).reshape(p.shape)
            p._data = Tensor(vals.astype(p.numpy().dtype))._data


class GeoSync:
    """Geo-SGD delta aggregation (reference memory_sparse_geo_table.cc +
    GeoCommunicator): each trainer optimizes LOCALLY; every `push_interval`
    steps it pushes `delta = local - base` to the server, which ADDS deltas
    from all trainers into the global parameter; the trainer then pulls the
    merged value and rebases. Unlike DenseSync's grad-push, the server runs
    no optimizer — aggregation is exact addition of locally-optimized
    movement, which is the geo-SGD algorithm (arXiv:1811.11682).
    """

    def __init__(self, client: PSClient, params: Dict[int, "object"],
                 push_interval: int = 4,
                 init_from_server: Optional[bool] = None):
        # params: table_id -> Parameter tensor (trainer-side, optimizer-owned)
        self.client = client
        self.params = params
        self.push_interval = push_interval
        self._step = 0
        self._base: Dict[int, np.ndarray] = {}
        if init_from_server is None:
            # only rank 0 seeds the server; a later-starting trainer that
            # pushed its init unconditionally would WIPE deltas already
            # aggregated by earlier trainers
            init_from_server = int(os.environ.get("PADDLE_TRAINER_ID",
                                                  "0")) != 0
        for tid, p in params.items():
            self.client.register_table_dim(tid, int(np.prod(p.shape)))
            if init_from_server:
                self._pull_one(tid, p)
            else:
                self.client.push_dense_param(tid, p.numpy().reshape(-1))
            self._base[tid] = np.asarray(p.numpy(), np.float32).copy()

    def step(self) -> None:
        """Call AFTER the local optimizer step."""
        self._step += 1
        if self._step % self.push_interval == 0:
            self.sync()

    def sync(self) -> None:
        for tid, p in self.params.items():
            local = np.asarray(p.numpy(), np.float32)
            delta = (local - self._base[tid]).reshape(-1)
            self.client.push_dense_delta(tid, delta)
            self._pull_one(tid, p)
            self._base[tid] = np.asarray(p.numpy(), np.float32).copy()

    def _pull_one(self, tid, p) -> None:
        from ...core.tensor import Tensor

        vals = self.client.pull_dense(tid).reshape(p.shape)
        p._data = Tensor(vals.astype(p.numpy().dtype))._data


class GraphClient:
    """High-level GNN graph-store API over the PS graph table (reference
    common_graph_table.cc service surface: add edges, sample neighbors,
    node features, degrees)."""

    def __init__(self, client: PSClient, table_id: int, feat_dim: int = 0):
        self.client = client
        self.table_id = table_id
        self.feat_dim = feat_dim
        if feat_dim:
            client.register_table_dim(table_id, feat_dim)

    def add_edges(self, src, dst, bidirectional: bool = False) -> None:
        self.client.graph_add_edges(self.table_id, np.asarray(src),
                                    np.asarray(dst))
        if bidirectional:
            self.client.graph_add_edges(self.table_id, np.asarray(dst),
                                        np.asarray(src))

    def degree(self, ids) -> np.ndarray:
        return self.client.graph_degree(self.table_id, np.asarray(ids))

    def sample_neighbors(self, ids, k: int, seed: int = 0) -> np.ndarray:
        """[*ids.shape, k] uint64; UINT64_MAX marks neighborless nodes."""
        return self.client.graph_sample_neighbors(self.table_id,
                                                  np.asarray(ids), k, seed)

    def set_node_feat(self, ids, feats) -> None:
        self.client.graph_set_feat(self.table_id, np.asarray(ids),
                                   np.asarray(feats), self.feat_dim or None)

    def get_node_feat(self, ids) -> np.ndarray:
        return self.client.graph_get_feat(self.table_id, np.asarray(ids),
                                          self.feat_dim or None)

"""Parameter-server runtime (Python surface over the C++ tables/service).

Reference: paddle/fluid/distributed/ps/ (#24) + python TheOnePSRuntime
(python/paddle/distributed/ps/the_one_ps.py:816, #39). The C++ side lives in
core/native/ps_table.cc: sharded sparse/dense tables with server-side optimizers
behind a TCP service (brpc in the reference). Ids shard across server instances
by `id % num_servers` exactly like the reference's key-hash table partitioning.
"""
from .service import (PSClient, PSServer, SparseTableConfig,
                      DenseTableConfig, GraphTableConfig)
from .runtime import (TheOnePSRuntime, DenseSync, GeoSync, GraphClient)
from .layers import DistributedEmbedding, distributed_lookup_table

__all__ = ["PSClient", "PSServer", "SparseTableConfig", "DenseTableConfig",
           "GraphTableConfig", "TheOnePSRuntime", "DenseSync", "GeoSync",
           "GraphClient", "DistributedEmbedding", "distributed_lookup_table"]

"""PS-backed layers: distributed embedding lookup with push-on-backward.

Reference: operators/pscore/distributed_lookup_table_op.cc (trainer-side op whose
forward pulls rows from the PS and whose grad op pushes row gradients back) and
`paddle.static.nn.sparse_embedding`. TPU-native: the pull happens on host (table
RPC), the dense compute stays on device; the lookup records a custom grad Node
whose vjp aggregates per-id gradients (duplicate ids sum — the reference's
SelectedRows merge-add) and pushes them to the server-side optimizer. The table
is *not* a trainer parameter, so the node has no differentiable inputs; the
backward is a pure side effect, exactly like the reference's push op.
"""
from __future__ import annotations

import numpy as np

from ...core.autograd import Node, is_grad_enabled
from ...core.tensor import Tensor
from ...nn.layer import Layer


def distributed_lookup_table(ids: Tensor, client, table_id: int, dim: int) -> Tensor:
    """Pull embedding rows for `ids` from the PS; gradients push back on backward."""
    ids_np = np.asarray(ids.numpy(), dtype=np.uint64)
    rows = client.pull_sparse(table_id, ids_np, dim).astype(np.float32)
    out = Tensor(rows)
    if is_grad_enabled():
        out_shape, out_dtype = tuple(rows.shape), np.dtype(np.float32)

        def vjp_fn(cotangent):
            g = np.asarray(cotangent, dtype=np.float32)
            flat_ids = ids_np.reshape(-1)
            flat_g = g.reshape(flat_ids.size, dim)
            uniq, inv = np.unique(flat_ids, return_inverse=True)
            merged = np.zeros((uniq.size, dim), dtype=np.float32)
            np.add.at(merged, inv, flat_g)
            client.push_sparse(table_id, uniq, merged, dim)
            return ()  # no differentiable inputs; the push IS the gradient

        out._stop_gradient = False
        out._node = Node(vjp_fn, [], [(out_shape, out_dtype)],
                         name="distributed_lookup_table")
        out._out_index = 0
    return out


class DistributedEmbedding(Layer):
    """Embedding whose table lives on the parameter server (reference
    sparse_embedding); the trainer holds no weights for it."""

    def __init__(self, table_id: int, embedding_dim: int, client=None):
        super().__init__()
        self.table_id = table_id
        self.embedding_dim = embedding_dim
        self._client = client

    def set_client(self, client):
        self._client = client

    def forward(self, ids: Tensor) -> Tensor:
        assert self._client is not None, \
            "DistributedEmbedding needs a PSClient (fleet.init_worker wires it)"
        return distributed_lookup_table(ids, self._client, self.table_id,
                                        self.embedding_dim)

"""Elastic checkpointing: async crash-safe snapshots + cross-mesh restore.

A pod-scale service preempts, resizes, and restores onto different
topologies. This module gives TrainStepEngine a production fault-tolerance
tier in three pieces:

**Async snapshots that overlap training.** ``capture_snapshot`` runs on the
training thread: it issues ``copy_to_host_async`` on every param/opt shard
first (the D2H transfers overlap each other and the in-flight step, the
PR 2 prefetcher pattern turned device-to-host), then materializes owned
host copies — ``np.array(..., copy=True)`` is load-bearing, because a CPU
jax array can alias the device buffer and that buffer is *donated* to the
next dispatch. Serialization, hashing, and fsync then happen on a
background writer thread behind a depth-1 queue (double buffer): at most
one snapshot is in flight, and a save interval that fires while the writer
is busy skips with a ``ckpt.skipped`` count instead of stalling the step.

**Crash-safe commit.** Each checkpoint is written to a hidden
``.tmp.ckpt_<step>.<pid>`` dir: payload ``.npy`` files first (fsync'd),
then ``manifest.json`` LAST — with a sha256 per payload file and a
self-checksum over the manifest body — and the single commit point is
``os.rename(tmp, ckpt_<step>)`` + parent-dir fsync. A kill at ANY byte of
the write leaves either the previous committed checkpoints untouched plus
an ignorable ``.tmp`` dir, or the fully-verified new one; there is no torn
state ``verify_checkpoint`` would accept. Retention GC keeps the newest
``keep`` checkpoints and sweeps ``.tmp`` dirs whose writer pid is dead.

**Cross-mesh restore.** Params are merged from saved shard ranges with the
auto_parallel ``Converter`` and ``device_put`` with the TARGET engine's
shardings — save on dp4×mp2, resume on dp2×mp4; the reshard IS the
device_put (XLA expresses the slice/transfer program). ZeRO flat optimizer
shards (PR 8) restore across a *changed dp degree* without ever
reconstructing the per-param dict: the flat [n_pad] slot vectors are
re-padded for the new replica count and re-sliced by the target
``_residual_sharding`` — and a ZeRO checkpoint restores into a
non-ZeRO engine (and vice versa) by splitting/concatenating at
``health.segment_layout`` offsets.

Opt-in auto-rollback: with ``rollback_on_nonfinite=True`` (or
``FLAGS_ckpt_rollback``) a non-finite loss triggers a flight-recorder dump
and restores the newest valid checkpoint in place of the diverged state.

Counters (core.monitor): ckpt.saves / ckpt.restores / ckpt.bytes /
ckpt.skipped / ckpt.corrupt / ckpt.failures / ckpt.rollbacks /
ckpt.gc_removed. Histograms (when a metrics registry is active):
ckpt.capture_ms (training-thread cost), ckpt.save_ms (background wall),
ckpt.overlap_ms (the async save wall that overlapped training).

Fault-injection hook: ``PADDLE_TPU_CKPT_SLOW_WRITE_MS`` sleeps that long
after each payload file — widens the mid-save kill window for the
kill-and-resume dryrun phase without touching the commit protocol.
"""
from __future__ import annotations

import hashlib
import json
import math
import os
import queue
import shutil
import threading
import time
import warnings
from typing import List, Optional, Tuple

import numpy as np

from ..core import flags as _flags
from ..core import monitor as _monitor
from ..observability import flight_recorder as _obs_flight
from ..observability import metrics as _obs_metrics

SAVES = _monitor.stat("ckpt.saves")
RESTORES = _monitor.stat("ckpt.restores")
BYTES_WRITTEN = _monitor.stat("ckpt.bytes")
SKIPPED = _monitor.stat("ckpt.skipped")
CORRUPT = _monitor.stat("ckpt.corrupt")
FAILURES = _monitor.stat("ckpt.failures")
ROLLBACKS = _monitor.stat("ckpt.rollbacks")
GC_REMOVED = _monitor.stat("ckpt.gc_removed")

FORMAT_VERSION = 1
CKPT_PREFIX = "ckpt_"
TMP_PREFIX = ".tmp."
MANIFEST = "manifest.json"


class CheckpointCorrupt(RuntimeError):
    """A checkpoint directory failed manifest/payload verification."""


# ---------------------------------------------------------------- hashing
def file_sha256(path: str, blocksize: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(blocksize), b""):
            h.update(block)
    return h.hexdigest()


def manifest_digest(manifest: dict) -> str:
    """Self-checksum over the canonical JSON of the manifest body (every
    field except the checksum itself). Canonical = sort_keys, so the digest
    survives a JSON round-trip."""
    body = {k: v for k, v in manifest.items() if k != "manifest_checksum"}
    return hashlib.sha256(
        json.dumps(body, sort_keys=True).encode()).hexdigest()


def _fsync_dir(dirname: str) -> None:
    try:
        fd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass  # some filesystems refuse directory fsync; rename is still atomic
    finally:
        os.close(fd)


# ---------------------------------------------------------------- capture
class Snapshot:
    """Host-owned copy of one training state, safe to hand to a background
    thread: plain numpy only, nothing aliasing device buffers."""

    __slots__ = ("step", "opt_step", "key_words", "key_shape", "params",
                 "opt", "zero", "capture_ms")

    def __init__(self, step, opt_step, key_words, key_shape, params, opt,
                 zero, capture_ms):
        self.step = step
        self.opt_step = opt_step
        self.key_words = key_words
        self.key_shape = key_shape
        self.params = params      # {name: {"shape","dtype","pieces":[(ranges, np)]}}
        self.opt = opt            # same keyed "name.slot", or None
        self.zero = zero          # {"meta": {...}, "pieces": [(slot, off, np)]} or None
        self.capture_ms = capture_ms


def _host_pieces(arr):
    """Dedup'd (ranges, owned-host-array) pieces of one global array.
    Replicated shards save once; np.array(copy=True) detaches from the
    donated device buffer."""
    from .auto_parallel.dist_saver import _index_to_ranges

    shape = tuple(int(d) for d in np.shape(arr))
    shards = getattr(arr, "addressable_shards", None)
    if shards is None:
        return {"shape": list(shape), "dtype": str(np.asarray(arr).dtype),
                "pieces": [([[0, d] for d in shape],
                            np.array(arr, copy=True))]}
    pieces, seen = [], set()
    for sh in shards:
        ranges = tuple(map(tuple, _index_to_ranges(sh.index, shape)))
        if ranges in seen:
            continue
        seen.add(ranges)
        pieces.append(([list(r) for r in ranges],
                       np.array(sh.data, copy=True)))
    return {"shape": list(shape), "dtype": str(arr.dtype), "pieces": pieces}


def _flat_pieces(flat):
    """Dedup'd (offset, owned-host-slice) pieces of one 1-D flat ZeRO slot
    vector; each replica owns a contiguous [off, off+size) slice."""
    shards = getattr(flat, "addressable_shards", None)
    n_pad = int(flat.shape[0])
    if shards is None:
        return [(0, np.array(flat, copy=True))]
    pieces, seen = [], set()
    for sh in shards:
        sl = sh.index[0] if sh.index else slice(0, n_pad)
        off = 0 if sl.start is None else int(sl.start)
        if off in seen:
            continue
        seen.add(off)
        pieces.append((off, np.array(sh.data, copy=True)))
    return pieces


def capture_snapshot(engine) -> Snapshot:
    """Training-thread half of an async save: overlap-issue every D2H copy,
    then materialize owned host arrays. After this returns, the snapshot is
    independent of the engine — donation may invalidate the device buffers
    on the very next dispatch."""
    import jax

    t0 = time.perf_counter()

    def issue(a):
        try:
            a.copy_to_host_async()
        except Exception:
            pass  # non-jax or already-host arrays: materialize below anyway

    fsdp = getattr(engine, "_fsdp_params", None) is not None
    if fsdp:
        for flat in engine._fsdp_params:
            issue(flat)
        for col in engine._fsdp_opt:
            for flat in col:
                issue(flat)
    else:
        for arr in engine.params.values():
            issue(arr)
        if engine._zero_opt is not None:
            for flat in engine._zero_opt:
                issue(flat)
        elif engine.opt_state is not None:
            for comps in engine.opt_state.values():
                for c in comps:
                    issue(c)

    opt = None
    zero = None
    if fsdp:
        # decode the per-bucket flat shards host-side into the ordinary
        # replicated manifest sections (params per-name, opt per name.slot)
        # — every restore path (replicated, ZeRO, fsdp, changed dp degree)
        # then works unchanged off the same manifest, and the fsdp target
        # re-encodes lazily on its next step
        params = {n: _host_pieces(arr)
                  for n, arr in engine._gather_fsdp_params().items()}
        opt = {}
        for n, comps in engine._gather_fsdp_opt().items():
            for ci, c in enumerate(comps):
                opt[f"{n}.{ci}"] = _host_pieces(c)
    else:
        params = {n: _host_pieces(arr) for n, arr in engine.params.items()}
        if engine._zero_opt is not None:
            n, n_pad, shard, nrep = engine._zero_layout()
            zero = {"meta": {"n": int(n), "n_pad": int(n_pad),
                             "nrep": int(nrep),
                             "slots": len(engine._zero_opt)},
                    "pieces": []}
            for j, flat in enumerate(engine._zero_opt):
                for off, piece in _flat_pieces(flat):
                    zero["pieces"].append((j, off, piece))
        elif engine.opt_state is not None:
            opt = {}
            for n, comps in engine.opt_state.items():
                for ci, c in enumerate(comps):
                    opt[f"{n}.{ci}"] = _host_pieces(c)

    key_words = np.array(jax.random.key_data(engine._key), copy=True)
    snap = Snapshot(
        step=int(engine._step_count),
        opt_step=int(engine.optimizer._step_count),
        key_words=[int(w) for w in key_words.reshape(-1)],
        key_shape=list(key_words.shape),
        params=params, opt=opt, zero=zero,
        capture_ms=(time.perf_counter() - t0) * 1e3)
    return snap


# ---------------------------------------------------------------- commit
def checkpoint_path(dirname: str, step: int) -> str:
    return os.path.join(dirname, f"{CKPT_PREFIX}{step:08d}")


def list_checkpoints(dirname: str) -> List[Tuple[int, str]]:
    """Committed checkpoints as (step, path), oldest first. ``.tmp`` dirs
    (uncommitted / crashed saves) are invisible by construction."""
    out = []
    try:
        names = os.listdir(dirname)
    except OSError:
        return out
    for name in names:
        if name.startswith(CKPT_PREFIX) and name[len(CKPT_PREFIX):].isdigit():
            out.append((int(name[len(CKPT_PREFIX):]),
                        os.path.join(dirname, name)))
    return sorted(out)


def write_checkpoint(snap: Snapshot, dirname: str,
                     slow_write_ms: float = 0.0) -> Tuple[str, int]:
    """Commit one snapshot crash-safely; returns (path, payload_bytes).
    Payloads first, manifest last, ``os.rename`` as the single commit
    point — a kill anywhere in here can never produce a directory that
    ``verify_checkpoint`` accepts partially."""
    os.makedirs(dirname, exist_ok=True)
    final = checkpoint_path(dirname, snap.step)
    tmp = os.path.join(
        dirname, f"{TMP_PREFIX}{os.path.basename(final)}.{os.getpid()}")
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    total = 0

    def write_npy(fn, arr):
        nonlocal total
        path = os.path.join(tmp, fn)
        with open(path, "wb") as f:
            np.save(f, arr)
            f.flush()
            os.fsync(f.fileno())
        if slow_write_ms > 0:
            time.sleep(slow_write_ms / 1e3)
        size = os.path.getsize(path)
        total += size
        return {"file": fn, "bytes": int(size), "checksum": file_sha256(path)}

    manifest = {"format": FORMAT_VERSION, "step": snap.step,
                "opt_step": snap.opt_step,
                "key": {"words": snap.key_words, "shape": snap.key_shape},
                "params": {}, "opt": None, "zero_opt": None}
    for key, ent in snap.params.items():
        shards = []
        for i, (ranges, arr) in enumerate(ent["pieces"]):
            meta = write_npy(f"params__{key}__{i}.npy".replace("/", "_"), arr)
            meta["ranges"] = ranges
            shards.append(meta)
        manifest["params"][key] = {"shape": ent["shape"],
                                   "dtype": ent["dtype"], "shards": shards}
    if snap.opt is not None:
        manifest["opt"] = {}
        for key, ent in snap.opt.items():
            shards = []
            for i, (ranges, arr) in enumerate(ent["pieces"]):
                meta = write_npy(f"opt__{key}__{i}.npy".replace("/", "_"), arr)
                meta["ranges"] = ranges
                shards.append(meta)
            manifest["opt"][key] = {"shape": ent["shape"],
                                    "dtype": ent["dtype"], "shards": shards}
    if snap.zero is not None:
        shards = []
        for slot, off, arr in snap.zero["pieces"]:
            meta = write_npy(f"zero__s{slot}__o{off}.npy", arr)
            meta.update({"slot": int(slot), "offset": int(off),
                         "size": int(arr.shape[0])})
            shards.append(meta)
        manifest["zero_opt"] = dict(snap.zero["meta"], shards=shards)

    manifest["manifest_checksum"] = manifest_digest(manifest)
    mpath = os.path.join(tmp, MANIFEST)
    with open(mpath, "w") as f:
        json.dump(manifest, f, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    if os.path.isdir(final):
        # re-saving a step we rolled back to: replace, commit still atomic
        shutil.rmtree(final)
    os.rename(tmp, final)
    _fsync_dir(dirname)
    return final, total


# ---------------------------------------------------------------- verify
def verify_checkpoint(path: str) -> dict:
    """Full offline verification of one committed checkpoint dir: manifest
    parses, self-checksum matches, every payload file present with a
    matching sha256. Returns the manifest; raises CheckpointCorrupt."""
    mpath = os.path.join(path, MANIFEST)
    if not os.path.isfile(mpath):
        raise CheckpointCorrupt(f"{path}: no manifest")
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except (ValueError, OSError) as e:
        raise CheckpointCorrupt(f"{path}: unreadable manifest ({e})")
    if not isinstance(manifest, dict) or manifest.get("format") != FORMAT_VERSION:
        raise CheckpointCorrupt(
            f"{path}: unsupported format {manifest.get('format')!r}"
            if isinstance(manifest, dict) else f"{path}: manifest not a dict")
    if manifest_digest(manifest) != manifest.get("manifest_checksum"):
        raise CheckpointCorrupt(f"{path}: manifest checksum mismatch")
    for kind, entries in (("params", manifest.get("params") or {}),
                          ("opt", manifest.get("opt") or {})):
        for key, ent in entries.items():
            for sh in ent["shards"]:
                _verify_payload(path, kind, key, sh)
    zero = manifest.get("zero_opt")
    if zero is not None:
        for sh in zero["shards"]:
            _verify_payload(path, "zero_opt", f"slot{sh.get('slot')}", sh)
    return manifest


def _verify_payload(path, kind, key, sh):
    fpath = os.path.join(path, sh["file"])
    if not os.path.isfile(fpath):
        raise CheckpointCorrupt(f"{path}: {kind}/{key}: missing {sh['file']}")
    if os.path.getsize(fpath) != sh.get("bytes"):
        raise CheckpointCorrupt(
            f"{path}: {kind}/{key}: {sh['file']} truncated "
            f"({os.path.getsize(fpath)} != {sh.get('bytes')} bytes)")
    if file_sha256(fpath) != sh.get("checksum"):
        raise CheckpointCorrupt(
            f"{path}: {kind}/{key}: {sh['file']} checksum mismatch")


# ---------------------------------------------------------------- restore
def _merge_entry(path, ent):
    """Converter merge step: saved shard slices -> one full host array."""
    from .auto_parallel.dist_saver import Converter

    pieces = [(np.load(os.path.join(path, sh["file"])), sh["ranges"])
              for sh in ent["shards"]]
    return Converter.merge_with_dist_attr(pieces, tuple(ent["shape"]),
                                          dtype=ent["dtype"])


def _merge_zero(path, zero):
    """Saved flat slices -> [slots, old_n_pad] host matrix."""
    full = np.zeros((int(zero["slots"]), int(zero["n_pad"])), np.float32)
    for sh in zero["shards"]:
        arr = np.load(os.path.join(path, sh["file"]))
        full[int(sh["slot"]), int(sh["offset"]):int(sh["offset"]) + len(arr)] = arr
    return full


def _restore_opt(engine, path, manifest):
    import jax

    from ..observability.health import segment_layout

    zero_ckpt = manifest.get("zero_opt")
    try:
        zero_target = bool(engine._zero_on())
    except Exception:
        zero_target = False
    slots_target = engine._zero_n_slots()

    if zero_ckpt is not None:
        if int(zero_ckpt["slots"]) != slots_target:
            raise ValueError(
                f"checkpoint has {zero_ckpt['slots']} optimizer slots but "
                f"the target optimizer expects {slots_target} — restore "
                "requires the same optimizer rule")
        full = _merge_zero(path, zero_ckpt)  # [slots, old_n_pad]
        n = int(zero_ckpt["n"])
        if zero_target:
            # flat -> flat across a changed dp degree: re-pad the true [0:n)
            # prefix for the NEW replica count and let device_put with the
            # target residual sharding do the reslice — the per-param dict
            # is never reconstructed (segment_layout offsets stay valid
            # because the flat order is sorted-by-name on both sides)
            n_new, n_pad_new, _shard, _nrep = engine._zero_layout()
            if n != n_new:
                raise ValueError(
                    f"checkpoint flat opt vector has {n} elements but the "
                    f"target model has {n_new}")
            sh = engine._residual_sharding()
            flats = []
            for j in range(slots_target):
                buf = np.zeros((n_pad_new,), np.float32)
                buf[:n] = full[j, :n]
                flats.append(jax.device_put(buf, sh))
            engine._zero_opt = tuple(flats)
            engine.opt_state = None
        else:
            # flat -> replicated dict: split at segment_layout offsets
            layout = segment_layout(
                {nm: tuple(engine._state_refs[nm].shape)
                 for nm in engine._param_names})
            new_opt = {}
            for nm, off, size in layout:
                shape = tuple(engine._state_refs[nm].shape)
                new_opt[nm] = tuple(
                    jax.device_put(full[j, off:off + size].reshape(shape),
                                   engine._opt_sharding(engine.opt_specs[nm]))
                    for j in range(slots_target))
            engine.opt_state = new_opt
            engine._zero_opt = None
        return

    opt_ckpt = manifest.get("opt")
    if opt_ckpt is None:
        raise CheckpointCorrupt(f"{path}: manifest has neither opt nor zero_opt")
    new_opt = {}
    for nm in engine._param_names:
        comps = []
        for ci in range(slots_target):
            key = f"{nm}.{ci}"
            if key not in opt_ckpt:
                raise KeyError(f"checkpoint missing optimizer state {key}")
            comps.append(jax.device_put(
                _merge_entry(path, opt_ckpt[key]),
                engine._opt_sharding(engine.opt_specs[nm])))
        new_opt[nm] = tuple(comps)
    # a dict checkpoint restoring into a ZeRO engine converts lazily on the
    # next step via _ensure_zero_opt (one-way, same as first engagement)
    engine.opt_state = new_opt
    engine._zero_opt = None


def restore_checkpoint(engine, path: str, manifest: Optional[dict] = None) -> int:
    """Load one verified checkpoint into an engine whose mesh layout may
    differ from the saving run's: merge shards (Converter), device_put with
    the TARGET shardings. Returns the restored step."""
    import jax
    from jax.sharding import NamedSharding

    if manifest is None:
        manifest = verify_checkpoint(path)
    if getattr(engine, "_fsdp_params", None) is not None or \
            engine.params is None:
        # restore lands in the replicated layout: drop the fsdp shard
        # residency; the next fsdp step re-encodes lazily (bit-exact — the
        # f32 encode is a straight copy into the bucket-padded buffers)
        engine._fsdp_params = None
        engine._fsdp_opt = None
        engine.params = {}
    for n in engine._param_names:
        if n not in manifest["params"]:
            raise KeyError(f"checkpoint missing param {n}")
        ent = manifest["params"][n]
        engine.params[n] = jax.device_put(
            _merge_entry(path, ent),
            NamedSharding(engine.mesh, engine.param_specs[n]))
    _restore_opt(engine, path, manifest)
    engine._step_count = int(manifest["step"])
    engine.optimizer._step_count = int(
        manifest.get("opt_step", manifest["step"]))
    engine._lr_cache = (None, None)
    key = manifest.get("key")
    if key and key.get("words"):
        engine._key = jax.random.wrap_key_data(
            np.asarray(key["words"], np.uint32).reshape(key["shape"]))
    engine.last_loss = None
    return int(manifest["step"])


def restore_latest(engine, dirname: str) -> int:
    """Restore the newest VALID checkpoint: corrupt ones (flipped bytes,
    truncated payloads, bad manifests) are skipped with a warning, a
    ``ckpt.corrupt`` count, and a flight dump — automatic fallback to the
    previous complete checkpoint. Raises FileNotFoundError when nothing
    under ``dirname`` verifies."""
    last_err = None
    for step, path in reversed(list_checkpoints(dirname)):
        try:
            manifest = verify_checkpoint(path)
        except CheckpointCorrupt as e:
            last_err = e
            CORRUPT.increase()
            warnings.warn(f"skipping corrupt checkpoint {path}: {e}")
            fr = _obs_flight.get()
            if fr is not None:
                fr.dump("ckpt_corrupt", {"path": path, "error": str(e)})
            continue
        restored = restore_checkpoint(engine, path, manifest)
        RESTORES.increase()
        return restored
    if last_err is not None:
        raise FileNotFoundError(
            f"no valid checkpoint under {dirname} (newest error: {last_err})")
    raise FileNotFoundError(f"no checkpoint under {dirname}")


def live_reshard(engine, new_hcg) -> float:
    """In-memory topology change: redistribute the engine's params + flat
    ZeRO opt shards onto ``new_hcg``'s mesh WITHOUT a disk bounce, and
    return the pause in milliseconds.

    This is the live twin of save + ``restore_latest`` onto a new
    topology: the same host bytes land under the same target shardings
    (``engine.reform_mesh`` reuses the segment_layout reslice math above),
    so training continues bit-identically to the checkpoint-restore path —
    just without serializing ~2x model size through the filesystem.
    ``restore_latest`` stays the fallback for hard crashes; membership
    tracking + the pause/resume protocol live in
    distributed/membership.py (ElasticCoordinator)."""
    t0 = time.perf_counter()
    engine.reform_mesh(new_hcg)
    return (time.perf_counter() - t0) * 1000.0


# ---------------------------------------------------------------- manager
class CheckpointManager:
    """Owns one checkpoint directory: periodic async saves, retention GC,
    newest-valid restore with corruption fallback, opt-in non-finite-loss
    rollback. Engine integration is ``engine.enable_checkpointing(...)`` /
    ``FLAGS_ckpt_*``; standalone use:

        mgr = CheckpointManager("/ckpts", interval=100, keep=3)
        for step, batch in enumerate(loader, 1):
            loss = engine.step(*batch)
            mgr.on_step(engine, step, loss)
        mgr.close()
    """

    def __init__(self, dirname: str, interval: int = 100, keep: int = 3,
                 async_save: bool = True, rollback_on_nonfinite: bool = False,
                 slow_write_ms: Optional[float] = None):
        self.dirname = str(dirname)
        os.makedirs(self.dirname, exist_ok=True)
        self.interval = max(1, int(interval))
        self.keep = max(1, int(keep))
        self.async_save = bool(async_save)
        self.rollback_on_nonfinite = bool(rollback_on_nonfinite)
        if slow_write_ms is None:
            slow_write_ms = os.environ.get(
                "PADDLE_TPU_CKPT_SLOW_WRITE_MS", "0") or 0
        self._slow_write_ms = float(slow_write_ms)
        self._q = queue.Queue(maxsize=2)
        self._thread = None
        self._pending = 0
        self._cond = threading.Condition()
        self._closed = False
        self.last_error = None
        self.last_saved_step = None

    # ---- background writer ----
    def _ensure_thread(self):
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._worker, name="ckpt-writer", daemon=True)
            self._thread.start()

    def _worker(self):
        while True:
            snap = self._q.get()
            if snap is None:
                return
            try:
                self._commit(snap, overlap=True)
            except Exception as e:
                self._note_failure(snap.step, e)
            finally:
                with self._cond:
                    self._pending -= 1
                    self._cond.notify_all()

    def _note_failure(self, step, e):
        self.last_error = e
        FAILURES.increase()
        fr = _obs_flight.get()
        if fr is not None:
            fr.dump("ckpt_save_failed", {"step": step, "error": repr(e)})
        warnings.warn(f"checkpoint save failed at step {step}: {e!r}")

    def _commit(self, snap, overlap=False):
        t0 = time.perf_counter()
        _path, nbytes = write_checkpoint(snap, self.dirname,
                                         slow_write_ms=self._slow_write_ms)
        save_ms = (time.perf_counter() - t0) * 1e3
        SAVES.increase()
        BYTES_WRITTEN.increase(nbytes)
        self.last_saved_step = snap.step
        reg = _obs_metrics.active_registry()
        if reg is not None:
            reg.histogram("ckpt.save_ms").observe(save_ms)
            reg.histogram("ckpt.capture_ms").observe(snap.capture_ms)
            if overlap:
                # wall the writer spent while the training thread kept
                # stepping — the async win the bench pins
                reg.histogram("ckpt.overlap_ms").observe(save_ms)
        self._gc()

    def _gc(self):
        ckpts = list_checkpoints(self.dirname)
        for _step, path in ckpts[:-self.keep] if self.keep else []:
            shutil.rmtree(path, ignore_errors=True)
            GC_REMOVED.increase()
        for name in os.listdir(self.dirname):
            if not name.startswith(TMP_PREFIX):
                continue
            pid = name.rsplit(".", 1)[-1]
            if pid.isdigit() and int(pid) != os.getpid() and not _pid_alive(int(pid)):
                # crashed writer's leftovers: never part of a commit
                shutil.rmtree(os.path.join(self.dirname, name),
                              ignore_errors=True)

    # ---- public API ----
    def save(self, engine, block: bool = False) -> bool:
        """Snapshot now. Async (default): capture on this thread, hand the
        host copy to the writer; returns False (with a ``ckpt.skipped``
        count) when the previous save is still writing. ``block=True``
        commits synchronously and propagates write errors."""
        if self._closed:
            raise RuntimeError("CheckpointManager is closed")
        if not (self.async_save and not block):
            snap = capture_snapshot(engine)
            try:
                self._commit(snap)
            except Exception as e:
                self._note_failure(snap.step, e)
                raise
            return True
        with self._cond:
            # double buffer: one snapshot writing + one queued; a third
            # interval landing here skips instead of stalling the step
            if self._pending >= 2:
                SKIPPED.increase()
                return False
            self._pending += 1
        snap = capture_snapshot(engine)
        self._ensure_thread()
        self._q.put(snap)
        return True

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Drain in-flight async saves; True when idle."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while self._pending > 0:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(remaining if remaining is not None else 0.5)
        return True

    def on_step(self, engine, step: int, loss=None,
                window: int = 1) -> Optional[int]:
        """Per-step hook (called from the engine step tail): opt-in
        rollback on a non-finite loss, else an interval-gated async save.
        ``window`` is the number of optimizer steps this call covers
        (run_steps fuses K of them) — a save fires when ANY step in
        ``(step-window, step]`` lands on the interval. Returns the
        restored step after a rollback, None otherwise."""
        if self._closed:
            return None
        if self.rollback_on_nonfinite and loss is not None:
            try:
                lv = float(loss)
            except Exception:
                lv = None
            if lv is not None and not math.isfinite(lv):
                return self._rollback(engine, step, lv)
        if (step // self.interval) > (step - window) // self.interval:
            self.save(engine)
        return None

    def _rollback(self, engine, step, loss_value):
        fr = _obs_flight.get()
        if fr is not None:
            fr.dump("ckpt_rollback", {"step": step, "loss": loss_value})
        self.wait()  # the newest committed save must win the restore walk
        try:
            restored = restore_latest(engine, self.dirname)
        except FileNotFoundError:
            warnings.warn(
                f"non-finite loss at step {step} but no valid checkpoint "
                f"under {self.dirname} to roll back to")
            return None
        ROLLBACKS.increase()
        warnings.warn(
            f"non-finite loss ({loss_value}) at step {step}: rolled back "
            f"to checkpoint step {restored}")
        return restored

    def restore(self, engine) -> int:
        """Restore the newest valid checkpoint (corruption falls back)."""
        self.wait()
        return restore_latest(engine, self.dirname)

    def checkpoints(self) -> List[Tuple[int, str]]:
        return list_checkpoints(self.dirname)

    def close(self):
        """Drain and stop the writer thread. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.wait()
        if self._thread is not None and self._thread.is_alive():
            self._q.put(None)
            self._thread.join(timeout=30)
        self._thread = None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        pass
    return True


def from_flags() -> Optional[CheckpointManager]:
    """FLAGS_ckpt_dir (or PADDLE_TPU_CKPT_DIR via the flag's env bootstrap)
    turns checkpointing on at engine construction; empty means off."""
    dirname = _flags.flag("ckpt_dir")
    if not dirname:
        return None
    return CheckpointManager(
        dirname,
        interval=int(_flags.flag("ckpt_interval")),
        keep=int(_flags.flag("ckpt_keep")),
        async_save=bool(_flags.flag("ckpt_async")),
        rollback_on_nonfinite=bool(_flags.flag("ckpt_rollback")))

"""DistributedStrategy — the uber-config.

Reference: proto at paddle/fluid/framework/distributed_strategy.proto:277 with per-feature
sub-configs (:26-152), wrapped by fleet/base/distributed_strategy.py:109. Same option surface,
plain dataclasses instead of proto (nothing crosses a language boundary here on TPU).
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class AMPConfig:
    init_loss_scaling: float = 32768.0
    incr_every_n_steps: int = 1000
    decr_every_n_nan_or_inf: int = 2
    incr_ratio: float = 2.0
    decr_ratio: float = 0.8
    use_dynamic_loss_scaling: bool = True
    custom_white_list: List[str] = field(default_factory=list)
    custom_black_list: List[str] = field(default_factory=list)
    use_pure_fp16: bool = False
    use_fp16_guard: bool = True
    dtype: str = "bfloat16"  # TPU default low precision


@dataclass
class RecomputeConfig:
    checkpoints: List[str] = field(default_factory=list)
    enable_offload: bool = False
    checkpoint_shape: List[int] = field(default_factory=list)
    # "full" recomputes whole segments; "selective" saves matmul outputs and
    # recomputes only the elementwise tail (jax.checkpoint policy) — the
    # reference's recompute_granularity knob
    granularity: str = "full"


@dataclass
class GradientMergeConfig:
    k_steps: int = 1
    avg: bool = True


@dataclass
class ShardingConfig:
    sharding_segment_strategy: str = "segment_broadcast_MB"
    segment_broadcast_MB: float = 32.0
    sharding_degree: int = 8
    stage: int = 1
    mp_degree: int = 1
    dp_degree: int = 1
    pp_degree: int = 1
    optimize_offload: bool = False
    gradient_merge_acc_step: int = 1


@dataclass
class PipelineConfig:
    micro_batch_size: int = 1
    accumulate_steps: int = 1
    schedule_mode: str = "1F1B"
    p2p_cache_shape: bool = True


@dataclass
class HybridConfig:
    dp_degree: int = -1
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sep_degree: int = 1  # sequence parallel (TPU addition; absent in reference)
    ep_degree: int = 1   # expert parallel


@dataclass
class TensorParallelConfig:
    tensor_parallel_degree: int = 1
    tensor_init_seed: int = -1


@dataclass
class LocalSGDConfig:
    k_steps: int = 1
    begin_step: int = 1


@dataclass
class DGCConfig:
    rampup_begin_step: int = 0
    rampup_step: int = 1
    sparsity: List[float] = field(default_factory=lambda: [0.999])


@dataclass
class LambConfig:
    lamb_weight_decay: float = 0.01
    exclude_from_weight_decay: List[str] = field(default_factory=list)


@dataclass
class LarsConfig:
    lars_coeff: float = 0.001
    lars_weight_decay: float = 0.0005
    epsilon: float = 0.0
    exclude_from_weight_decay: List[str] = field(default_factory=list)


@dataclass
class ASyncConfig:
    k_steps: int = -1
    max_merge_var_num: int = 1
    send_queue_size: int = 16
    independent_recv_thread: bool = False
    thread_pool_size: int = 1
    send_wait_times: int = 1
    runtime_split_send_recv: bool = False


class DistributedStrategy:
    def __init__(self):
        # feature switches (proto field parity)
        self.amp = False
        self.recompute = False
        self.gradient_merge = False
        self.sharding = False
        self.pipeline = False
        self.tensor_parallel = False
        self.sequence_parallel = False
        self.expert_parallel = False
        self.dgc = False
        self.localsgd = False
        self.lars = False
        self.lamb = False
        self.fp16_allreduce = False
        self.a_sync = False
        self.heter_ccl_mode = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self.without_graph_optimization = True
        self.find_unused_parameters = False
        self.last_comm_group_size_MB = 1.0
        self.fuse_grad_merge = False
        self.semi_auto = False
        self.auto_search = False
        # sequence-parallel attention flavor: "ulysses" (head-scatter
        # all-to-all) or "ring" (KV rotation via ppermute). Ulysses is the
        # default on the XLA cost model (BASELINE.md ring-vs-Ulysses table:
        # near-dense peak memory and bytes-moved at sp 2-4, all-to-alls ride
        # ICI); ring remains available for the seq >> 100k regime where its
        # O(1) per-step working set wins.
        # PROVENANCE (VERDICT r5 weak #7): this default is cost-model-chosen
        # ONLY — it has never been measured on real multi-chip hardware (the
        # dryrun certifies correctness, not the ranking). Re-validate
        # ring-vs-Ulysses on a pod before trusting the default at scale.
        self.sep_impl = "ulysses"

        # sub-configs
        self.amp_configs = AMPConfig()
        self.recompute_configs = RecomputeConfig()
        self.gradient_merge_configs = GradientMergeConfig()
        self.sharding_configs = ShardingConfig()
        self.pipeline_configs = PipelineConfig()
        self.hybrid_configs = HybridConfig()
        self.tensor_parallel_configs = TensorParallelConfig()
        self.localsgd_configs = LocalSGDConfig()
        self.dgc_configs = DGCConfig()
        self.lamb_configs = LambConfig()
        self.lars_configs = LarsConfig()
        self.a_sync_configs = ASyncConfig()

    def __setattr__(self, name, value):
        # accept dict assignment to *_configs like the reference python wrapper
        if name.endswith("_configs") and isinstance(value, dict):
            current = self.__dict__.get(name)
            if current is not None and dataclasses.is_dataclass(current):
                for k, v in value.items():
                    if hasattr(current, k):
                        setattr(current, k, v)
                    else:
                        raise ValueError(f"unknown {name} key {k!r}")
                return
        object.__setattr__(self, name, value)

    def to_dict(self) -> Dict[str, Any]:
        out = {}
        for k, v in self.__dict__.items():
            out[k] = dataclasses.asdict(v) if dataclasses.is_dataclass(v) else v
        return out

    def __repr__(self):
        on = [k for k, v in self.__dict__.items() if v is True]
        return f"DistributedStrategy(enabled={on})"

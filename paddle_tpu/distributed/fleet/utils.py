"""fleet.utils: recompute (activation checkpointing) + hybrid parallel helpers.

Reference: fleet/utils/recompute.py:199 (RecomputeFunction — PyLayer that stashes RNG
state and inputs, replays the forward under grad in backward) and
fleet/utils/hybrid_parallel_util.py:128,142 (param broadcast, fused grad allreduce).

TPU-native: in traced mode (inside the engine's pjit step) recompute IS `jax.checkpoint`
— XLA rematerializes the segment in backward, the exact hardware analogue. Eagerly it is
the reference's replay strategy on the vjp tape.
"""
from __future__ import annotations

import jax

from ...core import random as random_mod
from ...core.autograd import Node, enable_grad, is_grad_enabled, no_grad
from ...core.autograd import grad as grad_api
from ...core.tensor import Tensor
from ...jit import in_jit_trace


_REMAT_POLICIES = {
    # reference recompute_granularity analogues (recompute_configs; the
    # static sharding optimizer's fp16_helper/offload split the same knob):
    #   "full"      — save nothing, recompute the whole segment (max HBM win,
    #                 ~+33% forward FLOPs)
    #   "selective" — save matmul/dot outputs, recompute only the cheap
    #                 elementwise tail (most of the memory win at a fraction
    #                 of the recompute FLOPs — the TPU-native middle ground,
    #                 since elementwise recompute is HBM-cheap and the MXU
    #                 matmuls are what recompute would otherwise repeat)
    "full": None,  # jax.checkpoint default: nothing saveable
    "selective": "dots_with_no_batch_dims_saveable",
}


def _resolve_policy(policy):
    if policy is None or policy == "full":
        return None
    if callable(policy):
        return policy
    fn = getattr(jax.checkpoint_policies,
                 _REMAT_POLICIES.get(policy, policy), None) \
        if isinstance(policy, str) else None
    if fn is None:
        raise ValueError(
            f"unknown recompute policy {policy!r}; use 'full', 'selective', "
            f"a jax.checkpoint_policies name, or a callable")
    return fn


def recompute(function, *args, **kwargs):
    preserve_rng_state = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)
    # policy applies on the traced (jax.checkpoint) path; the eager tape
    # replay below always recomputes the full segment ("full" semantics).
    # Resolve unconditionally so a typo'd granularity fails fast in BOTH
    # modes instead of silently training full-remat eagerly.
    policy = _resolve_policy(kwargs.pop("policy", None))

    tensor_args = [a for a in args if isinstance(a, Tensor)]

    if in_jit_trace():
        # traced: lower to jax.checkpoint (remat). Closure tracers (layer params from
        # functional_call) are differentiated through correctly by jax.
        def f(*arrays):
            wrapped = []
            it = iter(arrays)
            for a in args:
                wrapped.append(Tensor(next(it)) if isinstance(a, Tensor) else a)
            out = function(*wrapped, **kwargs)
            if isinstance(out, (tuple, list)):
                return tuple(o._data if isinstance(o, Tensor) else o for o in out)
            return out._data if isinstance(out, Tensor) else out

        out = jax.checkpoint(f, policy=policy)(
            *[t._data for t in tensor_args])
        if isinstance(out, tuple):
            return tuple(Tensor(o) for o in out)
        return Tensor(out)

    # eager: replay-in-backward on the vjp tape
    if not is_grad_enabled() or not any(not t.stop_gradient for t in tensor_args):
        return function(*args, **kwargs)

    rng_state = random_mod.get_rng_state() if preserve_rng_state else None

    with no_grad():
        outputs = function(*args, **kwargs)

    multi = isinstance(outputs, (tuple, list))
    outs = list(outputs) if multi else [outputs]
    out_tensors = [o for o in outs if isinstance(o, Tensor)]

    import numpy as np

    def vjp_fn(cotangents):
        cots = cotangents if isinstance(cotangents, tuple) else (cotangents,)
        if rng_state is not None:
            saved = random_mod.get_rng_state()
            random_mod.set_rng_state(rng_state)
        try:
            detached = []
            for a in args:
                if isinstance(a, Tensor):
                    d = a.detach()
                    d.stop_gradient = a.stop_gradient
                    detached.append(d)
                else:
                    detached.append(a)
            with enable_grad():
                replay = function(*detached, **kwargs)
            replay_list = list(replay) if isinstance(replay, (tuple, list)) else [replay]
            replay_t = [o for o in replay_list if isinstance(o, Tensor)
                        and not o.stop_gradient]
            # Real backward over the replayed segment: deposits grads directly into the
            # captured parameters' .grad (the reference RecomputeFunction's backward
            # does exactly this) and into the detached inputs, whose grads we return
            # as cotangents for the outer tape.
            from ...core.autograd import run_backward

            run_backward(replay_t, [Tensor(c) for c in cots[:len(replay_t)]])
        finally:
            if rng_state is not None:
                random_mod.set_rng_state(saved)
        result = []
        di = iter([d for d in detached if isinstance(d, Tensor)])
        for t in tensor_args:
            d = next(di)
            if t.stop_gradient or d._grad is None:
                result.append(None)
            else:
                result.append(d._grad._data)
        return tuple(result)

    node = Node(vjp_fn, tensor_args,
                [(tuple(o.shape), np.dtype(o.dtype)) for o in out_tensors],
                name="recompute")
    for i, o in enumerate(out_tensors):
        o._stop_gradient = False
        o._node = node
        o._out_index = i
    return outputs


def fused_allreduce_gradients(parameter_list, hcg):
    """Reference hybrid_parallel_util.py:142 — under the pjit engine this is the
    XLA allreduce from batch-sharded grads; eagerly (multi-process) it fuses
    grads into comm-buffer buckets and runs one collective per bucket
    (meta_parallel.data_parallel.Reducer, reference reducer.cc)."""
    from ..meta_parallel.data_parallel import Reducer

    group = hcg.get_data_parallel_group() if hcg else None
    if group is None or group.nranks <= 1:
        return
    # small per-group LRU keyed by TRAINABLE membership: a stop_gradient flip
    # (un/refreezing) rebuilds the buckets; a handful of models sharing one
    # group (e.g. GAN generator/discriminator) each stay cached; anything
    # older is evicted so discarded models aren't pinned forever
    params = [p for p in parameter_list
              if not getattr(p, "stop_gradient", True) and p.size]
    key = tuple(id(p) for p in params)
    slots = _reducer_cache.setdefault(id(group), {})
    red = slots.pop(key, None)  # pop+reinsert: dict order = recency
    if red is None:
        while len(slots) >= 4:  # bounded: evict least recently used
            slots.pop(next(iter(slots)))
        red = Reducer(params, group=group)
    slots[key] = red
    red.sync()


_reducer_cache = {}  # id(group) -> {trainable-ids: Reducer} (LRU, max 4)


def _broadcast_group_parameters(model, group, skip_axis=None):
    """Broadcast params from the group's first rank (reference
    hybrid_parallel_util.py broadcast_*_parameters). Single-controller mode is
    a no-op — every replica IS the same global array. Multi-controller mode
    (jax.distributed processes) really broadcasts, except params sharded over
    `skip_axis`, which intentionally differ per rank."""
    import jax

    if group is None or getattr(group, "nranks", 1) <= 1:
        return
    if jax.process_count() == 1:
        return
    from .. import collective

    for p in model.parameters():
        spec = getattr(p, "dist_attr", None)
        if skip_axis is not None and collective.spec_has_axis(spec, skip_axis):
            continue
        collective.broadcast(p, src=group.ranks[0], group=group)


def broadcast_mp_parameters(model, hcg):
    # replicated (non-mp-sharded) params must agree across the mp group;
    # mp-sharded ones (dist_attr over 'mp') differ by construction
    _broadcast_group_parameters(model, hcg.get_model_parallel_group(),
                                skip_axis="mp")


def broadcast_dp_parameters(model, hcg):
    _broadcast_group_parameters(model, hcg.get_data_parallel_group())


def broadcast_sharding_parameters(model, hcg):
    _broadcast_group_parameters(model, hcg.get_sharding_parallel_group())


from . import fs  # noqa: E402,F401
from .fs import FSStore, HDFSClient, LocalFS  # noqa: E402,F401

"""Fleet datasets: InMemoryDataset / QueueDataset over the C++ data feed.

Reference: python/paddle/distributed/fleet/dataset/dataset.py (InMemoryDataset
:init/_init_distributed_settings/load_into_memory/global_shuffle, QueueDataset)
backed by the C++ MultiSlotDataset/InMemoryDataFeed (data_set.h:47,
data_feed.h:966). Same split here: core/native/data_feed.cc does the
multithreaded parsing, in-memory store, shuffle, and CSR batch emission; this
module is the user-facing config + iteration surface. Sparse (uint64 id)
slots come back as (values, lod_offsets); dense float slots as [batch, dim]
arrays.
"""
from __future__ import annotations

import ctypes
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class _NativeFeed:
    def __init__(self):
        from ...core.native import load_library

        self._lib = load_library("data_feed")
        if self._lib is None:
            raise RuntimeError("native data_feed unavailable (no C++ toolchain)")
        self._lib.df_load.restype = ctypes.c_longlong
        self._lib.df_size.restype = ctypes.c_longlong
        self._lib.df_next.restype = ctypes.c_longlong
        self._lib.df_slot_vals.restype = ctypes.c_longlong
        self._lib.df_shuffle.argtypes = [ctypes.c_int, ctypes.c_longlong]
        self._h = None

    def create(self, types: str):
        self._h = self._lib.df_create(len(types), types.encode())
        if self._h < 0:
            raise RuntimeError("df_create failed (slot/type mismatch)")

    def load(self, files: Sequence[str], nthreads: int) -> int:
        return self._lib.df_load(self._h, ",".join(files).encode(), nthreads)

    def size(self) -> int:
        return self._lib.df_size(self._h)

    def shuffle(self, seed: int):
        self._lib.df_shuffle(self._h, seed)

    def begin(self, batch_size: int):
        self._lib.df_begin(self._h, batch_size)

    def next(self) -> int:
        return self._lib.df_next(self._h)

    def slot(self, idx: int, typ: str, rows: int):
        n = self._lib.df_slot_vals(self._h, idx)
        offs = np.zeros(rows + 1, np.int64)
        offs_p = offs.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong))
        if typ == "u":
            vals = np.zeros(max(n, 1), np.uint64)
            self._lib.df_slot_copy_u(
                self._h, idx, vals.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                offs_p)
        else:
            vals = np.zeros(max(n, 1), np.float32)
            self._lib.df_slot_copy_f(
                self._h, idx, vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                offs_p)
        return vals[:n], offs

    def destroy(self):
        if self._h is not None:
            self._lib.df_destroy(self._h)
            self._h = None


class DatasetBase:
    """Config surface shared by InMemory/Queue datasets (reference
    DatasetBase.init: batch_size/thread_num/use_var/pipe_command...)."""

    def __init__(self):
        self._batch_size = 1
        self._thread_num = 1
        self._slots: List[Tuple[str, str]] = []  # (name, 'u'|'f')
        self._filelist: List[str] = []
        self._feed: Optional[_NativeFeed] = None

    def init(self, batch_size=1, thread_num=1, use_var=None, fs_name="",
             fs_ugi="", pipe_command="cat", download_cmd="cat",
             input_type=0, **kwargs):
        self._batch_size = batch_size
        self._thread_num = thread_num
        if use_var:
            self._slots = [self._var_slot(v) for v in use_var]
        return self

    @staticmethod
    def _var_slot(v):
        """Accept (name, kind) pairs, dicts, or Tensors (int dtype -> sparse)."""
        if isinstance(v, tuple):
            return (v[0], "u" if v[1] in ("u", "sparse", "int64") else "f")
        if isinstance(v, dict):
            return (v["name"], "u" if v.get("sparse") else "f")
        name = getattr(v, "name", str(id(v)))
        dt = str(getattr(v, "dtype", "float32"))
        return (name, "u" if "int" in dt else "f")

    def set_filelist(self, filelist: Sequence[str]):
        self._filelist = list(filelist)

    def set_batch_size(self, batch_size: int):
        self._batch_size = batch_size

    def set_thread(self, thread_num: int):
        self._thread_num = thread_num

    def set_use_var(self, use_var):
        self._slots = [self._var_slot(v) for v in use_var]

    def _ensure_feed(self):
        if self._feed is None:
            self._feed = _NativeFeed()
            self._feed.create("".join(t for _, t in self._slots))
        return self._feed

    # ---- iteration: yields {slot_name: dense [b, d] | (values, lod)} ----
    def _iter_batches(self):
        feed = self._ensure_feed()
        feed.begin(self._batch_size)
        while True:
            rows = feed.next()
            if rows <= 0:
                break
            out: Dict[str, object] = {}
            for i, (name, typ) in enumerate(self._slots):
                vals, offs = feed.slot(i, typ, rows)
                widths = np.diff(offs)
                if typ == "f" and len(widths) and (widths == widths[0]).all():
                    out[name] = vals.reshape(rows, -1)
                else:
                    out[name] = (vals, offs)
            yield out

    def __iter__(self):
        return self._iter_batches()

    def release_memory(self):
        if self._feed is not None:
            self._feed.destroy()
            self._feed = None


class InMemoryDataset(DatasetBase):
    """Load everything, shuffle globally, iterate (reference InMemoryDataset)."""

    def load_into_memory(self):
        assert self._filelist, "call set_filelist() first"
        feed = self._ensure_feed()
        n = feed.load(self._filelist, self._thread_num)
        if n < 0:
            raise RuntimeError("data feed load failed")
        return n

    def get_memory_data_size(self) -> int:
        return self._ensure_feed().size()

    def global_shuffle(self, fleet=None, thread_num=12, seed=None):
        """Single-host global shuffle; with a fleet handle the reference
        exchanges records across trainers — here each trainer shuffles its own
        shard (the launcher already splits the filelist per trainer)."""
        if seed is None:
            seed = int.from_bytes(os.urandom(4), "little")
        self._ensure_feed().shuffle(seed)

    def local_shuffle(self, seed=None):
        self.global_shuffle(seed=seed)


class QueueDataset(DatasetBase):
    """Streaming iteration: files are parsed lazily per-iteration rather than
    held resident (reference QueueDataset). Reuses the same native parser,
    loading one file at a time."""

    def _iter_batches(self):
        for f in self._filelist:
            feed = _NativeFeed()
            feed.create("".join(t for _, t in self._slots))
            feed.load([f], self._thread_num)
            feed.begin(self._batch_size)
            while True:
                rows = feed.next()
                if rows <= 0:
                    break
                out: Dict[str, object] = {}
                for i, (name, typ) in enumerate(self._slots):
                    vals, offs = feed.slot(i, typ, rows)
                    widths = np.diff(offs)
                    if typ == "f" and len(widths) and (widths == widths[0]).all():
                        out[name] = vals.reshape(rows, -1)
                    else:
                        out[name] = (vals, offs)
                yield out
            feed.destroy()

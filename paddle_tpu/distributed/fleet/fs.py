"""Filesystem abstraction: LocalFS + HDFSClient surface.

Reference: python/paddle/distributed/fleet/utils/fs.py — checkpoint/PS table
dumps go through an FS interface so HDFS-backed clusters work. The TPU build
keeps the interface; HDFS operations require a `hadoop` binary on PATH and
degrade with a clear error otherwise (zero-egress images have none)."""
from __future__ import annotations

import os
import shutil
import subprocess


class ExecuteError(Exception):
    pass


class FS:
    def ls_dir(self, path):
        raise NotImplementedError

    def is_exist(self, path):
        raise NotImplementedError


class LocalFS(FS):
    def ls_dir(self, path):
        if not self.is_exist(path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, name)) else files).append(name)
        return dirs, files

    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst):
        os.rename(src, dst)

    def mv(self, src, dst, overwrite=False, test_exists=True):
        if overwrite:
            self.delete(dst)
        os.rename(src, dst)

    def upload(self, local, remote):
        shutil.copy(local, remote)

    def download(self, remote, local):
        shutil.copy(remote, local)

    def touch(self, path, exist_ok=True):
        if os.path.exists(path) and not exist_ok:
            raise ExecuteError(path)
        open(path, "a").close()

    def cat(self, path):
        with open(path) as f:
            return f.read()

    def list_dirs(self, path):
        return self.ls_dir(path)[0]


class HDFSClient(FS):
    """hadoop-CLI-backed client (reference fs.py HDFSClient)."""

    def __init__(self, hadoop_home, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else "hadoop"
        self._configs = configs or {}

    def _run(self, *args):
        cmd = [self._hadoop, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", f"{k}={v}"]
        cmd += list(args)
        try:
            out = subprocess.run(cmd, capture_output=True, text=True, check=True)
        except FileNotFoundError as e:
            raise ExecuteError(
                "hadoop binary not found — HDFSClient needs a hadoop install "
                "(this build is zero-egress; use LocalFS)") from e
        except subprocess.CalledProcessError as e:
            raise ExecuteError(e.stderr) from e
        return out.stdout

    def is_exist(self, path):
        try:
            self._run("-test", "-e", path)
            return True
        except ExecuteError:
            return False

    def ls_dir(self, path):
        out = self._run("-ls", path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            (dirs if parts[0].startswith("d") else files).append(parts[-1])
        return dirs, files

    def mkdirs(self, path):
        self._run("-mkdir", "-p", path)

    def delete(self, path):
        self._run("-rm", "-r", "-f", path)

    def upload(self, local, remote):
        self._run("-put", "-f", local, remote)

    def download(self, remote, local):
        self._run("-get", remote, local)


class FSStore:
    """Rendezvous/barrier store over any FS backend — the HdfsStore analogue
    (reference paddle/fluid/framework/fleet/gloo_wrapper.h:134: gloo's PS
    barriers rendezvous through HDFS files when no TCP store is reachable).

    Works with LocalFS on a shared mount (NFS/FUSE) or HDFSClient; keys are
    files under `root`, barriers are per-rank marker files counted with
    ls_dir. Polling store — suited to low-rate control-plane traffic
    (barriers, endpoint publication), not data.
    """

    def __init__(self, fs: FS, root: str, world_size: int = 1, rank: int = 0,
                 poll_interval: float = 0.2):
        import tempfile

        self.fs = fs
        self.root = root.rstrip("/")
        self.world_size = world_size
        self.rank = rank
        self.poll = poll_interval
        self._tmp = tempfile.mkdtemp(prefix="fsstore_")
        self._barrier_gen: dict = {}
        fs.mkdirs(self.root)

    def _p(self, key: str) -> str:
        return f"{self.root}/{key.replace('/', '%2F')}"

    def _local_tmp(self) -> str:
        import tempfile

        fd, path = tempfile.mkstemp(dir=self._tmp)  # per-call: thread-safe
        os.close(fd)
        return path

    def set(self, key: str, value) -> None:
        data = value if isinstance(value, bytes) else str(value).encode()
        local = self._local_tmp()
        try:
            with open(local, "wb") as f:
                f.write(data)
            # visibility must be atomic: a polling get() on another node must
            # see nothing or the complete value. HDFS -put is rename-atomic;
            # LocalFS copy is NOT, so stage under the reserved .__stage prefix
            # (which _p() can never produce — "/" escapes to %2F, and
            # list_keys hides it) and rename into place.
            dst = self._p(key)
            if isinstance(self.fs, LocalFS):
                staged = os.path.join(
                    self.root, f".__stage.{self.rank}.{os.path.basename(local)}")
                self.fs.upload(local, staged)
                os.replace(staged, dst)
            else:
                self.fs.upload(local, dst)
        finally:
            os.unlink(local)

    def get(self, key: str, wait: bool = True, timeout: float = 300.0) -> bytes:
        import time as _time

        deadline = _time.monotonic() + timeout
        path = self._p(key)
        while True:
            if self.fs.is_exist(path):
                local = self._local_tmp()
                # download to a DERIVED name: unlinking the mkstemp
                # reservation itself would let a concurrent call reuse it
                dl = local + ".dl"
                try:
                    self.fs.download(path, dl)
                    with open(dl, "rb") as f:
                        return f.read()
                finally:
                    os.unlink(local)
                    if os.path.exists(dl):
                        os.unlink(dl)
            if not wait:
                raise KeyError(key)
            if _time.monotonic() > deadline:
                raise TimeoutError(key)
            _time.sleep(self.poll)

    def wait(self, keys, timeout: float = 300.0) -> None:
        if isinstance(keys, str):
            keys = [keys]
        for k in keys:
            self.get(k, wait=True, timeout=timeout)

    def delete_key(self, key: str) -> bool:
        path = self._p(key)
        if self.fs.is_exist(path):
            self.fs.delete(path)
            return True
        return False

    def list_keys(self, prefix: str = ""):
        _, files = self.fs.ls_dir(self.root)
        keys = [os.path.basename(f).replace("%2F", "/") for f in files
                if not os.path.basename(f).startswith(".__stage.")]
        return [k for k in keys if k.startswith(prefix)]

    def barrier(self, name: str, world_size=None, timeout: float = 300.0,
                rank=None) -> None:
        """Each rank drops `<name>/<rank>` and waits for world_size markers
        (exactly the HdfsStore wait pattern). Repeated barriers on the same
        name get a per-call generation suffix so stale markers from an earlier
        round can never satisfy a later one (every rank calls each named
        barrier the same number of times, so generations agree)."""
        import time as _time

        world = world_size or self.world_size
        who = self.rank if rank is None else rank
        gen = self._barrier_gen.get(name, 0)
        self._barrier_gen[name] = gen + 1
        bdir = f"{self.root}/barrier_{name}_g{gen}"
        self.fs.mkdirs(bdir)
        local = self._local_tmp()
        try:
            self.fs.upload(local, f"{bdir}/{who}")
        finally:
            os.unlink(local)
        deadline = _time.monotonic() + timeout
        while True:
            _, files = self.fs.ls_dir(bdir)
            if len(files) >= world:
                return
            if _time.monotonic() > deadline:
                raise TimeoutError(f"barrier {name}: {len(files)}/{world}")
            _time.sleep(self.poll)

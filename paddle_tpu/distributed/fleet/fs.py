"""Filesystem abstraction: LocalFS + HDFSClient surface.

Reference: python/paddle/distributed/fleet/utils/fs.py — checkpoint/PS table
dumps go through an FS interface so HDFS-backed clusters work. The TPU build
keeps the interface; HDFS operations require a `hadoop` binary on PATH and
degrade with a clear error otherwise (zero-egress images have none)."""
from __future__ import annotations

import os
import shutil
import subprocess


class ExecuteError(Exception):
    pass


class FS:
    def ls_dir(self, path):
        raise NotImplementedError

    def is_exist(self, path):
        raise NotImplementedError


class LocalFS(FS):
    def ls_dir(self, path):
        if not self.is_exist(path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, name)) else files).append(name)
        return dirs, files

    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst):
        os.rename(src, dst)

    def mv(self, src, dst, overwrite=False, test_exists=True):
        if overwrite:
            self.delete(dst)
        os.rename(src, dst)

    def upload(self, local, remote):
        shutil.copy(local, remote)

    def download(self, remote, local):
        shutil.copy(remote, local)

    def touch(self, path, exist_ok=True):
        if os.path.exists(path) and not exist_ok:
            raise ExecuteError(path)
        open(path, "a").close()

    def cat(self, path):
        with open(path) as f:
            return f.read()

    def list_dirs(self, path):
        return self.ls_dir(path)[0]


class HDFSClient(FS):
    """hadoop-CLI-backed client (reference fs.py HDFSClient)."""

    def __init__(self, hadoop_home, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._hadoop = os.path.join(hadoop_home, "bin", "hadoop") \
            if hadoop_home else "hadoop"
        self._configs = configs or {}

    def _run(self, *args):
        cmd = [self._hadoop, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", f"{k}={v}"]
        cmd += list(args)
        try:
            out = subprocess.run(cmd, capture_output=True, text=True, check=True)
        except FileNotFoundError as e:
            raise ExecuteError(
                "hadoop binary not found — HDFSClient needs a hadoop install "
                "(this build is zero-egress; use LocalFS)") from e
        except subprocess.CalledProcessError as e:
            raise ExecuteError(e.stderr) from e
        return out.stdout

    def is_exist(self, path):
        try:
            self._run("-test", "-e", path)
            return True
        except ExecuteError:
            return False

    def ls_dir(self, path):
        out = self._run("-ls", path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            (dirs if parts[0].startswith("d") else files).append(parts[-1])
        return dirs, files

    def mkdirs(self, path):
        self._run("-mkdir", "-p", path)

    def delete(self, path):
        self._run("-rm", "-r", "-f", path)

    def upload(self, local, remote):
        self._run("-put", "-f", local, remote)

    def download(self, remote, local):
        self._run("-get", remote, local)

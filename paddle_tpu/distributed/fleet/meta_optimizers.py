"""Meta-optimizers + StrategyCompiler.

Reference: python/paddle/distributed/fleet/meta_optimizers/ (amp_optimizer.py,
recompute_optimizer.py, gradient_merge_optimizer.py, localsgd_optimizer.py,
dgc_optimizer.py, lars/lamb_optimizer.py, fp16_allreduce, raw_program_optimizer,
sharding_optimizer.py) selected and chained by strategy_compiler.py via
meta_optimizer_factory.py.

TPU-native: the reference's meta-optimizers REWRITE a static ProgramDesc (insert
cast ops, comm ops, segment programs). Here the "program" is either the eager
tape or the engine's single pjit computation, so each meta-optimizer is a
composable wrapper over the optimizer's step/clear_grad (eager path) plus a
strategy marker the TrainStepEngine reads at trace time (amp autocast, sharded
optimizer states, recompute). The compiler keeps the reference's selection and
ordering semantics so `fleet.distributed_optimizer(opt, strategy)` behaves the
same from the user's side.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...core.autograd import no_grad


class MetaOptimizerBase:
    """Wrapper protocol: everything proxies to the innermost optimizer unless
    overridden. `applied_meta_list`-style introspection via .name chains."""

    name = "base"
    # meta-optimizers this one cannot compose with (reference
    # meta_optimizer.disable_in_strategy semantics)
    conflicts: tuple = ()

    def __init__(self, inner, strategy, hcg=None):
        self._inner_opt = inner
        self._strategy = strategy
        self._hcg = hcg

    @classmethod
    def can_apply(cls, strategy, hcg=None) -> bool:
        return False

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=True):
        self._inner_opt.clear_grad(set_to_zero)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, []

    @property
    def applied_meta_list(self):
        chain = []
        opt = self
        while isinstance(opt, MetaOptimizerBase):
            chain.append(opt.name)
            opt = opt._inner_opt
        return chain


class AMPOptimizer(MetaOptimizerBase):
    """bf16 autocast + (optional) dynamic loss scaling.

    Reference amp_optimizer.py rewrites the program with cast ops +
    check_finite_and_unscale/update_loss_scaling. On TPU the low dtype is
    bfloat16 whose exponent range equals f32, so loss scaling is inert by
    default; the autocast itself happens in the forward — eagerly via the
    amp_context() this wrapper exposes, or at trace time when the engine sees
    strategy.amp. float16 configs still get a working GradScaler."""

    name = "amp"

    def __init__(self, inner, strategy, hcg=None):
        super().__init__(inner, strategy, hcg)
        from ...amp import GradScaler

        cfg = strategy.amp_configs
        need_scaling = cfg.dtype == "float16" and cfg.use_dynamic_loss_scaling
        self._scaler = GradScaler(
            enable=need_scaling,
            init_loss_scaling=cfg.init_loss_scaling,
            incr_ratio=cfg.incr_ratio, decr_ratio=cfg.decr_ratio,
            incr_every_n_steps=cfg.incr_every_n_steps,
            decr_every_n_nan_or_inf=cfg.decr_every_n_nan_or_inf)

    @classmethod
    def can_apply(cls, strategy, hcg=None):
        return bool(strategy.amp)

    def amp_context(self):
        from ...amp import amp_guard_from_configs

        return amp_guard_from_configs(self._strategy.amp_configs)

    def scale(self, loss):
        if self._scaler._enable:
            self._loss_was_scaled = True
            return self._scaler.scale(loss)
        return loss

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.scale(loss).backward()
        self.step()
        return None, []

    def step(self):
        # unscale only when this wrapper scaled the loss — a plain
        # loss.backward(); step() must not divide unscaled grads
        if self._scaler._enable and getattr(self, "_loss_was_scaled", False):
            self._scaler.step(self._inner_opt)
            self._scaler.update()
            self._loss_was_scaled = False
        else:
            self._inner_opt.step()


class RecomputeOptimizer(MetaOptimizerBase):
    """Turns on activation checkpointing for the model's recompute-capable
    blocks (reference recompute_optimizer.py marks checkpoint vars; models here
    carry `use_recompute` flags consumed by fleet.utils.recompute)."""

    name = "recompute"

    @classmethod
    def can_apply(cls, strategy, hcg=None):
        return bool(strategy.recompute)

    def enable_on(self, model):
        gran = getattr(self._strategy.recompute_configs, "granularity", "full")
        n = 0
        for layer in model.sublayers(include_self=True):
            if hasattr(layer, "use_recompute"):
                layer.use_recompute = True
                if hasattr(layer, "recompute_granularity"):
                    layer.recompute_granularity = gran
                n += 1
        return n


class GradientMergeOptimizer(MetaOptimizerBase):
    """Accumulate grads for k_steps micro-steps, then apply one update
    (reference gradient_merge_optimizer.py; the tape's += grad accumulation
    plays the role of the @GRAD@MERGED vars)."""

    name = "gradient_merge"

    def __init__(self, inner, strategy, hcg=None):
        super().__init__(inner, strategy, hcg)
        self.k_steps = max(1, int(strategy.gradient_merge_configs.k_steps))
        self.avg = bool(strategy.gradient_merge_configs.avg)
        self._acc = 0

    @classmethod
    def can_apply(cls, strategy, hcg=None):
        return bool(strategy.gradient_merge) and \
            strategy.gradient_merge_configs.k_steps > 1

    @no_grad()
    def step(self):
        self._acc += 1
        if self._acc % self.k_steps != 0:
            return  # keep accumulating; clear_grad below also holds
        if self.avg:
            for p in self._inner_opt._parameter_list:
                if p.grad is not None:
                    p.grad.set_value(p.grad._data / self.k_steps)
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=True):
        if self._acc % self.k_steps == 0:
            self._inner_opt.clear_grad(set_to_zero)


class LocalSGDOptimizer(MetaOptimizerBase):
    """Step locally; average params across the dp group every k_steps
    (reference localsgd_optimizer.py)."""

    name = "localsgd"
    conflicts = ("dgc",)

    def __init__(self, inner, strategy, hcg=None):
        super().__init__(inner, strategy, hcg)
        self.k_steps = max(1, int(strategy.localsgd_configs.k_steps))
        self.begin_step = int(strategy.localsgd_configs.begin_step)
        self._step_i = 0

    @classmethod
    def can_apply(cls, strategy, hcg=None):
        return bool(strategy.localsgd)

    @no_grad()
    def step(self):
        self._inner_opt.step()
        self._step_i += 1
        if self._step_i >= self.begin_step and self._step_i % self.k_steps == 0:
            self._sync_params()

    def _sync_params(self):
        from .. import collective
        from ..env import get_world_size

        world = (self._hcg.get_data_parallel_world_size()
                 if self._hcg is not None else get_world_size())
        if world <= 1:
            return
        group = self._hcg.get_data_parallel_group() if self._hcg else None
        for p in self._inner_opt._parameter_list:
            collective.all_reduce(p, group=group)
            p.set_value(p._data / world)


class DGCOptimizer(MetaOptimizerBase):
    """Deep gradient compression: before each step keep only the top-s
    fraction of each grad's entries (reference dgc_optimizer.py /
    operators/dgc_op). The momentum-correction residual is kept locally."""

    name = "dgc"
    conflicts = ("localsgd",)

    def __init__(self, inner, strategy, hcg=None):
        super().__init__(inner, strategy, hcg)
        cfg = strategy.dgc_configs
        self.rampup_begin_step = int(cfg.rampup_begin_step)
        self.sparsity = list(cfg.sparsity) or [0.999]
        self._step_i = 0
        self._residual = {}

    @classmethod
    def can_apply(cls, strategy, hcg=None):
        return bool(strategy.dgc)

    @no_grad()
    def step(self):
        import jax.numpy as jnp

        self._step_i += 1
        if self._step_i > self.rampup_begin_step:
            s = self.sparsity[min(len(self.sparsity) - 1, self._step_i - 1)]
            for p in self._inner_opt._parameter_list:
                if p.grad is None:
                    continue
                g = p.grad._data + self._residual.get(id(p), 0.0)
                k = max(1, int(round(g.size * (1.0 - s))))
                flat = jnp.abs(g.reshape(-1))
                thresh = jnp.sort(flat)[-k]
                mask = (jnp.abs(g) >= thresh).astype(g.dtype)
                self._residual[id(p)] = g * (1.0 - mask)
                p.grad.set_value(g * mask)
        self._inner_opt.step()


class FP16AllReduceOptimizer(MetaOptimizerBase):
    """Halve allreduce bytes by casting grads to bf16 before the dp sync
    (reference fp16_allreduce meta-optimizer casts to fp16 for NCCL)."""

    name = "fp16_allreduce"

    @classmethod
    def can_apply(cls, strategy, hcg=None):
        return bool(getattr(strategy, "fp16_allreduce", False))

    @no_grad()
    def step(self):
        import jax.numpy as jnp

        for p in self._inner_opt._parameter_list:
            if p.grad is not None and p.grad._data.dtype == jnp.float32:
                p.grad.set_value(
                    p.grad._data.astype(jnp.bfloat16).astype(jnp.float32))
        self._inner_opt.step()


class LarsOptimizer(MetaOptimizerBase):
    """Swap Momentum/SGD for LARS (reference lars_optimizer.py)."""

    name = "lars"

    @classmethod
    def can_apply(cls, strategy, hcg=None):
        return bool(strategy.lars)

    @staticmethod
    def rebuild(inner, strategy):
        from ... import optimizer as opt_mod

        if inner._rule not in ("sgd", "momentum"):
            return inner
        cfg = strategy.lars_configs
        return opt_mod.Lars(
            learning_rate=inner._learning_rate,
            momentum=inner._hyper.get("momentum", 0.9)
            if hasattr(inner, "_hyper") else 0.9,
            lars_coeff=cfg.lars_coeff,
            lars_weight_decay=cfg.lars_weight_decay,
            epsilon=cfg.epsilon,
            exclude_from_weight_decay=cfg.exclude_from_weight_decay,
            parameters=inner._parameter_list, grad_clip=inner._grad_clip)


class LambOptimizer(MetaOptimizerBase):
    """Swap Adam/AdamW for LAMB (reference lamb_optimizer.py)."""

    name = "lamb"

    @classmethod
    def can_apply(cls, strategy, hcg=None):
        return bool(strategy.lamb)

    @staticmethod
    def rebuild(inner, strategy):
        from ... import optimizer as opt_mod

        if inner._rule not in ("adam", "adamw"):
            return inner
        cfg = strategy.lamb_configs
        exclude = list(cfg.exclude_from_weight_decay)

        def exclude_fn(p):
            return any(s in (getattr(p, "name", "") or "") for s in exclude)

        return opt_mod.Lamb(
            learning_rate=inner._learning_rate,
            lamb_weight_decay=cfg.lamb_weight_decay,
            parameters=inner._parameter_list, grad_clip=inner._grad_clip,
            exclude_from_weight_decay_fn=exclude_fn if exclude else None)


class ShardingOptimizer(MetaOptimizerBase):
    """Marker: optimizer-state sharding happens inside the engine's pjit step
    (opt-state arrays laid out over the sharding axis — TrainStepEngine reads
    strategy.sharding), replacing the reference's program-segmenting rewrite
    (sharding_optimizer.py:569)."""

    name = "sharding"

    @classmethod
    def can_apply(cls, strategy, hcg=None):
        return bool(strategy.sharding)


class RawProgramOptimizer(MetaOptimizerBase):
    """Plain dp allreduce mode (reference raw_program_optimizer.py). The eager
    dp path already allreduces through HybridParallelOptimizer/DataParallel;
    under the engine the grads are reduced by GSPMD — nothing to rewrite."""

    name = "raw_program"

    @classmethod
    def can_apply(cls, strategy, hcg=None):
        return bool(getattr(strategy, "without_graph_optimization", False))


class DpSyncOptimizer(MetaOptimizerBase):
    """Innermost dp gradient allreduce: runs AFTER every grad-transforming
    meta-optimizer (dgc sparsification, fp16 cast) and only when an update
    actually happens (gradient merge boundaries) — the ordering the reference
    gets by rewriting comm ops into the program. LocalSGD replaces it."""

    name = "dp_sync"

    @no_grad()
    def step(self):
        from .utils import fused_allreduce_gradients

        if self._hcg is not None and \
                self._hcg.get_data_parallel_world_size() > 1:
            fused_allreduce_gradients(self._inner_opt._parameter_list, self._hcg)
        self._inner_opt.step()


# innermost-first chain order: grad-transforming comm optimizers sit just
# outside dp_sync; step-frequency optimizers (gradient merge) outside those;
# amp outermost (reference strategy_compiler ordering, inverted because we
# wrap instead of rewrite)
_META_OPTIMIZERS = [
    FP16AllReduceOptimizer,
    DGCOptimizer,
    LocalSGDOptimizer,
    ShardingOptimizer,
    GradientMergeOptimizer,
    RecomputeOptimizer,
    AMPOptimizer,
    RawProgramOptimizer,
]


class StrategyCompiler:
    """Pick applicable meta-optimizers, drop conflicting ones (first wins, like
    the reference's _disable_strategy propagation), order and chain them."""

    def compile(self, optimizer, strategy, hcg=None, model=None):
        applied: List[str] = []
        disabled: set = set()

        # optimizer-rule swaps first (they replace, not wrap)
        if LarsOptimizer.can_apply(strategy, hcg):
            rebuilt = LarsOptimizer.rebuild(optimizer, strategy)
            if rebuilt is not optimizer:
                optimizer = rebuilt
                applied.append("lars")
        if LambOptimizer.can_apply(strategy, hcg):
            rebuilt = LambOptimizer.rebuild(optimizer, strategy)
            if rebuilt is not optimizer:
                optimizer = rebuilt
                applied.append("lamb")

        wrappers = []
        for cls in _META_OPTIMIZERS:
            if cls.name in ("lars", "lamb"):
                continue
            if cls.name in disabled or not cls.can_apply(strategy, hcg):
                continue
            disabled.update(cls.conflicts)
            wrappers.append(cls)

        handles_dp_sync = False
        if any(w.name not in ("sharding", "raw_program") for w in wrappers):
            # a real chain exists: dp sync moves innermost (LocalSGD replaces it)
            if not any(w.name == "localsgd" for w in wrappers) and \
                    hcg is not None and hcg.get_data_parallel_world_size() > 1:
                optimizer = DpSyncOptimizer(optimizer, strategy, hcg)
            handles_dp_sync = True

        for cls in wrappers:
            wrapper = cls(optimizer, strategy, hcg)
            if isinstance(wrapper, RecomputeOptimizer) and model is not None:
                wrapper.enable_on(model)
            if cls.name in ("sharding", "raw_program"):
                # markers: engine-level behavior, no wrapping needed
                applied.append(cls.name)
                continue
            optimizer = wrapper
            applied.append(cls.name)

        if handles_dp_sync:
            optimizer._handles_dp_sync = True
        return optimizer, applied

"""Elastic training manager: fault tolerance + scale in/out over the TCPStore.

Reference: python/paddle/distributed/fleet/elastic/manager.py:130 — etcd node
registry under a job prefix with TTL lease heartbeat (:247-257), prefix watches
for join/leave (:245), endpoint re-layout, launcher restart. TPU equivalent: the
same registry over our C++ TCPStore (keys `<job>/nodes/<id>` holding the last
heartbeat timestamp; staleness > ttl ≙ lease expiry — the store has no server-side
TTL so the watcher applies it on read), plus hooks for slice preemption notices.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    def __init__(self, store, job_id: str, np: int, host: str,
                 heartbeat_interval: float = 2.0, ttl: float = 10.0,
                 min_np: Optional[int] = None, max_np: Optional[int] = None):
        """np: target node count; min_np/max_np bound the scale in/out window
        (reference parses `np` ranges like "2:4" the same way)."""
        self.store = store
        self.job_id = job_id
        self.np = np
        self.min_np = min_np or np
        self.max_np = max_np or np
        self.host = host
        self.heartbeat_interval = heartbeat_interval
        self.ttl = ttl
        self._prefix = f"{job_id}/nodes/"
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._watch_thread: Optional[threading.Thread] = None
        self._callbacks: List[Callable[[List[str]], None]] = []
        self._last_members: List[str] = []
        self._beat_seq = 0
        self._hb_store_obj = None
        # node -> (last seen heartbeat seq, local monotonic time it changed);
        # liveness is judged by seq *progress* against the reader's own clock, so
        # cross-node wall-clock skew cannot expire a healthy node's lease
        self._seen: Dict[str, tuple] = {}

    # ---- membership registry (reference manager.py:247 lease/heartbeat) ----
    def _hb_store(self):
        """Heartbeats get their OWN store connection: the main connection
        serializes requests, so a long blocking wait/barrier there would starve
        the lease and peers would declare this healthy node dead."""
        if self._hb_store_obj is None:
            from ..store import TCPStore

            s = self.store
            if isinstance(s, TCPStore):
                # also on the master node: connect a second CLIENT to its own
                # server, so its heartbeats never queue behind a blocking wait
                try:
                    self._hb_store_obj = TCPStore(s.host, s.port, is_master=False,
                                                  world_size=s.world_size,
                                                  timeout=s.timeout)
                except Exception:
                    self._hb_store_obj = s
            else:
                self._hb_store_obj = s
        return self._hb_store_obj

    def register(self) -> None:
        self._beat()
        self._hb_thread = threading.Thread(target=self._hb_loop, daemon=True)
        self._hb_thread.start()

    def _beat(self) -> None:
        self._beat_seq += 1
        self._hb_store().set(self._prefix + self.host,
                             json.dumps({"seq": self._beat_seq,
                                         "host": self.host}))

    def _hb_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            try:
                self._beat()
            except Exception:
                pass  # store briefly unreachable; next beat retries

    def alive_nodes(self) -> List[str]:
        """Nodes whose heartbeat seq advanced within the last ttl seconds (as
        measured on THIS node's monotonic clock — no cross-node clock compare)."""
        now = time.monotonic()
        alive = []
        present = set()
        for key in self.store.list_keys(self._prefix):
            try:
                rec = json.loads(self.store.get(key, wait=False))
            except (KeyError, ValueError):
                continue
            node = key[len(self._prefix):]
            present.add(node)
            seen = self._seen.get(node)
            if seen is None or seen[0] != rec["seq"]:
                self._seen[node] = (rec["seq"], now)
                alive.append(node)
            elif now - seen[1] <= self.ttl:
                alive.append(node)
            else:
                self.store.delete_key(key)  # lease expired: no progress for > ttl
        for gone in set(self._seen) - present:
            del self._seen[gone]
        return sorted(alive)

    # ---- watch (reference manager.py:245 etcd watch -> callbacks) ----
    def watch(self, callback: Callable[[List[str]], None]) -> None:
        self._callbacks.append(callback)
        if self._watch_thread is None:
            self._last_members = self.alive_nodes()
            self._watch_thread = threading.Thread(target=self._watch_loop,
                                                  daemon=True)
            self._watch_thread.start()

    def _watch_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            try:
                members = self.alive_nodes()
            except Exception:
                continue
            if members != self._last_members:
                self._last_members = members
                for cb in self._callbacks:
                    cb(members)

    # ---- scale decisions (reference manager.py exit/restart logic) ----
    def health_status(self) -> str:
        n = len(self.alive_nodes())
        if n == self.np:
            return ElasticStatus.COMPLETED
        if self.min_np <= n < self.np:
            return ElasticStatus.RESTART  # scale-in: relaunch with fewer nodes
        if n < self.min_np:
            return ElasticStatus.HOLD  # wait for nodes to rejoin
        return ElasticStatus.RESTART  # scale-out

    def wait_for_np(self, np: Optional[int] = None, timeout: float = 60.0) -> bool:
        target = np or self.np
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.alive_nodes()) >= target:
                return True
            time.sleep(self.heartbeat_interval / 2)
        return False

    def endpoints_layout(self) -> Dict[str, int]:
        """Deterministic node -> rank assignment after membership change
        (reference re-writes PADDLE_TRAINER_ENDPOINTS the same way)."""
        return {h: i for i, h in enumerate(self.alive_nodes())}

    def exit(self) -> None:
        self._stop.set()
        try:
            self.store.delete_key(self._prefix + self.host)
        except Exception:
            pass
        for t in (self._hb_thread, self._watch_thread):
            if t is not None:
                t.join(timeout=2 * self.heartbeat_interval)

    # ---- preemption notices (SURVEY §5.3: TPU slices get maintenance/preempt
    # notices; the store key is the transport — on real infra a metadata-server
    # watcher writes the same key) ----
    def announce_preemption(self, host: Optional[str] = None,
                            deadline_s: float = 30.0) -> None:
        """Publish a preemption notice for `host` (default: this node)."""
        target = host or self.host
        self.store.set(f"{self.job_id}/preempt/{target}",
                       json.dumps({"host": target, "deadline_s": deadline_s,
                                   "seq": self._beat_seq}))

    def preemption_notice(self, host: Optional[str] = None) -> Optional[dict]:
        """The pending notice for `host` (default: this node), or None."""
        target = host or self.host
        try:
            raw = self.store.get(f"{self.job_id}/preempt/{target}", wait=False)
        except KeyError:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None

    def clear_preemption(self, host: Optional[str] = None) -> None:
        try:
            self.store.delete_key(f"{self.job_id}/preempt/{host or self.host}")
        except Exception:
            pass

    def on_preemption(self, callback: Callable[[dict], None],
                      clear: bool = False) -> None:
        """Run `callback(notice)` (checkpoint-and-drain hook) when a notice for
        this node appears. Fires once per distinct notice.

        clear=False (default) leaves the store key in place: the LAUNCHER is
        the notice's owner and deletes it after draining the pod — a worker
        clearing it would starve the launcher's own poll and skip the
        respawn/re-layout. Pass clear=True only when no launcher is watching.
        """
        def _poll():
            seen = None
            while not self._stop.wait(self.heartbeat_interval / 2):
                notice = self.preemption_notice()
                if notice is not None and notice != seen:
                    seen = notice
                    try:
                        callback(notice)
                    except Exception:  # a failing checkpoint hook must not
                        import traceback  # kill the watcher: later notices
                        #                   still need handling
                        traceback.print_exc()
                    finally:
                        if clear:
                            self.clear_preemption()
        t = threading.Thread(target=_poll, daemon=True)
        t.start()


def preemption_requested() -> bool:
    """Trainer-side check: True when the launcher (or infra) has signalled
    this worker to checkpoint and exit (reference elastic manager signals
    workers before restart; on TPU this mirrors the slice maintenance-notice
    contract). The launcher points PADDLE_ELASTIC_PREEMPT_FILE at a per-worker
    flag file it touches when a preemption notice arrives."""
    import os

    path = os.environ.get("PADDLE_ELASTIC_PREEMPT_FILE")
    return bool(path) and os.path.exists(path)

"""HybridParallelOptimizer.

Reference: fleet/meta_parallel/dygraph_optimizer/hybrid_parallel_optimizer.py:170 —
wraps the inner optimizer so global-norm grad clip spans mp/pp groups (:51) and grads
are fused-allreduced across dp before step.

TPU-native: inside the engine's pjit step, clipping already sees the full global grads
(single program), so this wrapper only matters for the eager multi-process path and for
API parity.
"""
from __future__ import annotations

from ...core.autograd import no_grad
from .utils import fused_allreduce_gradients


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    @no_grad()
    def step(self):
        # a meta-optimizer chain moves dp sync innermost (after dgc/fp16 grad
        # transforms, on gradient-merge boundaries only) — don't double-sync
        if not getattr(self._inner_opt, "_handles_dp_sync", False) and \
                self._hcg is not None and \
                self._hcg.get_data_parallel_world_size() > 1:
            fused_allreduce_gradients(self._inner_opt._parameter_list, self._hcg)
        self._inner_opt.step()

    def clear_grad(self, *a, **kw):
        self._inner_opt.clear_grad(*a, **kw)

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, []

"""Fleet facade.

Reference: python/paddle/distributed/fleet/base/fleet_base.py:139 (Fleet singleton:
init/init_parallel_env, distributed_model:932, distributed_optimizer:875, minimize:1438)
plus role makers. The TPU build keeps the exact user surface; underneath, init builds the
HybridCommunicateGroup mesh and distributed_model wraps by strategy
(fleet_base.py:1038-1061 dispatch preserved).
"""
from __future__ import annotations

from typing import Optional

from ... import nn
from ..env import ParallelEnv, get_rank, get_world_size, init_parallel_env
from ..mesh import (
    HybridCommunicateGroup, get_hybrid_communicate_group,
    set_hybrid_communicate_group,
)
from .distributed_strategy import DistributedStrategy
from . import utils  # noqa: F401
from .utils import recompute  # noqa: F401
from .hybrid_parallel_optimizer import HybridParallelOptimizer


class RoleMakerBase:
    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective
        self._env = ParallelEnv()

    def worker_index(self):
        return self._env.rank

    def worker_num(self):
        return self._env.world_size

    def is_first_worker(self):
        return self._env.rank == 0

    def is_worker(self):
        return True

    def is_server(self):
        return False


class PaddleCloudRoleMaker(RoleMakerBase):
    pass


class UserDefinedRoleMaker(RoleMakerBase):
    pass


class Fleet:
    def __init__(self):
        self._role_maker = None
        self._strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._is_initialized = False

    # ---- init ----
    def init(self, role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
        self._role_maker = role_maker or PaddleCloudRoleMaker(is_collective=is_collective)
        self._strategy = strategy or DistributedStrategy()
        env = init_parallel_env()
        hc = self._strategy.hybrid_configs
        self._hcg = HybridCommunicateGroup(
            dp_degree=hc.dp_degree,
            mp_degree=hc.mp_degree, pp_degree=hc.pp_degree,
            sharding_degree=hc.sharding_degree, sp_degree=hc.sep_degree,
            ep_degree=hc.ep_degree)
        set_hybrid_communicate_group(self._hcg)
        self._is_initialized = True
        return self

    def get_hybrid_communicate_group(self):
        return self._hcg

    @property
    def worker_index(self):
        return self._role_maker.worker_index

    def worker_num(self):
        return self._role_maker.worker_num()

    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_endpoints(self, to_string=False):
        eps = ParallelEnv().trainer_endpoints
        return ",".join(eps) if to_string else eps

    def barrier_worker(self):
        from .. import collective

        collective.barrier()

    # ---- model/optimizer wrapping (fleet_base.py:1038-1061) ----
    def distributed_model(self, model):
        from ..meta_parallel import DataParallel, PipelineLayer, PipelineParallel

        if not self._is_initialized:
            self.init()
        hcg = self._hcg
        if hcg.get_pipe_parallel_world_size() > 1:
            if isinstance(model, PipelineLayer):
                return PipelineParallel(model, hcg, self._strategy)
            if not getattr(model, "_pipeline_stacked", False):
                # pipeline-stacked models (e.g. GPTForPretrainingPipe) run the SPMD
                # schedule inside the engine and need no wrapper
                raise RuntimeError(
                    "pp_degree > 1 requires a PipelineLayer or a pipeline-stacked model")
        if hcg.get_parallel_mode() == "data_parallel" and hcg.nranks > 1:
            return DataParallel(model)
        # tensor/sharding/pipeline models execute through TrainStepEngine shardings;
        # params already carry dist_attrs — wrapper is identity for those modes
        return model

    def distributed_optimizer(self, optimizer, strategy=None, model=None):
        if strategy is not None:
            self._strategy = strategy
        if not self._is_initialized:
            self.init()
        # strategy -> meta-optimizer chain (reference strategy_compiler.py),
        # then the hybrid wrapper (dp grad sync + cross-group clip) outermost
        from .meta_optimizers import StrategyCompiler

        optimizer, applied = StrategyCompiler().compile(
            optimizer, self._strategy, self._hcg, model=model)
        self._applied_meta_list = applied
        return HybridParallelOptimizer(optimizer, self._hcg, self._strategy)

    def distributed_engine(self, model, optimizer, loss_fn=None,
                           auto=False, sample_batch=None, **kw):
        """TPU-native: build the fused pjit train step for this fleet config.

        auto=True (+ sample_batch): ignore the configured topology and let
        the planner (auto_parallel/planner.py — the reference planner.py/
        cost_model.py analogue) pick the cheapest feasible hybrid config by
        AOT-compiling candidates; this fleet is re-initialized on the winner.
        """
        from ..engine import TrainStepEngine

        inner = optimizer
        while hasattr(inner, "_inner_opt"):  # unwrap hybrid + meta chain
            inner = inner._inner_opt
        if auto:
            if sample_batch is None:
                raise ValueError(
                    "distributed_engine(auto=True) needs sample_batch= to "
                    "compile candidate topologies against")
            from ..auto_parallel.planner import plan

            opt_cls, opt_kw = type(inner), dict(
                learning_rate=inner.get_lr(),
                parameters=model.parameters())
            best, results = plan(
                lambda: model,  # compile-only: the model is never executed
                lambda m: opt_cls(**opt_kw),
                sample_batch, loss_fn=loss_fn)
            strategy = DistributedStrategy()  # fresh: hybrid_configs merge
            strategy.hybrid_configs = dict(best)
            if best.get("sharding_degree", 1) > 1:
                strategy.sharding = True
            from ..mesh import set_hybrid_communicate_group

            set_hybrid_communicate_group(None)
            self._is_initialized = False
            self.init(is_collective=True, strategy=strategy)
            self.plan_results = results
        return TrainStepEngine(model, inner, loss_fn=loss_fn, hcg=self._hcg,
                               strategy=self._strategy, **kw)

    def minimize(self, optimizer, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return optimizer.minimize(loss)

    # ---- checkpoint (fleet_base.py:824) ----
    def save_persistables(self, executor_or_model, dirname, main_program=None, mode=0):
        from ...framework import io as fio

        if hasattr(executor_or_model, "state_dict"):
            fio.save(executor_or_model.state_dict(), dirname + "/model.pdparams")

    def save(self, dirname, **kwargs):
        pass


fleet = Fleet()

# module-level convenience mirroring `from paddle.distributed import fleet; fleet.init(...)`
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
distributed_engine = fleet.distributed_engine
worker_num = fleet.worker_num
is_first_worker = fleet.is_first_worker
barrier_worker = fleet.barrier_worker
save_persistables = fleet.save_persistables
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group


def worker_index():
    return get_rank()

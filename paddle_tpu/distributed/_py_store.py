"""Pure-Python TCP store fallback (same semantics as core/native/tcp_store.cc).

Used only when the C++ toolchain is unavailable. Wire protocol is private to this
pair (server+client always come from the same implementation on a host because
rank 0 hosts the server) so it can stay simple: pickled request/response frames.
"""
from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
import time
from typing import Dict, List


def _send_frame(sock, obj) -> None:
    data = pickle.dumps(obj)
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_frame(sock):
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("store connection closed")
        hdr += chunk
    (n,) = struct.unpack(">I", hdr)
    data = b""
    while len(data) < n:
        chunk = sock.recv(n - len(data))
        if not chunk:
            raise ConnectionError("store connection closed")
        data += chunk
    return pickle.loads(data)


class PyStoreServer:
    def __init__(self, port: int = 0):
        self._data: Dict[str, bytes] = {}
        self._cond = threading.Condition()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        req = _recv_frame(self.request)
                    except (ConnectionError, EOFError):
                        return
                    _send_frame(self.request, outer._handle(req))

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server(("0.0.0.0", port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()

    def _handle(self, req):
        op = req["op"]
        key = req.get("key", "")
        with self._cond:
            if op == "set":
                self._data[key] = req["value"]
                self._cond.notify_all()
                return {"status": 0}
            if op == "get":
                if req.get("wait", True):
                    deadline = time.monotonic() + req.get("timeout", 900.0)
                    while key not in self._data:
                        if not self._cond.wait(min(1.0, deadline - time.monotonic())):
                            if time.monotonic() >= deadline:
                                return {"status": -1}
                if key not in self._data:
                    return {"status": -1}
                return {"status": 0, "value": self._data[key]}
            if op == "add":
                cur = int(self._data.get(key, b"0"))
                new = cur + req["delta"]
                self._data[key] = str(new).encode()
                self._cond.notify_all()
                return {"status": 0, "value": new}
            if op == "wait":
                deadline = time.monotonic() + req.get("timeout", 900.0)
                while key not in self._data:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not self._cond.wait(min(1.0, remaining)):
                        if time.monotonic() >= deadline:
                            return {"status": -1}
                return {"status": 0}
            if op == "num_keys":
                return {"status": 0, "value": len(self._data)}
            if op == "delete":
                return {"status": 0, "value": int(self._data.pop(key, None)
                                                  is not None)}
            if op == "list_prefix":
                return {"status": 0,
                        "value": [k for k in self._data if k.startswith(key)]}
        return {"status": -22}

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class PyStoreClient:
    def __init__(self, host: str, port: int, timeout: float):
        self._lock = threading.Lock()
        deadline = time.monotonic() + timeout
        while True:
            try:
                self._sock = socket.create_connection((host, port), timeout=5.0)
                self._sock.settimeout(None)
                self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                break
            except OSError:
                if time.monotonic() >= deadline:
                    raise TimeoutError(f"cannot connect to store {host}:{port}")
                time.sleep(0.05)

    def _call(self, **req):
        with self._lock:
            _send_frame(self._sock, req)
            return _recv_frame(self._sock)

    def set(self, key: str, value: bytes) -> None:
        self._call(op="set", key=key, value=value)

    def get(self, key: str, wait: bool = True, timeout: float = 900.0) -> bytes:
        resp = self._call(op="get", key=key, wait=wait, timeout=timeout)
        if resp["status"] != 0:
            if wait:
                raise TimeoutError(f"get({key!r}) timed out after {timeout}s")
            raise KeyError(key)
        return resp["value"]

    def add(self, key: str, delta: int) -> int:
        return self._call(op="add", key=key, delta=delta)["value"]

    def wait(self, key: str, timeout: float) -> None:
        resp = self._call(op="wait", key=key, timeout=timeout)
        if resp["status"] != 0:
            raise TimeoutError(f"wait({key!r}) timed out")

    def num_keys(self) -> int:
        return self._call(op="num_keys")["value"]

    def delete(self, key: str) -> bool:
        return bool(self._call(op="delete", key=key)["value"])

    def list_prefix(self, prefix: str) -> List[str]:
        return self._call(op="list_prefix", key=prefix)["value"]

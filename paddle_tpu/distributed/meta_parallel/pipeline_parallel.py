"""PipelineParallel facade — the reference's dygraph pipeline engine API.

Reference: fleet/meta_parallel/pipeline_parallel.py:31 (PipelineParallel,
forward_backward_pipeline:81, train_batch:153) driving 1F1B over NCCL p2p
(p2p_communication.py:26 initialize_p2p_groups, :39 SendRecvMeta, :217 _p2p_helper).

TPU-native: two execution paths, same user API.

1. **SPMD path** (the perf path): when the wrapped model is pipeline-stacked (e.g.
   GPTForPretrainingPipe), the whole 1F1B schedule is inside ONE pjit program via
   distributed/pipeline_schedule.spmd_pipeline — use TrainStepEngine/fleet.
   distributed_engine, not this class.

2. **Eager facade** (this class): `train_batch` splits the batch into
   `accumulate_steps` micro-batches and runs forward/backward per micro-batch with
   gradient accumulation. On a single controller this is numerically IDENTICAL to the
   reference's 1F1B (1F1B reorders micro-batch work across ranks but computes the same
   accumulated gradient); stage overlap comes from the SPMD path. The reference's
   shape-negotiation handshake (SendRecvMeta) has no equivalent: XLA shapes are static.
"""
from __future__ import annotations

import contextlib

from ... import nn
from ...core.tensor import Tensor
from ..mesh import get_hybrid_communicate_group


class PipelineParallel(nn.Layer):
    def __init__(self, layers, hcg=None, strategy=None):
        super().__init__()
        self._layers = layers  # __setattr__ auto-registers the sublayer
        self._hcg = hcg or get_hybrid_communicate_group()
        self._strategy = strategy
        pc = getattr(strategy, "pipeline_configs", None)
        self.accumulate_steps = int(getattr(pc, "accumulate_steps", 1) or 1)
        self.micro_batch_size = getattr(pc, "micro_batch_size", None)
        self.total_loss = None

    def _num_micro(self, data):
        # accumulate_steps wins when set; otherwise a non-default micro_batch_size
        # derives the split (reference: micro_batch_size * accumulate_steps = batch)
        if self.accumulate_steps > 1:
            return self.accumulate_steps
        if self.micro_batch_size and self.micro_batch_size > 1:
            inputs = data[0] if isinstance(data, (tuple, list)) else data
            b = inputs.shape[0]
            if b % self.micro_batch_size != 0:
                raise ValueError(
                    f"batch {b} not divisible by micro_batch_size "
                    f"{self.micro_batch_size}")
            return b // self.micro_batch_size
        return self.accumulate_steps

    # reference pipeline_parallel.py:153
    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        from ...core.autograd import no_grad

        with no_grad():
            inputs, labels = self._load_micro_batches(data, 1)[0]
            out = self._layers(inputs)
            if compute_loss and hasattr(self._layers, "loss"):
                return self._layers.loss(out, labels)
            return out

    # reference pipeline_parallel.py:81
    def forward_backward_pipeline(self, data, scaler=None):
        micros = self._load_micro_batches(data, self._num_micro(data))
        n = len(micros)
        total = None
        for inputs, labels in micros:
            out = self._layers(inputs)
            if hasattr(self._layers, "loss") and labels is not None:
                loss = self._layers.loss(out, labels)
            else:
                loss = out
            loss = loss / n
            (scaler.scale(loss) if scaler is not None else loss).backward()
            total = loss.detach() if total is None else total + loss.detach()
        self.total_loss = total
        return total

    def _load_micro_batches(self, data, n):
        if isinstance(data, (tuple, list)):
            inputs, labels = data[0], data[1] if len(data) > 1 else None
        else:
            inputs, labels = data, None

        def split(t):
            if t is None:
                return [None] * n
            b = t.shape[0]
            if b % n != 0:
                raise ValueError(f"batch {b} not divisible by accumulate_steps {n}")
            mb = b // n
            return [t[i * mb:(i + 1) * mb] for i in range(n)]

        return list(zip(split(inputs), split(labels)))

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    @contextlib.contextmanager
    def no_sync(self):
        yield  # grad sync happens in optimizer.step / engine; nothing to suppress


class PipelineParallelWithInterleave(PipelineParallel):
    """Interleaved (virtual-stage) schedule; identical numerics on the eager
    facade. The REAL interleaved scheduler is the SPMD path:
    GPTForPretrainingPipe(num_virtual_stages=V) runs
    pipeline_schedule.spmd_pipeline_interleaved — a static circular schedule
    where each rank holds V stage chunks and the bubble shrinks to ~(P-1)
    ticks total instead of V*(P-1) (reference SectionWorker interleaving,
    device_worker.h:615)."""

from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)
from .parallel_layers import get_rng_state_tracker, model_parallel_random_seed  # noqa: F401
from .data_parallel import DataParallel  # noqa: F401
from .pp_layers import LayerDesc, PipelineLayer, SharedLayerDesc  # noqa: F401
from .pipeline_parallel import (  # noqa: F401
    PipelineParallel, PipelineParallelWithInterleave,
)
from .sharding import (  # noqa: F401
    GroupShardedOptimizerStage2, GroupShardedStage2, GroupShardedStage3,
    group_sharded_parallel,
)
from .moe import GShardGate, MoELayer, NaiveGate, SwitchGate  # noqa: F401

"""Model-parallel RNG state trees.

Reference: fleet/meta_parallel/parallel_layers/random.py — a tracker holding named RNG
states so dropout inside mp regions uses a local (per-mp-rank) seed while other randomness
stays globally synced. TPU-native: named generators from core.random; inside pjit, per-shard
variation comes from folding the axis index into the traced key (jax.random.fold_in).
"""
from __future__ import annotations

import contextlib

from ...core import random as random_mod

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_ = {}

    def add(self, name, seed):
        gen = random_mod.named_generator(name)
        gen.manual_seed(seed)
        self.states_[name] = gen

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            self.add(name, random_mod.default_generator().initial_seed() + 1024)
        # temporarily make the named generator the default draw source
        saved = random_mod._state.gen if hasattr(random_mod._state, "gen") else None
        random_mod._state.gen = self.states_[name]
        try:
            yield
        finally:
            random_mod._state.gen = saved


_tracker = RNGStatesTracker()


def get_rng_state_tracker():
    return _tracker


def model_parallel_random_seed(seed=None):
    import random as pyrandom

    seed = seed or (pyrandom.Random().randint(0, 2 ** 31))
    global_seed = seed
    local_seed = seed + 1024
    _tracker.reset()
    random_mod.seed(global_seed)
    _tracker.add(MODEL_PARALLEL_RNG, local_seed)

"""Pipeline-parallel layer description & segmentation.

Reference: fleet/meta_parallel/parallel_layers/pp_layers.py:159 (PipelineLayer —
LayerDesc list, uniform/param-size/custom segmentation, shared embeddings) and
pipeline_parallel.py:31 (1F1B schedule over p2p ops with shape-meta negotiation).

TPU-native execution model: a stage is a contiguous segment of the LayerDesc list; the
schedule runs as a single staged XLA program — microbatches move between stages with
`jax.lax.ppermute` over the 'pp' mesh axis inside shard_map (GPipe-style fill/drain loop
under `lax.scan`, see distributed/pipeline_schedule.py). There is no per-rank Python
scheduler process and no shape negotiation: shapes are static in the traced program
(the SendRecvMeta handshake of p2p_communication.py:39 is unnecessary by construction).

Eagerly (one chip) a PipelineLayer behaves as the plain sequential stack, so models
debug in dygraph unchanged.
"""
from __future__ import annotations

import math
from typing import Callable, List, Optional, Union

import numpy as np

from ... import nn
from ..mesh import get_hybrid_communicate_group


class LayerDesc:
    def __init__(self, layer_cls, *inputs, **kwargs):
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_cls, nn.Layer):
            raise TypeError(f"LayerDesc expects an nn.Layer subclass, got {layer_cls}")

    def build_layer(self):
        return self.layer_cls(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(nn.Layer):
    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, recompute_ctx=None,
                 num_virtual_pipeline_stages=None):
        super().__init__()
        hcg = topology or get_hybrid_communicate_group()
        self._num_stages = num_stages or (hcg.get_pipe_parallel_world_size() if hcg else 1)
        self._loss_fn = loss_fn
        self._seg_method = seg_method
        self._recompute_interval = recompute_interval

        self._descs: List = list(layers)
        self._shared = {}
        built = []
        for i, d in enumerate(self._descs):
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    built.append(("shared", d.layer_name, d.forward_func))
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
                    self.add_sublayer(f"shared_{d.layer_name}", layer)
                    built.append(("shared_first", d.layer_name, d.forward_func))
            elif isinstance(d, LayerDesc):
                layer = d.build_layer()
                self.add_sublayer(str(i), layer)
                built.append(("layer", layer, None))
            elif isinstance(d, nn.Layer):
                self.add_sublayer(str(i), d)
                built.append(("layer", d, None))
            elif callable(d):
                built.append(("func", d, None))
            else:
                raise TypeError(f"unsupported pipeline entry {d!r}")
        self._built = built
        self.segment_parts = self._segment_network(self._num_stages)

    # reference pp_layers.py:314
    def _segment_network(self, num_stages) -> List[int]:
        n = len(self._built)
        if self._seg_method == "uniform" or not self._seg_method:
            base = n // num_stages
            extra = n % num_stages
            bounds = [0]
            for s in range(num_stages):
                bounds.append(bounds[-1] + base + (1 if s < extra else 0))
            return bounds
        if self._seg_method.startswith("layer:"):
            cls_name = self._seg_method.split(":", 1)[1]
            marks = [i for i, (kind, l, _) in enumerate(self._built)
                     if kind == "layer" and type(l).__name__ == cls_name]
            if not marks:
                raise ValueError(f"seg_method {self._seg_method!r}: no layer matches")
            per = len(marks) / num_stages
            bounds = [0]
            for s in range(1, num_stages):
                bounds.append(marks[min(int(per * s), len(marks) - 1)])
            bounds.append(len(self._built))
            return bounds
        if self._seg_method == "param_size":
            sizes = []
            for kind, l, _ in self._built:
                if kind == "layer":
                    sizes.append(sum(p.size for p in l.parameters()))
                elif kind.startswith("shared"):
                    sizes.append(sum(p.size for p in self._shared_for(l).parameters()))
                else:
                    sizes.append(0)
            total = sum(sizes) or 1
            target = total / num_stages
            bounds = [0]
            acc = 0
            for i, s in enumerate(sizes):
                acc += s
                if acc >= target * len(bounds) and len(bounds) < num_stages:
                    bounds.append(i + 1)
            while len(bounds) < num_stages:
                bounds.append(len(self._built))
            bounds.append(len(self._built))
            return bounds[: num_stages + 1]
        raise ValueError(f"unknown seg_method {self._seg_method!r}")

    def _shared_for(self, name):
        return self._shared[name]

    def get_stage_layers(self, stage: int):
        lo, hi = self.segment_parts[stage], self.segment_parts[stage + 1]
        return self._built[lo:hi]

    def forward(self, x):
        for kind, item, ffn in self._built:
            if kind == "layer":
                x = item(x)
            elif kind == "func":
                x = item(x)
            else:  # shared / shared_first
                layer = self._shared[item]
                x = ffn(layer, x) if ffn is not None else layer(x)
        return x

    def loss(self, out, label):
        return self._loss_fn(out, label) if self._loss_fn else out

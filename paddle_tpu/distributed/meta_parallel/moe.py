"""Mixture-of-Experts layer (expert parallelism).

Reference: incubate MoELayer (moe_layer.py:233) with gshard/switch/naive gates
dispatching tokens to experts via global_scatter/global_gather all-to-all collectives
(operators/collective/global_scatter_op.*).

TPU-native: experts live stacked on the 'ep' mesh axis (one leading expert dim, sharded);
dispatch is dense einsum routing with capacity (the GShard formulation) so the whole layer
is one XLA program — `jax.lax.all_to_all` moves tokens between expert shards when traced
over the mesh. Dense-dispatch beats gather/scatter on TPU (MXU-friendly, static shapes).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ... import nn
from ...core import random as random_mod
from ...core.tensor import Tensor
from ...ops import activation as A
from ...ops import nn_functional as F
from ...core.dispatch import apply
from ..mesh import get_hybrid_communicate_group


class NaiveGate(nn.Layer):
    def __init__(self, d_model, num_experts):
        super().__init__()
        self.gate = nn.Linear(d_model, num_experts)

    def forward(self, x):
        return self.gate(x)


class GShardGate(NaiveGate):
    pass


class SwitchGate(NaiveGate):
    pass


class ExpertFFN(nn.Layer):
    """One expert's FFN weights, stored stacked over all experts for dense dispatch."""

    def __init__(self, num_experts, d_model, d_hidden, activation="gelu"):
        super().__init__()
        self.w1 = self.create_parameter((num_experts, d_model, d_hidden))
        self.b1 = self.create_parameter((num_experts, 1, d_hidden), is_bias=True)
        self.w2 = self.create_parameter((num_experts, d_hidden, d_model))
        self.b2 = self.create_parameter((num_experts, 1, d_model), is_bias=True)
        self.w1.dist_attr = P("ep", None, "mp")
        self.b1.dist_attr = P("ep", None, "mp")
        self.w2.dist_attr = P("ep", "mp", None)
        self.b2.dist_attr = P("ep", None, None)
        self.act = activation


class MoELayer(nn.Layer):
    """Top-k MoE with capacity-based dense dispatch (GShard).

    moe_group ≙ the 'ep' mesh axis; the reference's global_scatter/global_gather
    all-to-all pair is what GSPMD inserts between the token-sharded activations and
    the expert-sharded FFN weights.
    """

    def __init__(self, d_model, d_hidden, num_experts, top_k=2, capacity_factor=1.25,
                 gate=None, moe_group=None, mp_group=None, recompute_interval=0,
                 activation="gelu"):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.gate = gate if isinstance(gate, nn.Layer) else NaiveGate(d_model, num_experts)
        self.experts = ExpertFFN(num_experts, d_model, d_hidden, activation)

    def forward(self, x):
        """x: [batch, seq, d_model] (or [tokens, d_model])."""
        orig_shape = x.shape
        if len(orig_shape) == 3:
            from ...ops.manipulation import reshape

            tokens = reshape(x, (orig_shape[0] * orig_shape[1], orig_shape[2]))
        else:
            tokens = x
        n_tokens = tokens.shape[0]
        capacity = max(1, int(self.capacity_factor * n_tokens * self.top_k
                              / self.num_experts))

        logits = self.gate(tokens)  # [T, E]
        e = self.experts
        act_fn = {"gelu": jax.nn.gelu, "relu": jax.nn.relu, "silu": jax.nn.silu,
                  "swish": jax.nn.silu}[e.act]

        def kernel(tok, lg, w1, b1, w2, b2):
            probs = jax.nn.softmax(lg.astype(jnp.float32), axis=-1)
            # top-k routing with capacity (GShard dense dispatch)
            topv, topi = jax.lax.top_k(probs, self.top_k)          # [T, K]
            onehot = jax.nn.one_hot(topi, self.num_experts, dtype=jnp.float32)  # [T,K,E]
            # position of each (token, k) slot within its expert's queue: one shared
            # counter per expert across ALL k ranks (k-major order, so every 1st
            # choice outranks every 2nd choice — the GShard priority rule). A
            # per-k-column cumsum would hand the same capacity slot to a 1st-choice
            # and a 2nd-choice token and silently sum their embeddings.
            oh_k = jnp.swapaxes(onehot, 0, 1).reshape(self.top_k * onehot.shape[0],
                                                      self.num_experts)  # [K*T, E]
            pos_k = jnp.cumsum(oh_k, axis=0) - 1.0
            pos = jnp.swapaxes(
                pos_k.reshape(self.top_k, onehot.shape[0], self.num_experts), 0, 1)
            keep = (pos < capacity).astype(jnp.float32) * onehot
            gates = topv[..., None] * keep                          # [T,K,E]
            pos_idx = jnp.einsum("tke,tke->tk", pos, keep).astype(jnp.int32)
            cap_oh = jax.nn.one_hot(pos_idx, capacity, dtype=jnp.float32)  # [T,K,C]
            # dispatch tensor [T, E, C]
            dispatch = jnp.einsum("tke,tkc->tec", keep, cap_oh)
            combine = jnp.einsum("tke,tkc->tec", gates, cap_oh)
            expert_in = jnp.einsum("tec,td->ecd", dispatch, tok.astype(jnp.float32))
            h = jnp.einsum("ecd,edh->ech", expert_in, w1.astype(jnp.float32)) + b1
            h = act_fn(h)
            out = jnp.einsum("ech,ehd->ecd", h, w2.astype(jnp.float32)) + b2
            y = jnp.einsum("tec,ecd->td", combine, out)
            return y.astype(tok.dtype)

        out = apply("moe_dispatch", kernel,
                    [tokens, logits, e.w1, e.b1, e.w2, e.b2])
        if len(orig_shape) == 3:
            from ...ops.manipulation import reshape

            out = reshape(out, orig_shape)
        return out

"""Sharding / ZeRO API wrappers.

Reference: dygraph GroupSharded stage2/3 (group_sharded_optimizer_stage2.py:48,
group_sharded_stage2.py, group_sharded_stage3.py:58) — per-rank optimizer-state /
grad / param shards with hand-coded broadcast/reduce ops.

TPU-native: the engine realizes ZeRO by sharding the optimizer-state pytree over the
'sharding' mesh axis (stage 1/2) or the parameters themselves (stage 3) with
NamedShardings — XLA generates the reduce-scatter + all-gather pattern of ZeRO from the
shardings (arXiv:2004.13336). These wrappers keep the reference API and mark the intent
that TrainStepEngine reads.
"""
from __future__ import annotations

from jax.sharding import PartitionSpec as P

from ... import nn
from ..mesh import get_hybrid_communicate_group


class GroupShardedOptimizerStage2:
    """Wraps an optimizer: optimizer states will be sharded over the sharding
    axis. ``offload=True`` (reference group_sharded_optimizer_stage2.py:48)
    keeps optimizer state host-resident between steps: eager mode stores the
    state tuples as numpy (host RAM), the pjit engine places them with
    pinned_host memory-kind shardings — either way per-device HBM holds no
    optimizer state between steps."""

    def __init__(self, params, optim, group=None, offload=False, device="tpu", **kw):
        self._optim = optim
        self._params = list(params)
        self.offload = offload
        self.zero_stage = 2
        optim._zero_stage = 2
        optim._offload = bool(offload)

    def __getattr__(self, name):
        return getattr(self._optim, name)

    def step(self):
        self._optim.step()

    def clear_grad(self, *a, **k):
        self._optim.clear_grad(*a, **k)


class GroupShardedStage2(nn.Layer):
    def __init__(self, layer, sharding_optimizer, group=None, sync_buffers=False,
                 buffer_max_size=2 ** 23, auto_refresh_trainable=True, device="tpu"):
        super().__init__()
        self.add_sublayer("_layers", layer)
        object.__setattr__(self, "_layers", layer)
        self._sharding_optimizers = (
            sharding_optimizer if isinstance(sharding_optimizer, list)
            else [sharding_optimizer])

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)


class GroupShardedStage3(nn.Layer):
    """Stage 3: parameters themselves sharded over the sharding axis (fully sharded).
    Marks every (divisible) parameter with a 'sharding' dist_attr; the engine's
    NamedShardings then keep only 1/N of each param resident per device, with XLA
    all-gathering per-layer at use (the segment_size prefetch of the reference maps to
    XLA's scheduling)."""

    def __init__(self, layer, optimizer=None, group=None, sync_buffers=False,
                 device="tpu", segment_size=2 ** 20, pertrain_sync_models=True,
                 offload=False, sync_comm=False):
        super().__init__()
        self.add_sublayer("_layers", layer)
        object.__setattr__(self, "_layers", layer)
        self._optim = optimizer
        self.segment_size = segment_size
        hcg = get_hybrid_communicate_group()
        deg = hcg.degrees["sharding"] if hcg else 1
        if deg > 1:
            for p in layer.parameters():
                if getattr(p, "dist_attr", None) is not None:
                    continue  # TP-sharded params keep their annotation
                if p.size <= segment_size:
                    continue  # small params stay whole, exactly the reference
                    #           unslice rule (group_sharded_stage3.py:314
                    #           `p._numel() > self._segment_size`)
                shape = p.shape
                for i, s in enumerate(shape):
                    if s % deg == 0:
                        entries = [None] * len(shape)
                        entries[i] = "sharding"
                        p.dist_attr = P(*entries)
                        break
        if optimizer is not None:
            optimizer._zero_stage = 3
            optimizer._offload = bool(offload)

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, sd, *a, **k):
        return self._layers.set_state_dict(sd, *a, **k)


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm=False):
    """Reference entry python/paddle/distributed/sharding/group_sharded.py:40."""
    if level in ("os", "os_g"):
        opt = GroupShardedOptimizerStage2(model.parameters(), optimizer, group=group,
                                          offload=offload)
        model = GroupShardedStage2(model, opt, group=group, sync_buffers=sync_buffers,
                                   buffer_max_size=buffer_max_size)
        out_opt = opt
    elif level == "p_g_os":
        model = GroupShardedStage3(model, optimizer=optimizer, group=group,
                                   sync_buffers=sync_buffers, segment_size=segment_size,
                                   offload=offload, sync_comm=sync_comm)
        out_opt = optimizer
    else:
        raise ValueError(f"level must be os | os_g | p_g_os, got {level!r}")
    if scaler is not None:
        return model, out_opt, scaler
    return model, out_opt

"""Tensor-parallel layers.

Reference: fleet/meta_parallel/parallel_layers/mp_layers.py:30,97,170,249
(VocabParallelEmbedding / ColumnParallelLinear / RowParallelLinear / ParallelCrossEntropy),
which hold 1/N weight shards per rank and hand-code c_identity / mp_allreduce / c_concat
collectives around them.

TPU-native: each layer holds the FULL logical weight carrying a PartitionSpec `dist_attr`;
under pjit, GSPMD physically shards it and inserts exactly those collectives — the identity
(input broadcast), the row-parallel psum, the column-gather — from the sharding alone.
Eagerly on one chip the layers behave like their dense counterparts, so dygraph debugging
works unchanged. `gather_output` / `input_is_parallel` map to output/input sharding
constraints.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ... import nn
from ...core.tensor import Tensor
from ...jit import in_jit_trace
from ...ops import nn_functional as F


def _constraint(t: Tensor, spec: P) -> Tensor:
    """Apply a sharding constraint inside a mesh trace; no-op eagerly."""
    if in_jit_trace() and isinstance(t._data, jax.core.Tracer):
        try:
            return Tensor(jax.lax.with_sharding_constraint(t._data, spec),
                          stop_gradient=t.stop_gradient)
        except Exception:
            return t
    return t


class VocabParallelEmbedding(nn.Layer):
    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, mp_group=None,
                 name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        self.weight.dist_attr = P("mp", None)  # vocab rows sharded across mp
        self.weight.is_distributed = True

    def forward(self, x):
        return F.embedding(x, self.weight)


class ColumnParallelLinear(nn.Layer):
    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 gather_output=True, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        self.weight.dist_attr = P(None, "mp")  # output columns sharded
        self.weight.is_distributed = True
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias.dist_attr = P("mp")
            self.bias.is_distributed = True

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if not self.gather_output:
            # keep the hidden dim sharded: the paired RowParallelLinear
            # consumes it. Leading (batch/seq) dims stay UNCONSTRAINED — a
            # None would pin them REPLICATED, fighting the engine's
            # dp x sharding batch sharding; GSPMD then resolves the forward/
            # backward conflict with an involuntary full rematerialization
            # of the activation (VERDICT r3 #4).
            out = _constraint(out, P(*([P.UNCONSTRAINED]
                                       * (len(out.shape) - 1) + ["mp"])))
        return out


class RowParallelLinear(nn.Layer):
    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=nn.initializer.XavierNormal())
        self.weight.dist_attr = P("mp", None)  # input rows sharded; GSPMD psums output
        self.weight.is_distributed = True
        self.bias = None
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            # bias replicated (added once, after the implicit allreduce)

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        # only the feature dim is pinned dense (GSPMD inserts the psum over
        # 'mp' from the contracted-dim sharding); batch/seq dims stay
        # UNCONSTRAINED so dp/sharding/sp batch specs propagate through the
        # residual stream instead of being forced replicated here
        return _constraint(out, P(*([P.UNCONSTRAINED]
                                    * (len(out.shape) - 1) + [None])))


class ParallelCrossEntropy(nn.Layer):
    """Vocab-parallel softmax cross entropy (reference c_softmax_with_cross_entropy_op.cu):
    logits arrive vocab-sharded; the log-softmax reduction over vocab becomes a psum
    inserted by GSPMD from the shardings — no custom kernel needed."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.softmax_with_cross_entropy(input, label, ignore_index=self.ignore_index)


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True, weight_attr=None,
          bias_attr=None, inner_rank=0):
    """Reference paddle.distributed.split (collective.py:1520) — builds the matching
    parallel layer."""
    if operation == "linear":
        if axis == 0:
            layer = RowParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                      has_bias=bias_attr is not False)
        else:
            layer = ColumnParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out)
        return layer(x)
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1], weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"unsupported split operation {operation!r}")

"""DataParallel layer wrapper.

Reference: python/paddle/fluid/dygraph/parallel.py:413 — broadcasts initial params,
builds the C++ Reducer (bucketed grad allreduce, reducer.cc), exposes no_sync.

TPU-native: under the engine's pjit step, dp-grad sync IS the XLA allreduce that
jax.grad of the batch-sharded mean loss produces — already maximally fused (one
collective for all grads, the fuse_all_reduce_ops end-state). This wrapper therefore
(1) keeps the API (forward passthrough, no_sync, scale_loss), and (2) in eager
multi-process mode syncs grads per-bucket through the collective API after backward.
"""
from __future__ import annotations

import contextlib

from ... import nn
from ...core.tensor import Tensor
from .. import collective
from ..env import get_world_size
from ..mesh import get_hybrid_communicate_group


class DataParallel(nn.Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False, group=None):
        super().__init__()
        self.add_sublayer("_layers", layers)
        object.__setattr__(self, "_layers", layers)
        self.find_unused_parameters = find_unused_parameters
        self.comm_buffer_size = comm_buffer_size
        self._grads_synced = True
        self._enable_sync = True
        hcg = get_hybrid_communicate_group()
        self.group = group or (hcg.get_data_parallel_group() if hcg else None)
        self._world = self.group.nranks if self.group else get_world_size()

    def forward(self, *inputs, **kwargs):
        out = self._layers(*inputs, **kwargs)
        if self._world > 1 and self._enable_sync:
            self._grads_synced = False
        return out

    @contextlib.contextmanager
    def no_sync(self):
        prev = self._enable_sync
        self._enable_sync = False
        try:
            yield
        finally:
            self._enable_sync = prev

    def sync_gradients(self):
        """Bucketed grad allreduce (the Reducer's job). Called by optimizer glue or
        explicitly after backward in eager multi-rank mode."""
        if self._world <= 1:
            return
        for p in self._layers.parameters():
            if p.grad is not None:
                collective.all_reduce(p.grad, op=collective.ReduceOp.AVG, group=self.group)
        self._grads_synced = True

    def scale_loss(self, loss):
        return loss

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)


def sync_params_buffers(model, comm_group=None, src_rank=0, is_model_parallel=False):
    """Reference parallel.py:369 — broadcast initial params within a group. Under a
    single controller all replicas are born identical; multi-controller broadcasts."""
    if get_world_size() <= 1:
        return
    for p in model.parameters():
        collective.broadcast(p, src=src_rank, group=comm_group)

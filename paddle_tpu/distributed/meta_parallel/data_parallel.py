"""DataParallel layer wrapper.

Reference: python/paddle/fluid/dygraph/parallel.py:413 — broadcasts initial params,
builds the C++ Reducer (bucketed grad allreduce, reducer.cc), exposes no_sync.

TPU-native: under the engine's pjit step, dp-grad sync IS the XLA allreduce that
jax.grad of the batch-sharded mean loss produces — already maximally fused (one
collective for all grads, the fuse_all_reduce_ops end-state). This wrapper therefore
(1) keeps the API (forward passthrough, no_sync, scale_loss), and (2) in eager
multi-process mode syncs grads per-bucket through the collective API after backward.
"""
from __future__ import annotations

import contextlib

from ... import nn
from ...core.tensor import Tensor
from .. import collective
from ..env import get_world_size
from ..mesh import get_hybrid_communicate_group


class Reducer:
    """Bucketed fused gradient allreduce.

    Reference: paddle/fluid/imperative/reducer.cc (reducer.h:126) — trainable
    parameters are grouped in REVERSE registration order (grads become ready
    back-to-front during backward) into dtype-homogeneous buckets capped at
    ``comm_buffer_size`` MB (the final bucket re-split to
    ``last_comm_buffer_size`` MB so the front-of-model flush stays small).
    ``sync()`` flattens each bucket's grads into one buffer, runs ONE
    collective per bucket, and scatters the averaged slices back — so the
    collective count is ceil(total_grad_MB / comm_buffer_size), not the
    parameter count.

    ``find_unused_parameters=True`` contributes zeros for parameters whose
    grad is None (unused in this step's graph), keeping every rank's
    collective schedule identical even when usage diverges — and, like the
    reference, writes back the group average so a parameter used by ANY rank
    steps on ALL ranks. With False, grad-less parameters are skipped; as in
    the reference, ranks must then agree on which parameters got grads.
    """

    def __init__(self, parameters, group=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False):
        self.params = [p for p in parameters if not p.stop_gradient and p.size]
        self.group = group
        self.find_unused_parameters = find_unused_parameters
        self.n_collectives = 0  # stats for tests/profiling
        self._buckets = self._build_buckets(
            comm_buffer_size * (1 << 20), last_comm_buffer_size * (1 << 20))

    def _build_buckets(self, cap, last_cap):
        def nbytes(p):
            return int(p._data.nbytes)

        buckets, cur, cur_bytes, cur_dtype = [], [], 0, None
        for p in reversed(self.params):
            if cur and (cur_dtype != p._data.dtype
                        or cur_bytes + nbytes(p) > cap):
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(p)
            cur_bytes, cur_dtype = cur_bytes + nbytes(p), p._data.dtype
        if cur:
            buckets.append(cur)
        # keep ONLY the final flush (front-of-model params) small: peel params
        # off the end of the last bucket into one <=last_cap chunk (reference
        # reducer.cc applies the small group_size_limit to a single group)
        if len(buckets) > 0 and last_cap < cap and len(buckets[-1]) > 1:
            tail = list(buckets[-1])
            small, bytes_ = [], 0
            while tail and bytes_ + nbytes(tail[-1]) <= last_cap:
                bytes_ += nbytes(tail[-1])
                small.insert(0, tail.pop())
            if small and tail:
                buckets[-1] = tail
                buckets.append(small)
        return buckets

    def sync(self):
        """Allreduce-AVG every bucket; returns the number of collectives."""
        import jax.numpy as jnp

        if self.group is None or getattr(self.group, "nranks", 1) <= 1:
            return 0
        calls = 0
        for bucket in self._buckets:
            if self.find_unused_parameters:
                live = bucket
            else:
                live = [p for p in bucket if p.grad is not None]
            if not live:
                continue
            flats = [p.grad._data.reshape(-1) if p.grad is not None
                     else jnp.zeros((p.size,), p._data.dtype) for p in live]
            buf = Tensor(jnp.concatenate(flats) if len(flats) > 1 else flats[0])
            collective.all_reduce(buf, op=collective.ReduceOp.AVG,
                                  group=self.group)
            calls += 1
            offset = 0
            for p in live:
                p.grad = Tensor(
                    buf._data[offset:offset + p.size].reshape(tuple(p.shape)))
                offset += p.size
        self.n_collectives += calls
        return calls


class DataParallel(nn.Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False, group=None):
        super().__init__()
        self.add_sublayer("_layers", layers)
        object.__setattr__(self, "_layers", layers)
        self.find_unused_parameters = find_unused_parameters
        self.comm_buffer_size = comm_buffer_size
        self.last_comm_buffer_size = last_comm_buffer_size
        self._grads_synced = True
        self._enable_sync = True
        hcg = get_hybrid_communicate_group()
        self.group = group or (hcg.get_data_parallel_group() if hcg else None)
        self._world = self.group.nranks if self.group else get_world_size()
        self._reducer = Reducer(
            list(layers.parameters()), group=self.group,
            comm_buffer_size=comm_buffer_size,
            last_comm_buffer_size=last_comm_buffer_size,
            find_unused_parameters=find_unused_parameters)

    def forward(self, *inputs, **kwargs):
        out = self._layers(*inputs, **kwargs)
        if self._world > 1 and self._enable_sync:
            self._grads_synced = False
        return out

    @contextlib.contextmanager
    def no_sync(self):
        prev = self._enable_sync
        self._enable_sync = False
        try:
            yield
        finally:
            self._enable_sync = prev

    def sync_gradients(self):
        """Bucketed fused grad allreduce via the Reducer. Called by optimizer
        glue or explicitly after backward in eager multi-rank mode."""
        if self._world <= 1:
            return
        # params un/re-frozen (stop_gradient flipped) or added after wrapping
        # must not be silently skipped: rebuild buckets on membership change
        trainable = [p for p in self._layers.parameters()
                     if not p.stop_gradient and p.size]
        if [id(p) for p in trainable] != [id(p) for p in self._reducer.params]:
            stats = self._reducer.n_collectives
            self._reducer = Reducer(
                trainable, group=self.group,
                comm_buffer_size=self.comm_buffer_size,
                last_comm_buffer_size=self.last_comm_buffer_size,
                find_unused_parameters=self.find_unused_parameters)
            self._reducer.n_collectives = stats
        self._reducer.sync()
        self._grads_synced = True

    def scale_loss(self, loss):
        return loss

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)


def sync_params_buffers(model, comm_group=None, src_rank=0, is_model_parallel=False):
    """Reference parallel.py:369 — broadcast initial params within a group. Under a
    single controller all replicas are born identical; multi-controller broadcasts."""
    if get_world_size() <= 1:
        return
    for p in model.parameters():
        collective.broadcast(p, src=src_rank, group=comm_group)

"""Sequence/context parallelism: ring attention + Ulysses (all-to-all) attention.

The reference has NO sequence parallelism (SURVEY.md §2: "SP ... ABSENT in the
reference"); this is a first-class addition mirroring how dp/mp/pp compose via
HybridCommunicateGroup. The 'sp' mesh axis shards the sequence dimension of
activations; attention — the only op that mixes positions — is computed either by:

- **ring attention** (Liu et al., arXiv:2310.01889): each shard keeps its query
  block and rotates KV blocks around the ring with `jax.lax.ppermute` (ICI
  neighbor exchange), merging partial results with online-softmax (running max +
  logsumexp) so the full [s, s] score matrix never exists anywhere; or
- **Ulysses** (arXiv:2309.14509): `jax.lax.all_to_all` re-shards from
  sequence-split to head-split, runs dense local attention (the Pallas flash
  kernel), and re-shards back. Needs num_heads % sp == 0.

Both run inside `jax.shard_map` manual regions over ONLY the 'sp' axis
(`axis_names={'sp'}`) so dp/mp/sharding stay under GSPMD auto-sharding — the
TPU-native analogue of composing a new communicator into the 4-D topology.
"""
from __future__ import annotations

import contextlib
import functools
import math
import threading

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30

_state = threading.local()


def active() -> bool:
    """True when a sequence-parallel scope is installed (engine sets it when sp>1)."""
    return getattr(_state, "ctx", None) is not None


@contextlib.contextmanager
def sequence_parallel_scope(mesh, axis: str = "sp", impl: str = "ring"):
    """Route scaled_dot_product_attention to ring/Ulysses attention over `axis`."""
    prev = getattr(_state, "ctx", None)
    _state.ctx = (mesh, axis, impl)
    try:
        yield
    finally:
        _state.ctx = prev


def apply_ring_attention(q, k, v, causal: bool):
    """Entry used by ops.nn_functional when a scope is active. q,k,v: Tensors
    [b, s_global, h, d] (traced global arrays inside pjit)."""
    from ...core.dispatch import apply

    mesh, axis, impl = _state.ctx
    fn = ring_attention if impl == "ring" else ulysses_attention

    @jax.jit  # partial-manual shard_map must run under jit (inlined when already traced)
    def kernel(qa, ka, va):
        return fn(qa, ka, va, mesh=mesh, axis=axis, causal=causal)

    return apply("ring_attention", kernel, [q, k, v])


# ------------------------------------------------------------------- ring ----

def _chunk_attn(q, k, v, sm_scale, mask):
    """One KV-chunk attention returning unnormalized accum + row stats.

    q: [b, sq, h, d], k/v: [b, sk, h, d], mask: [sq, sk] bool or None.
    Returns (acc [b,h,sq,d] f32, m [b,h,sq] f32, l [b,h,sq] f32).
    """
    # matmul inputs stay in storage dtype (bf16 under amp) for MXU rate;
    # f32 accumulation + f32 softmax stats keep the numerics
    qt = jnp.swapaxes(q, 1, 2)  # [b,h,sq,d]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt,
                   preferred_element_type=jnp.float32) * sm_scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                              # [b,h,sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p.astype(vt.dtype), vt,
                     preferred_element_type=jnp.float32)
    return acc, m, l


def _ring_shard(q, k, v, *, axis, causal, sm_scale):
    """Per-shard ring attention body (runs under shard_map, manual over `axis`).

    q,k,v: [b, s_local, h, d] — this rank's sequence shard.
    """
    p_size = jax.lax.axis_size(axis)
    my_idx = jax.lax.axis_index(axis)
    b, s_loc, h, d = q.shape

    qpos = jnp.arange(s_loc)
    kpos = jnp.arange(s_loc)

    def body(t, carry):
        o_acc, m_acc, l_acc, kc, vc = carry

        def merge(stats, mask):
            o_acc, m_acc, l_acc = stats
            acc, m, l = _chunk_attn(q, kc, vc, sm_scale, mask)
            m_new = jnp.maximum(m_acc, m)
            a1 = jnp.exp(m_acc - m_new)
            a2 = jnp.exp(m - m_new)
            return (o_acc * a1[..., None] + acc * a2[..., None],
                    m_new, l_acc * a1 + l * a2)

        stats = (o_acc, m_acc, l_acc)
        if causal:
            kv_idx = (my_idx - t) % p_size  # whose block we currently hold
            qg = my_idx * s_loc + qpos[:, None]
            kg = kv_idx * s_loc + kpos[None, :]
            # 3-way block dispatch: entirely-future blocks skip compute, the
            # diagonal block masks within, past blocks run unmasked
            stats = jax.lax.cond(
                kv_idx > my_idx,
                lambda s: s,
                lambda s: jax.lax.cond(
                    kv_idx == my_idx,
                    lambda s2: merge(s2, qg >= kg),
                    lambda s2: merge(s2, None),
                    s),
                stats)
        else:
            stats = merge(stats, None)
        o_acc, m_acc, l_acc = stats
        # rotate kv to the next rank (neighbor exchange on the ICI ring)
        perm = [(i, (i + 1) % p_size) for i in range(p_size)]
        kc = jax.lax.ppermute(kc, axis, perm)
        vc = jax.lax.ppermute(vc, axis, perm)
        return o_acc, m_acc, l_acc, kc, vc

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    m0 = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_loc), jnp.float32)
    o, m, l, _, _ = jax.lax.fori_loop(
        0, p_size, body, (o0, m0, l0, k, v), unroll=True)
    l = jnp.where(l == 0.0, 1.0, l)
    out = (o / l[..., None]).astype(q.dtype)            # [b,h,sq,d]
    return jnp.swapaxes(out, 1, 2)                      # [b,sq,h,d]


def ring_attention(q, k, v, mesh, axis: str = "sp", causal: bool = False,
                   sm_scale: float | None = None):
    """Global-view ring attention: q,k,v [b, s, h, d] with s sharded over `axis`."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(None, axis, None, None)
    fn = functools.partial(_ring_shard, axis=axis, causal=causal, sm_scale=sm_scale)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={axis},
                         check_vma=False)(q, k, v)


# ---------------------------------------------------------------- ulysses ----

def _ulysses_shard(q, k, v, *, axis, causal, sm_scale):
    """Per-shard Ulysses: seq-sharded [b, s/P, h, d] -> all_to_all ->
    head-sharded [b, s, h/P, d] -> dense local attention -> back."""
    p_size = jax.lax.axis_size(axis)

    def scatter_heads(x):
        # tiled all_to_all: heads scattered across ranks, sequence gathered
        # [b, s_loc, h, d] -> [b, s_loc * P, h / P, d]
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    def gather_heads(x, s_loc):
        # inverse: [b, s, h/P, d] -> [b, s_loc, h, d]
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    s_loc = q.shape[1]
    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    from ...ops.pallas.flash_attention import supported as flash_ok

    if jax.default_backend() != "cpu" and flash_ok(qg.shape[1], kg.shape[1], qg.shape[-1]):
        from ...ops.pallas.flash_attention import flash_attention

        out = flash_attention(qg, kg, vg, causal=causal, sm_scale=sm_scale)
    else:
        mask = None
        if causal:
            sq = qg.shape[1]
            mask = jnp.arange(sq)[:, None] >= jnp.arange(sq)[None, :]
        acc, m, l = _chunk_attn(qg, kg, vg, sm_scale, mask)
        out = jnp.swapaxes((acc / l[..., None]), 1, 2).astype(q.dtype)
    return gather_heads(out, s_loc)


def ulysses_attention(q, k, v, mesh, axis: str = "sp", causal: bool = False,
                      sm_scale: float | None = None):
    """DeepSpeed-Ulysses-style attention; requires num_heads % axis_size == 0."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(None, axis, None, None)
    fn = functools.partial(_ulysses_shard, axis=axis, causal=causal, sm_scale=sm_scale)
    return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={axis},
                         check_vma=False)(q, k, v)

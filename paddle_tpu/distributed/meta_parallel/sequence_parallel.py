"""Sequence/context parallelism: ring attention + Ulysses (all-to-all) attention.

The reference has NO sequence parallelism (SURVEY.md §2: "SP ... ABSENT in the
reference"); this is a first-class addition mirroring how dp/mp/pp compose via
HybridCommunicateGroup. The 'sp' mesh axis shards the sequence dimension of
activations; attention — the only op that mixes positions — is computed either by:

- **ring attention** (Liu et al., arXiv:2310.01889): each shard keeps its query
  block and rotates KV blocks around the ring with `jax.lax.ppermute` (ICI
  neighbor exchange), merging partial results with online-softmax (running max +
  logsumexp) so the full [s, s] score matrix never exists anywhere; or
- **Ulysses** (arXiv:2309.14509): `jax.lax.all_to_all` re-shards from
  sequence-split to head-split, runs dense local attention (the Pallas flash
  kernel), and re-shards back. Needs num_heads % sp == 0.

Both run inside `jax.shard_map` manual regions over ONLY the 'sp' axis
(`axis_names={'sp'}`) so dp/mp/sharding stay under GSPMD auto-sharding — the
TPU-native analogue of composing a new communicator into the 4-D topology.
"""
from __future__ import annotations

import contextlib
import functools
import math
import threading

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...core.jax_compat import axis_size, shard_map

NEG_INF = -1e30

_state = threading.local()


def active() -> bool:
    """True when a sequence-parallel scope is installed (engine sets it when sp>1)."""
    return getattr(_state, "ctx", None) is not None


@contextlib.contextmanager
def sequence_parallel_scope(mesh, axis: str = "sp", impl: str = "ulysses"):
    """Route scaled_dot_product_attention to ring/Ulysses attention over
    `axis`. Default matches DistributedStrategy.sep_impl ("ulysses")."""
    if impl not in ("ring", "ulysses"):
        raise ValueError(
            f"sequence-parallel impl must be 'ring' or 'ulysses', got "
            f"{impl!r}")
    prev = getattr(_state, "ctx", None)
    _state.ctx = (mesh, axis, impl)
    try:
        yield
    finally:
        _state.ctx = prev


def apply_ring_attention(q, k, v, causal: bool):
    """Entry used by ops.nn_functional when a scope is active. q,k,v: Tensors
    [b, s_global, h, d] (traced global arrays inside pjit)."""
    from ...core.dispatch import apply

    mesh, axis, impl = _state.ctx
    fn = ring_attention if impl == "ring" else ulysses_attention

    @jax.jit  # partial-manual shard_map must run under jit (inlined when already traced)
    def kernel(qa, ka, va):
        return fn(qa, ka, va, mesh=mesh, axis=axis, causal=causal)

    return apply("ring_attention", kernel, [q, k, v])


# ------------------------------------------------------------------- ring ----

def _chunk_attn(q, k, v, sm_scale, mask):
    """One KV-chunk attention returning unnormalized accum + row stats.

    q: [b, sq, h, d], k/v: [b, sk, h, d], mask: [sq, sk] bool or None.
    Returns (acc [b,h,sq,d] f32, m [b,h,sq] f32, l [b,h,sq] f32).
    """
    # matmul inputs stay in storage dtype (bf16 under amp) for MXU rate;
    # f32 accumulation + f32 softmax stats keep the numerics
    qt = jnp.swapaxes(q, 1, 2)  # [b,h,sq,d]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    s = jnp.einsum("bhqd,bhkd->bhqk", qt, kt,
                   preferred_element_type=jnp.float32) * sm_scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                              # [b,h,sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p.astype(vt.dtype), vt,
                     preferred_element_type=jnp.float32)
    return acc, m, l


def _ring_use_flash(s_loc: int, d: int) -> bool:
    """Per-shard block compute runs the Pallas flash kernel when the shapes
    qualify (SURVEY §5.7's Pallas-ring requirement). The flag policy is the
    SHARED one (ops.nn_functional._flash_flag_allows — so a user disabling
    use_flash_attention disables ring's kernel too, on any backend), with
    the test env knob PADDLE_TPU_RING_FLASH=1 as a CPU-only extra opt-in."""
    import os

    from ...ops.nn_functional import _flash_flag_allows
    from ...ops.pallas.flash_attention import supported

    from ...core import flags as _flags

    if not supported(s_loc, s_loc, d):
        return False
    if not _flags.flag("use_flash_attention"):
        return False  # an explicit disable beats every opt-in, env included
    if (jax.default_backend() == "cpu"
            and os.environ.get("PADDLE_TPU_RING_FLASH") == "1"):
        return True
    return _flash_flag_allows()


def _block_attn_normalized(q, kc, vc, sm_scale, *, diag, use_flash):
    """One KV-block attention -> (o [b,h,sq,d] f32 normalized, lse [b,h,sq]).

    diag=True applies the within-block causal mask (ring diagonal block, where
    q and kv share global offsets). Pallas flash kernel when available; jnp
    chunk attention otherwise.
    """
    if use_flash:
        from ...ops.pallas.flash_attention import flash_attention_with_lse

        o, lse = flash_attention_with_lse(q, kc, vc, causal=diag,
                                          sm_scale=sm_scale)
        return jnp.swapaxes(o, 1, 2).astype(jnp.float32), lse
    mask = None
    if diag:
        sq = q.shape[1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sq)[None, :]
    acc, m, l = _chunk_attn(q, kc, vc, sm_scale, mask)
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return acc / safe_l[..., None], m + jnp.log(safe_l)


def _ring_shard(q, k, v, *, axis, causal, sm_scale):
    """Per-shard ring attention body (runs under shard_map, manual over `axis`).

    q,k,v: [b, s_local, h, d] — this rank's sequence shard. Partial results
    are carried normalized with their logsumexp and merged as
    o <- w1*o_acc + w2*o_t, w_i = exp(lse_i - logaddexp(lse_acc, lse_t)),
    so the Pallas flash kernel (which returns normalized output + lse) drops
    straight into the loop.
    """
    p_size = axis_size(axis)
    my_idx = jax.lax.axis_index(axis)
    b, s_loc, h, d = q.shape
    use_flash = _ring_use_flash(s_loc, d)

    def body(t, carry):
        o_acc, lse_acc, kc, vc = carry

        def merge(stats, diag):
            o_acc, lse_acc = stats
            o_t, lse_t = _block_attn_normalized(q, kc, vc, sm_scale,
                                                diag=diag, use_flash=use_flash)
            lse_new = jnp.logaddexp(lse_acc, lse_t)
            w1 = jnp.exp(lse_acc - lse_new)
            w2 = jnp.exp(lse_t - lse_new)
            return o_acc * w1[..., None] + o_t * w2[..., None], lse_new

        stats = (o_acc, lse_acc)
        if causal:
            kv_idx = (my_idx - t) % p_size  # whose block we currently hold
            # 3-way block dispatch: entirely-future blocks skip compute, the
            # diagonal block masks within, past blocks run unmasked
            stats = jax.lax.cond(
                kv_idx > my_idx,
                lambda s: s,
                lambda s: jax.lax.cond(
                    kv_idx == my_idx,
                    lambda s2: merge(s2, True),
                    lambda s2: merge(s2, False),
                    s),
                stats)
        else:
            stats = merge(stats, False)
        o_acc, lse_acc = stats
        # rotate kv to the next rank (neighbor exchange on the ICI ring)
        perm = [(i, (i + 1) % p_size) for i in range(p_size)]
        kc = jax.lax.ppermute(kc, axis, perm)
        vc = jax.lax.ppermute(vc, axis, perm)
        return o_acc, lse_acc, kc, vc

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    lse0 = jnp.full((b, h, s_loc), NEG_INF, jnp.float32)
    o, lse, _, _ = jax.lax.fori_loop(
        0, p_size, body, (o0, lse0, k, v), unroll=True)
    return jnp.swapaxes(o.astype(q.dtype), 1, 2)        # [b,sq,h,d]


def ring_attention(q, k, v, mesh, axis: str = "sp", causal: bool = False,
                   sm_scale: float | None = None):
    """Global-view ring attention: q,k,v [b, s, h, d] with s sharded over `axis`."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(None, axis, None, None)
    fn = functools.partial(_ring_shard, axis=axis, causal=causal, sm_scale=sm_scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={axis},
                         check_vma=False)(q, k, v)


# ---------------------------------------------------------------- ulysses ----

def _ulysses_shard(q, k, v, *, axis, causal, sm_scale):
    """Per-shard Ulysses: seq-sharded [b, s/P, h, d] -> all_to_all ->
    head-sharded [b, s, h/P, d] -> dense local attention -> back."""
    p_size = axis_size(axis)

    def scatter_heads(x):
        # tiled all_to_all: heads scattered across ranks, sequence gathered
        # [b, s_loc, h, d] -> [b, s_loc * P, h / P, d]
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    def gather_heads(x, s_loc):
        # inverse: [b, s, h/P, d] -> [b, s_loc, h, d]
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    s_loc = q.shape[1]
    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    from ...ops.nn_functional import _use_flash

    if _use_flash(qg, kg):
        from ...ops.pallas.flash_attention import flash_attention

        out = flash_attention(qg, kg, vg, causal=causal, sm_scale=sm_scale)
    else:
        mask = None
        if causal:
            sq = qg.shape[1]
            mask = jnp.arange(sq)[:, None] >= jnp.arange(sq)[None, :]
        acc, m, l = _chunk_attn(qg, kg, vg, sm_scale, mask)
        out = jnp.swapaxes((acc / l[..., None]), 1, 2).astype(q.dtype)
    return gather_heads(out, s_loc)


def ulysses_attention(q, k, v, mesh, axis: str = "sp", causal: bool = False,
                      sm_scale: float | None = None):
    """DeepSpeed-Ulysses-style attention; requires num_heads % axis_size == 0."""
    sp_size = mesh.shape[axis]
    n_heads = q.shape[2]
    if n_heads % sp_size:
        # validate here, where the head count is known: the all_to_all's own
        # failure is an opaque shape error deep inside shard_map tracing
        # that never names the knob (matters since ulysses became the
        # sep_impl default)
        raise ValueError(
            f"ulysses sequence parallelism scatters heads over the '{axis}' "
            f"axis and needs num_heads ({n_heads}) divisible by its size "
            f"({sp_size}); use strategy.sep_impl = 'ring' (no divisibility "
            f"requirement) or change the head count / sep_degree")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(None, axis, None, None)
    fn = functools.partial(_ulysses_shard, axis=axis, causal=causal, sm_scale=sm_scale)
    return shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names={axis},
                         check_vma=False)(q, k, v)

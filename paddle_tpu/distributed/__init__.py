"""paddle.distributed equivalent — the TPU-native distributed stack.

Round-1 milestone ordering (SURVEY.md §7): env contract + mesh/topology first, then the
collective API (xccl = XLA collectives over ICI/DCN), fleet facade, and meta_parallel
strategies. See distributed/mesh.py for the HybridCommunicateGroup analogue.
"""
from .env import (  # noqa: F401
    ParallelEnv, get_rank, get_world_size, init_parallel_env, is_initialized,
)

"""paddle.distributed equivalent — the TPU-native distributed stack.

Layer map (SURVEY.md §2 #20-42 TPU equivalents):
- env.py           — launcher env contract + multi-controller bootstrap (TCPStore ≙ PJRT coordination)
- mesh.py          — HybridCommunicateGroup ≙ jax.sharding.Mesh with named axes
- collective.py    — xccl: allreduce/allgather/reducescatter/broadcast/alltoall over mesh axes
- fleet/           — Fleet facade, DistributedStrategy, recompute, HybridParallelOptimizer
- meta_parallel/   — TP layers, DataParallel, PipelineLayer, GroupSharded (ZeRO), MoE
- engine.py        — the fused pjit train step (forward+backward+clip+update, one XLA program)
"""
from .env import (  # noqa: F401
    ParallelEnv, get_rank, get_world_size, init_parallel_env, is_initialized,
)
from .collective import (  # noqa: F401
    P2POp, ReduceOp, all_gather, all_reduce, all_to_all, alltoall, barrier,
    batch_isend_irecv, broadcast, irecv, isend, new_group, recv, reduce,
    reduce_scatter, scatter, send, wait,
)
from .mesh import (  # noqa: F401
    CommGroup, HybridCommunicateGroup, build_mesh, get_hybrid_communicate_group,
    set_hybrid_communicate_group,
)
from .engine import TrainStepEngine, parallelize  # noqa: F401
from . import elastic  # noqa: F401
from .elastic import (  # noqa: F401
    CheckpointCorrupt, CheckpointManager, live_reshard, restore_latest,
    verify_checkpoint,
)
from . import membership  # noqa: F401
from .membership import ElasticCoordinator, WorkerAgent  # noqa: F401
from .prefetcher import DevicePrefetcher  # noqa: F401
from .store import FileStore, TCPStore  # noqa: F401
from . import auto_parallel  # noqa: F401
from .auto_parallel import ProcessMesh, shard_tensor  # noqa: F401
from . import fleet  # noqa: F401
from .fleet.distributed_strategy import DistributedStrategy  # noqa: F401
from .fleet.dataset import InMemoryDataset, QueueDataset  # noqa: F401
from . import fleet_executor  # noqa: F401
from . import utils  # noqa: F401
from .meta_parallel.mp_layers import split  # noqa: F401
from . import meta_parallel  # noqa: F401
from .spawn import spawn  # noqa: F401


def get_group(gid=0):
    from .collective import get_group as _g

    return _g(gid)

"""Gradient communication: in-program microbatch accumulation with ONE
deferred fused all-reduce, plus opt-in low-precision gradient collectives.

The reference framework's biggest data-parallel lever is the Reducer
(`paddle/fluid/imperative/reducer.cc`): gradients are bucketed into flat
buffers, the per-bucket all-reduce is issued once backward finishes, and
with gradient accumulation the reduce is DEFERRED to the last microbatch
(`fuse_all_reduce_ops` + `_enable_backward_accumulate`). This module is the
XLA-native equivalent, built from three composable pieces:

1. **In-program microbatch accumulation** — the global batch is reshaped to
   [K, B/K] and a `lax.scan` runs forward+backward per microbatch inside ONE
   compiled program, accumulating gradients into a flat f32 buffer. The
   activation peak scales with the microbatch (the scan body is compiled
   once), and there is exactly one dispatch per optimizer step.
2. **Deferred, bucketed reduction** — the per-microbatch `psum` the GSPMD
   partitioner would emit is replaced by a single collective over the
   flattened gradient buffer AFTER the accumulation scan. The data-parallel
   region runs under `shard_map` (manual collectives), so the deferral is
   structural — the compiled HLO carries exactly one gradient all-reduce
   regardless of K (pinned by tests/test_hlo_perf_gates.py).
3. **Opt-in low-precision collectives** (`FLAGS_grad_comm_dtype`):
   - ``f32`` (default): bit-exact f32 all-reduce, one [N+1] buffer (the
     scalar loss rides in the same collective).
   - ``bf16``: the buffer is reduced in bfloat16 — half the wire bytes.
   - ``int8``: EQuARX-style chunk-scaled quantization (arXiv:2506.17615):
     per-chunk absmax scales, int8 payload gathered over the data axis and
     reduced in f32 locally — ~4x fewer wire bytes than f32.
   ``FLAGS_grad_comm_error_feedback=1`` carries the local quantization error
   into the next step (error-feedback residual, 1-bit-Adam style), removing
   the bias of repeated rounding at the cost of one f32 gradient-sized
   buffer per replica.

Topology scope: the shard_map fast path covers pure data-parallel meshes
(dp and/or ZeRO `sharding` axes; every param replicated). Hybrid meshes
(mp/sp > 1) fall back to a GSPMD accumulation scan — still one dispatch and
a microbatch-sized activation peak, but the partitioner re-emits one fused
reduce per microbatch and the precision knob is ignored (f32).
"""
from __future__ import annotations

import math
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import flags as _flags
from ..core import monitor as _monitor
from ..core.jax_compat import shard_map

# grad_comm.* observability: steps through this subsystem, microbatches
# executed, and the collective payload bytes per device (analytic — the
# bytes handed to the wire-facing collective, the number that shrinks when
# the precision knob drops below f32).
STEPS = _monitor.stat("grad_comm.steps")
MICROBATCHES = _monitor.stat("grad_comm.microbatches")
BYTES_MOVED = _monitor.stat("grad_comm.bytes_moved")
LOWP_STEPS = _monitor.stat("grad_comm.lowp_steps")
# ZeRO weight-update-sharded steps: analytic per-device bytes handed to the
# reduce-scatter (gradients down) and the all-gather (updated weights back)
RS_BYTES = _monitor.stat("grad_comm.rs_bytes")
AG_BYTES = _monitor.stat("grad_comm.ag_bytes")

_CANON = {"f32": "f32", "float32": "f32", "fp32": "f32",
          "bf16": "bf16", "bfloat16": "bf16", "int8": "int8"}


def comm_dtype() -> str:
    """Canonical FLAGS_grad_comm_dtype value: 'f32' | 'bf16' | 'int8'."""
    v = str(_flags.flag("grad_comm_dtype")).lower()
    if v not in _CANON:
        raise ValueError(
            f"FLAGS_grad_comm_dtype={v!r} — expected one of "
            f"{sorted(set(_CANON))}")
    return _CANON[v]


def error_feedback() -> bool:
    return bool(_flags.flag("grad_comm_error_feedback"))


def chunk_size() -> int:
    c = int(_flags.flag("grad_comm_chunk"))
    if c <= 0:
        raise ValueError(f"FLAGS_grad_comm_chunk={c} must be positive")
    return c


def payload_bytes(n_grads: int, dtype: str, chunk: int) -> int:
    """Per-device bytes handed to the gradient collective for one optimizer
    step. f32/bf16 carry the loss scalar in the same buffer; int8 ships the
    quantized payload plus one f32 scale per chunk (+ the loss)."""
    if dtype == "f32":
        return (n_grads + 1) * 4
    if dtype == "bf16":
        return (n_grads + 1) * 2
    n_chunks = -(-n_grads // chunk)
    return n_chunks * chunk * 1 + (n_chunks + 1) * 4


def zero_pad_elems(n_grads: int, nrep: int, chunk: int) -> int:
    """Padded flat-buffer length for the ZeRO update path: a multiple of
    nrep*chunk, so every replica owns an equal contiguous shard AND the int8
    chunk grid tiles it exactly. Always leaves at least ONE spare pad slot —
    the f32/bf16 paths ride the loss scalar through the reduce-scatter in
    slot n_grads (the bit-exactness trick vs the replicated psum).
    dtype-independent on purpose — the sharded optimizer state keeps ONE
    shape across f32/bf16/int8 steps."""
    unit = max(1, nrep) * max(1, chunk)
    return -(-(n_grads + 1) // unit) * unit


def zero_payload_bytes(n_grads: int, nrep: int, dtype: str, chunk: int,
                       health_elems: int = 0) -> Tuple[int, int]:
    """(reduce_scatter_bytes, all_gather_bytes) per device per step for the
    ZeRO update path — the local contribution handed to each collective,
    the payload_bytes convention. The all-gather slab carries the updated
    f32 weight shard + the loss scalar + the health partials (when on)."""
    n_pad = zero_pad_elems(n_grads, nrep, chunk)
    shard = n_pad // max(1, nrep)
    if dtype == "f32":
        rs = n_pad * 4
    elif dtype == "bf16":
        rs = n_pad * 2
    else:  # int8 payload + one f32 scale per chunk, both via all-to-all
        rs = n_pad * 1 + (n_pad // chunk) * 4
    ag = (shard + 1 + health_elems) * 4
    return rs, ag


# ---------------------------------------------------------------- quantize --

def _quantize_int8(x, chunk):
    """Chunk-scaled int8 quantization (EQuARX block scaling): returns
    (q [C, chunk] int8, scales [C] f32). Zero-padded to a chunk multiple;
    the pad quantizes to exact zeros."""
    n = x.shape[0]
    pad = (-n) % chunk
    xp = jnp.pad(x, (0, pad)).reshape(-1, chunk)
    scale = jnp.max(jnp.abs(xp), axis=1) / 127.0
    safe = jnp.maximum(scale, jnp.float32(1e-30))
    q = jnp.clip(jnp.round(xp / safe[:, None]), -127, 127).astype(jnp.int8)
    return q, scale


def _dequantize_int8(q, scale, n):
    return (q.astype(jnp.float32) * scale[..., None]).reshape(
        q.shape[:-2] + (-1,))[..., :n]


def _reduce_local(flat, loss, axes, dtype, chunk, residual):
    """The ONE deferred gradient collective, inside the manual (shard_map)
    region. flat: [N] f32 local partial mean-grads; loss: local mean loss.
    Returns (reduced mean grads [N], mean loss, new residual [N] | None).
    With no collective axes (single-replica mesh) this degrades to the
    identity (plus quantize/dequantize for the low-precision dtypes, so the
    numerics a multi-replica run sees stay testable on one device)."""
    nrep = 1
    for ax in axes:
        nrep *= jax.lax.psum(1, ax)
    if residual is not None:
        flat = flat + residual
    if dtype == "f32":
        buf = jnp.concatenate([flat, loss[None]])
        if axes:
            buf = jax.lax.psum(buf, axes)
        return buf[:-1] / nrep, buf[-1] / nrep, None
    if dtype == "bf16":
        b = flat.astype(jnp.bfloat16)
        new_res = flat - b.astype(jnp.float32) if residual is not None else None
        buf = jnp.concatenate([b, loss.astype(jnp.bfloat16)[None]])
        if axes:
            buf = jax.lax.psum(buf, axes)
        buf = buf.astype(jnp.float32)
        return buf[:-1] / nrep, buf[-1] / nrep, new_res
    # int8: quantize the local partial, gather payload+scales over the data
    # axes, dequantize-and-sum in f32 (a quantized all-reduce built from
    # all-gather — per-replica scales survive the trip, matching EQuARX's
    # block-scaled exchange). The loss scalar rides in the f32 scales buffer.
    n = flat.shape[0]
    q, scale = _quantize_int8(flat, chunk)
    new_res = (flat - _dequantize_int8(q, scale, n)
               if residual is not None else None)
    aux = jnp.concatenate([scale, loss[None]])
    if axes:
        gq = jax.lax.all_gather(q, axes)            # [nrep, C, chunk]
        gaux = jax.lax.all_gather(aux, axes)        # [nrep, C+1]
        red = jnp.sum(_dequantize_int8(gq, gaux[:, :-1], n), axis=0)
        loss_sum = jnp.sum(gaux[:, -1])
        return red / nrep, loss_sum / nrep, new_res
    return _dequantize_int8(q, scale, n), loss, new_res


# ---------------------------------------------------------- step builders --

def _spec_axes(axes: Sequence[str]):
    """PartitionSpec dim-0 entry for a tuple of batch axes."""
    axes = tuple(axes)
    if not axes:
        return None
    return axes if len(axes) > 1 else axes[0]


def replica_count(mesh: Mesh, axes: Sequence[str]) -> int:
    n = 1
    for ax in axes:
        n *= mesh.shape[ax]
    return int(n)


def make_accum_step(*, compute_loss: Callable, update: Callable, clip,
                    mesh: Mesh, batch_axes: Sequence[str], k: int,
                    dtype: str, chunk: int, use_residual: bool,
                    param_specs: Optional[Dict[str, P]] = None,
                    zero_specs: Optional[Dict[str, P]] = None,
                    health_stats: Optional[Callable] = None):
    """Build the microbatch-accumulation train step for a pure-dp mesh.

    Returns step(params, opt_state[, residual], lr, step_i, key, *batch) ->
    (loss, new_params, new_opt[, new_residual][, health]). The data-parallel
    region (accumulation scan + the one deferred collective) runs under
    shard_map; clip and the optimizer update run outside it under GSPMD, so
    ZeRO opt-state sharding composes unchanged (the grads are pinned to the
    param spec then the opt spec exactly as the single-shot step does).

    health_stats (observability/health.py make_packed_stats): optional
    in-program stats fn (grads, params, new_params) -> f32 [4P], appended
    as the LAST output. It receives the PRE-clip reduced mean grads — i.e.
    slices of the flat gradient buffer the collective just carried — so
    per-parameter attribution rides the flat-buffer segment map for free
    (no extra collectives, no extra dispatch).
    """
    axes = tuple(a for a in batch_axes if mesh.shape[a] > 1)
    d0 = _spec_axes(axes)

    def _local(params, key, residual, *lbatch):
        # lbatch: per-replica shards [B/nrep, ...] -> [k, B/(nrep*k), ...]
        mbs = tuple(b.reshape((k, b.shape[0] // k) + b.shape[1:])
                    for b in lbatch)
        zero_flat, unravel = ravel_pytree(
            {n: jnp.zeros(v.shape, jnp.float32) for n, v in params.items()})
        shard_key = key
        for ax in axes:  # decorrelate dropout streams across data replicas
            shard_key = jax.random.fold_in(shard_key,
                                           jax.lax.axis_index(ax))

        def body(carry, mb):
            acc, i = carry
            sub = jax.random.fold_in(shard_key, i)
            loss, g = jax.value_and_grad(
                lambda ps: compute_loss(ps, sub, *mb))(params)
            gflat, _ = ravel_pytree(g)
            return (acc + gflat.astype(jnp.float32), i + jnp.int32(1)), loss

        (acc, _), losses = jax.lax.scan(body, (zero_flat, jnp.int32(0)), mbs)
        res_in = residual[0] if residual is not None else None
        red, loss, res_out = _reduce_local(acc / k, losses.mean(), axes,
                                           dtype, chunk, res_in)
        if residual is not None:
            return unravel(red), loss, res_out[None]
        return unravel(red), loss

    def _dp_region(params, key, residual, batch):
        if not axes:
            return _local(params, key, residual, *batch)
        n_extra = 3 if residual is not None else 2
        in_specs = ((P(), P()) + ((P(d0),) if residual is not None else ())
                    + tuple(P(d0) for _ in batch))
        out_specs = ((P(), P(), P(d0)) if residual is not None
                     else (P(), P()))

        def region(params, key, *rest):
            if residual is not None:
                return _local(params, key, rest[0], *rest[1:])
            return _local(params, key, None, *rest)

        fn = shard_map(region, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False)
        if residual is not None:
            return fn(params, key, residual, *batch)
        return fn(params, key, *batch)

    def _finish(params, opt_state, grads, lr, step_i):
        raw_grads = grads  # pre-clip: what health attribution must see
        if zero_specs is not None:
            # ZeRO boundary, same two-constraint chain as the single-shot
            # step (distributed/engine.py _raw_step): grads at the param
            # spec, then at the opt spec (the reduce-scatter transition)
            grads = {n: jax.lax.with_sharding_constraint(
                g, NamedSharding(mesh, param_specs[n]))
                for n, g in grads.items()}
            grads = {n: jax.lax.with_sharding_constraint(
                g, NamedSharding(mesh, zero_specs[n]))
                for n, g in grads.items()}
        from ..optimizer import functional as opt_funct

        grads = opt_funct.clip_grads(grads, clip)
        new_params, new_opt = update(params, grads, opt_state, lr, step_i)
        if health_stats is None:
            return new_params, new_opt, None
        return new_params, new_opt, health_stats(raw_grads, params,
                                                 new_params)

    if use_residual:
        def step(params, opt_state, residual, lr, step_i, key, *batch):
            grads, loss, new_res = _dp_region(params, key, residual, batch)
            new_params, new_opt, aux = _finish(params, opt_state, grads, lr,
                                               step_i)
            if aux is None:
                return loss, new_params, new_opt, new_res
            return loss, new_params, new_opt, new_res, aux

        return step

    def step(params, opt_state, lr, step_i, key, *batch):
        grads, loss = _dp_region(params, key, None, batch)
        new_params, new_opt, aux = _finish(params, opt_state, grads, lr,
                                           step_i)
        if aux is None:
            return loss, new_params, new_opt
        return loss, new_params, new_opt, aux

    return step


def _clip_shard(g, clip, axes):
    """Grad clip on the local 1/N shard of the flat mean-grad buffer.
    ByValue is elementwise; ByGlobalNorm needs the global sum of squares —
    ONE scalar psum (4 bytes on the wire), not a full-buffer all-reduce
    (note: the cross-replica summation order differs from the replicated
    per-parameter clip, so globally-clipped runs match to fp tolerance, not
    bit-exactly). ByNorm needs per-parameter norms and is rejected upstream
    (the engine falls back to the replicated update)."""
    from ..nn.clip import ClipGradByGlobalNorm, ClipGradByValue

    if clip is None:
        return g
    if isinstance(clip, ClipGradByGlobalNorm):
        sq = jnp.sum(jnp.square(g))
        if axes:
            sq = jax.lax.psum(sq, axes)
        gn = jnp.sqrt(sq)
        return g * (clip.clip_norm / jnp.maximum(gn, clip.clip_norm))
    if isinstance(clip, ClipGradByValue):
        return jnp.clip(g, clip.min, clip.max)
    raise ValueError(f"unsupported grad clip for the ZeRO update: {clip!r}")


def make_zero_accum_step(*, compute_loss: Callable, flat_update: Callable,
                         clip, mesh: Mesh, batch_axes: Sequence[str], k: int,
                         dtype: str, chunk: int, use_residual: bool,
                         param_templates: Dict[str, jax.ShapeDtypeStruct],
                         health_partial: Optional[Callable] = None):
    """ZeRO-style cross-replica weight-update sharding (arXiv:2004.13336).

    Same accumulation scan as make_accum_step, but the post-scan reduction
    decomposes into **reduce-scatter -> shard-local clip + optimizer update
    -> all-gather of updated weights**: each data replica owns the
    contiguous 1/nrep shard of the flat f32 parameter/optimizer-state
    vector at offset r*shard (r = row-major replica index over
    ``batch_axes``, shard = n_pad/nrep — the same sorted-name segment order
    as observability.health.segment_layout, pinned by tests), runs the
    update on only its shard, and the updated weight shards gather back to
    the replicated layout the model expects. Per optimizer step the
    compiled HLO carries exactly ONE reduce-scatter and ONE all-gather
    independent of K (f32/bf16; int8 replaces the reduce-scatter with two
    all-to-alls carrying the EQuARX chunk-scaled payload + f32 scales) and
    ZERO full-buffer all-reduces.

    flat_update(p_shard, g_shard, opt_shards, lr, step_i) ->
    (new_p_shard, new_opt_shards): ONE uniform elementwise rule over f32
    [shard] vectors (engine._make_flat_update guarantees uniformity). The
    loss scalar and the health partials ride the all-gather slab:
    health_partial (health.make_sharded_stats) sees the PRE-clip gradient
    shard plus a segment-id shard, and its [4P] partial sums are summed
    over replicas in-program — the packed buffer the host decodes is
    layout-identical to the replicated path's.

    Error feedback (use_residual, bf16/int8 only) carries the local
    quantization error of the SCATTERED payload: the residual is computed
    against the local pre-collective buffer, exactly as the replicated
    low-precision path does.

    Returns step(params, opt_shards[, residual], lr, step_i, key, *batch)
    -> (loss, new_params, new_opt_shards[, new_residual][, health])."""
    if use_residual and dtype == "f32":
        raise ValueError("error feedback needs a low-precision dtype")
    ride_loss = dtype != "int8"   # f32/bf16: loss rides the scatter buffer
    axes = tuple(a for a in batch_axes if mesh.shape[a] > 1)
    d0 = _spec_axes(axes)
    nrep = replica_count(mesh, axes)
    names = sorted(param_templates)
    shapes = {nm: tuple(param_templates[nm].shape) for nm in names}
    dtypes = {nm: param_templates[nm].dtype for nm in names}
    sizes = [int(np.prod(shapes[nm]) or 1) for nm in names]
    offs = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
    n = int(offs[-1])
    n_pad = zero_pad_elems(n, nrep, chunk)
    shard = n_pad // nrep
    # flat-index -> parameter-ordinal map for the sharded health partials;
    # pad slots land in segment P and are dropped by make_sharded_stats
    seg_ids = None
    if health_partial is not None:
        seg_ids = np.full((n_pad,), len(names), np.int32)
        for i, (o, s) in enumerate(zip(offs[:-1], sizes)):
            seg_ids[o:o + s] = i

    def _flatten(params):
        return jnp.concatenate(
            [params[nm].astype(jnp.float32).reshape(-1) for nm in names])

    def _unflatten(flat):
        return {nm: flat[offs[i]:offs[i + 1]].reshape(shapes[nm])
                .astype(dtypes[nm]) for i, nm in enumerate(names)}

    def _scatter(buf):
        """The ONE gradient reduce-scatter: [n_pad] local partial-mean
        grads -> ([shard] reduced MEAN grad shard, new residual | None).
        With no collective axes this degrades to the identity plus the
        quantize/dequantize roundtrip, mirroring _reduce_local."""
        if dtype == "f32":
            g = (jax.lax.psum_scatter(buf, axes, scatter_dimension=0,
                                      tiled=True) if axes else buf)
            return g / nrep, None
        if dtype == "bf16":
            b = buf.astype(jnp.bfloat16)
            res = ((buf - b.astype(jnp.float32))[:n]
                   if use_residual else None)
            g = (jax.lax.psum_scatter(b, axes, scatter_dimension=0,
                                      tiled=True) if axes else b)
            return g.astype(jnp.float32) / nrep, res
        # int8: quantized reduce-scatter built from all-to-all — replica i
        # keeps only the chunk rows of its own shard, every peer's scales
        # survive the trip (EQuARX block scaling), dequant-sum in f32
        q, scale = _quantize_int8(buf, chunk)      # [n_pad/chunk, chunk]
        res = ((buf - _dequantize_int8(q, scale, n_pad))[:n]
               if use_residual else None)
        qs = q.reshape((nrep, shard // chunk, chunk))
        ss = scale.reshape((nrep, shard // chunk))
        if axes:
            qs = jax.lax.all_to_all(qs, axes, split_axis=0, concat_axis=0)
            ss = jax.lax.all_to_all(ss, axes, split_axis=0, concat_axis=0)
        g = jnp.sum(qs.astype(jnp.float32) * ss[..., None], axis=0)
        return g.reshape(shard) / nrep, res

    def _local(params, lr, step_i, key, residual, opt, *lbatch):
        mbs = tuple(b.reshape((k, b.shape[0] // k) + b.shape[1:])
                    for b in lbatch)
        zero_flat, _ = ravel_pytree(
            {nm: jnp.zeros(v.shape, jnp.float32)
             for nm, v in params.items()})
        shard_key = key
        for ax in axes:  # decorrelate dropout streams across data replicas
            shard_key = jax.random.fold_in(shard_key,
                                           jax.lax.axis_index(ax))

        def body(carry, mb):
            acc, i = carry
            sub = jax.random.fold_in(shard_key, i)
            loss, g = jax.value_and_grad(
                lambda ps: compute_loss(ps, sub, *mb))(params)
            gflat, _ = ravel_pytree(g)
            return (acc + gflat.astype(jnp.float32), i + jnp.int32(1)), loss

        (acc, _), losses = jax.lax.scan(body, (zero_flat, jnp.int32(0)), mbs)
        flat = acc / k
        if residual is not None:
            flat = flat + residual[0]
        buf = jnp.pad(flat, (0, n_pad - n))
        if ride_loss:
            # f32/bf16: the local mean loss rides the reduce-scatter in pad
            # slot n (zero_pad_elems guarantees the spare) — the SAME
            # reduction+divide the grads take, so the final loss is
            # bit-identical to the replicated path's psum'd loss. int8 must
            # not quantize it; there it rides the gather slab in f32.
            buf = buf.at[n].set(losses.mean())
        g_shard, new_res = _scatter(buf)
        # own-shard offset: row-major replica index over the batch axes —
        # the order psum_scatter/all_gather tile in (pinned by tests)
        r = jnp.int32(0)
        for ax in axes:
            r = r * jnp.int32(mesh.shape[ax]) + jax.lax.axis_index(ax)
        if ride_loss:
            # extract the reduced loss from whichever replica owns slot n
            # (zero elsewhere: the gather-slab sum stays exact) and zero it
            # out of the grad shard before clip/update
            loss_mask = (r * jnp.int32(shard)
                         + jnp.arange(shard, dtype=jnp.int32)) == n
            loss_part = jnp.sum(jnp.where(loss_mask, g_shard, 0.0))
            g_shard = jnp.where(loss_mask, 0.0, g_shard)
        else:
            loss_part = losses.mean()
        p_shard = jax.lax.dynamic_slice(
            jnp.pad(_flatten(params), (0, n_pad - n)),
            (r * jnp.int32(shard),), (shard,))
        raw_g = g_shard                     # pre-clip: health attribution
        g_shard = _clip_shard(g_shard, clip, axes)
        new_p_shard, new_opt = flat_update(p_shard, g_shard, tuple(opt),
                                           lr, step_i)
        extras = [loss_part[None]]
        if health_partial is not None:
            ids_shard = jax.lax.dynamic_slice(
                jnp.asarray(seg_ids), (r * jnp.int32(shard),), (shard,))
            extras.append(health_partial(raw_g, p_shard, new_p_shard,
                                         ids_shard))
        # ONE all-gather: [updated weight shard | loss | health partials],
        # decoded by reshaping to one row per replica. ride_loss rows carry
        # the already-reduced loss on the owner replica and exact zeros
        # elsewhere (summing is exact); int8 rows carry local mean losses.
        slab = jnp.concatenate([new_p_shard] + extras)
        if axes:
            rows = jax.lax.all_gather(slab, axes, tiled=True).reshape(
                (nrep, slab.shape[0]))
            new_flat = rows[:, :shard].reshape(-1)[:n]
            loss = jnp.sum(rows[:, shard])
            if not ride_loss:
                loss = loss / nrep
            hbuf = (jnp.sum(rows[:, shard + 1:], axis=0)
                    if health_partial is not None else None)
        else:
            new_flat = new_p_shard[:n]
            loss = loss_part
            hbuf = extras[1] if health_partial is not None else None
        outs = (new_flat, loss, tuple(new_opt))
        if use_residual:
            outs += (new_res[None],)
        if health_partial is not None:
            outs += (hbuf,)
        return outs

    def _region_call(params, lr, step_i, key, residual, opt, batch):
        if not axes:
            return _local(params, lr, step_i, key, residual, opt, *batch)
        in_specs = ((P(), P(), P(), P())
                    + ((P(d0),) if use_residual else ())
                    + (P(d0),)                 # flat opt-state shards
                    + tuple(P(d0) for _ in batch))
        out_specs = (P(), P(), P(d0))
        if use_residual:
            out_specs += (P(d0),)
        if health_partial is not None:
            out_specs += (P(),)

        def region(params, lr, step_i, key, *rest):
            if use_residual:
                return _local(params, lr, step_i, key, rest[0], rest[1],
                              *rest[2:])
            return _local(params, lr, step_i, key, None, rest[0], *rest[1:])

        fn = shard_map(region, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False)
        if use_residual:
            return fn(params, lr, step_i, key, residual, tuple(opt), *batch)
        return fn(params, lr, step_i, key, tuple(opt), *batch)

    if use_residual:
        def step(params, opt_shards, residual, lr, step_i, key, *batch):
            outs = _region_call(params, lr, step_i, key, residual,
                                opt_shards, batch)
            ret = (outs[1], _unflatten(outs[0]), outs[2], outs[3])
            if health_partial is not None:
                ret += (outs[4],)
            return ret

        return step

    def step(params, opt_shards, lr, step_i, key, *batch):
        outs = _region_call(params, lr, step_i, key, None, opt_shards, batch)
        ret = (outs[1], _unflatten(outs[0]), outs[2])
        if health_partial is not None:
            ret += (outs[3],)
        return ret

    return step


def default_layer_key(name: str) -> str:
    """Fallback per-layer fsdp bucket key: the parameter's owning module
    path (everything before the final attribute), so e.g. a Linear's weight
    and bias share one bucket. Models override by defining an
    ``fsdp_layer_key(name)`` method that groups at the granularity whose
    gather should hide under the previous layer's compute (models/gpt.py
    groups one transformer block per bucket)."""
    return name.rsplit(".", 1)[0] if "." in name else name


def fsdp_buckets(param_shapes: Dict[str, Sequence[int]], nrep: int,
                 chunk: int, layer_key: Optional[Callable] = None):
    """Per-layer bucket layout of the sorted-name flat parameter vector.

    Walks the names in sorted order (== ravel_pytree dict flatten order ==
    health.segment_layout) and cuts a bucket at every change of the layer
    key — buckets are maximal contiguous RUNS, so a key that reappears
    later in the order simply opens another bucket and every bucket stays a
    contiguous slice of the flat vector. Each bucket pads to a multiple of
    nrep*chunk (equal per-replica shards AND an exact int8 chunk grid);
    these are the per-layer all-gather boundaries of the fsdp step. Returns
    dicts: {key, names, off (flat offset of the first real element),
    n (real elements), pad (padded length), shard (pad // nrep)}."""
    key_fn = layer_key or default_layer_key
    unit = max(1, nrep) * max(1, chunk)
    buckets: list = []
    off = 0
    for nm in sorted(param_shapes):
        key = str(key_fn(nm))
        size = int(np.prod(tuple(param_shapes[nm])) or 1)
        if not buckets or key != buckets[-1]["key"]:
            buckets.append({"key": key, "names": [], "off": off, "n": 0})
        buckets[-1]["names"].append(nm)
        buckets[-1]["n"] += size
        off += size
    for b in buckets:
        b["pad"] = -(-b["n"] // unit) * unit
        b["shard"] = b["pad"] // max(1, nrep)
    return buckets


def fsdp_payload_bytes(shard_elems: Sequence[int], nrep: int, dtype: str,
                       chunk: int) -> Tuple[int, int, list]:
    """(reduce_scatter_bytes, all_gather_bytes, per_layer_ag_bytes) per
    device per step for the fsdp path — the local contribution handed to
    each collective, the payload_bytes convention. The gather leg is L
    per-bucket f32 weight-shard gathers (there is NO trailing full-
    parameter gather — that is the arg-bytes win over ZeRO); the scatter
    leg carries the bucket-padded grads plus one aux loss column per
    replica row (int8: the aux column rides the f32 scales exchange)."""
    nrep = max(1, nrep)
    s_total = int(sum(shard_elems))
    if dtype == "f32":
        rs = nrep * (s_total + 1) * 4
    elif dtype == "bf16":
        rs = nrep * (s_total + 1) * 2
    else:  # int8 payload + one f32 scale per chunk + the aux loss column
        rs = nrep * s_total * 1 + nrep * (s_total // chunk + 1) * 4
    per_layer = [int(s) * 4 for s in shard_elems]
    return rs, sum(per_layer), per_layer


def fsdp_window_bytes(buckets: Sequence[dict], depth: int) -> int:
    """Analytic live-gathered bytes of a depth-``depth`` fsdp prefetch
    window: the max over window positions of the summed FULL (padded, f32)
    gathered bucket bytes held live at once — while bucket i's compute
    runs, buckets i..i+depth-1 are gathered. Depth 0 and 1 both hold one
    bucket (just-in-time); the default depth 2 holds the worst adjacent
    pair. This is the bound the exec.train.fsdp_* window-bytes gauge
    reports and tools/mem_report.py checks against measured temp bytes."""
    gb = [int(b["pad"]) * 4 for b in buckets]
    if not gb:
        return 0
    d = max(1, min(int(depth), len(gb)))
    return max(sum(gb[i:i + d]) for i in range(len(gb) - d + 1))


def fsdp_prefetch_ahead_bytes(buckets: Sequence[dict], depth: int) -> int:
    """Analytic EXTRA resident bytes a depth-``depth`` window holds vs the
    just-in-time baseline: the raw gathered buffers of buckets 1..depth-1
    (f32, padded) stay live across the whole microbatch scan — the step fn
    pins them with a post-scan read, so this delta is exactly measurable
    as depth-d temp bytes minus depth-0 temp bytes on the SAME model
    (tools/mem_report.py hard-asserts it). For the canonical two-bucket
    report model this is the second bucket's gather size. 0 below depth
    2."""
    if int(depth) < 2:
        return 0
    return sum(int(b["pad"]) * 4 for b in buckets[1:int(depth)])


def fsdp_prefetch_depth(buckets: Sequence[dict], requested: int) -> int:
    """Clamp the requested gather-prefetch depth so the live window never
    exceeds the two largest adjacent gathered buckets (the double-buffer
    byte bound): the largest d <= requested whose fsdp_window_bytes fits
    under the depth-2 window. <= 0 stays 0 (just-in-time, no barriers)."""
    d = min(int(requested), max(1, len(buckets)))
    if d <= 0:
        return 0
    cap = fsdp_window_bytes(buckets, 2)
    while d > 2 and fsdp_window_bytes(buckets, d) > cap:
        d -= 1
    return d


def make_fsdp_accum_step(*, compute_loss: Callable, flat_update: Callable,
                         clip, mesh: Mesh, batch_axes: Sequence[str], k: int,
                         dtype: str, chunk: int, use_residual: bool,
                         param_templates: Dict[str, jax.ShapeDtypeStruct],
                         buckets: Sequence[dict], prefetch: int = 0,
                         health_partial: Optional[Callable] = None):
    """Fully sharded data parallelism (arXiv:2004.13336 taken the rest of
    the way): parameters arrive as per-layer flat f32 SHARDS and leave the
    same way — no replicated copy exists between steps.

    Inside the compiled step, each bucket's weight shard is all-gathered
    just before the forward/backward consumes it (L independent per-layer
    gathers issued up front, so XLA's scheduler can hide each one under a
    neighbouring bucket's compute), the accumulation scan runs against the
    gathered view, and the post-scan reduction is ONE reduce-scatter over
    the bucket-shard-major permutation of the flat gradient buffer — each
    replica receives exactly the mean-grad slices for the shards it owns.
    Clip + the uniform elementwise optimizer rule then run per bucket on
    shard-local state and the updated shards are simply RETURNED: unlike
    the ZeRO step there is no trailing parameter all-gather, which is what
    drops per-device parameter residency to ~1/nrep. Per optimizer step
    the HLO carries exactly L all-gathers + 1 reduce-scatter (f32/bf16;
    int8 swaps the reduce-scatter for two all-to-alls of EQuARX payload +
    scales) and ZERO full-buffer all-reduces, independent of K.

    Bit-exactness vs the replicated trajectory at f32 rides on the same
    property the ZeRO step pinned: psum_scatter(tiled)'s per-element
    reduction order matches psum, and the permutation only relabels
    positions. The loss rides an aux column every replica writes
    identically into every destination row, so the scattered sum IS the
    global sum. Health partials can't ride a gather slab here (there is
    none, and they need the post-update shard), so each replica emits its
    [4P] segment partial as a sharded [nrep, 4P] output the engine sums
    host-side — zero extra collectives.

    With ``prefetch`` depth d >= 2 the gathers run under an overlap-ahead
    window: bucket i's gathered view is released through a value-identity
    select pin tied to the all-gathers for buckets i+1..i+d-1, so every
    consumer of bucket i carries a REAL data dependency on the next
    window's gathers — any valid schedule issues AG(i+1) before bucket i's
    compute (double-buffered at d=2), which is exactly what the
    schedule-order analysis contract reads out of the optimized HLO. The
    backward pass mirrors the window on the per-bucket cotangents in
    DESCENDING bucket order (bucket i's grads release together with
    buckets i-1..i-d+1's). The depth is clamped by fsdp_prefetch_depth so
    live-gathered bytes never exceed the two largest adjacent buckets.
    Both pins are identity on values: every depth is bit-equal to depth 0.

    Returns step(p_shards, opt_shards[, residual], lr, step_i, key, *batch)
    -> (loss, new_p_shards, new_opt_shards[, new_residual][, health])."""
    if use_residual and dtype == "f32":
        raise ValueError("error feedback needs a low-precision dtype")
    axes = tuple(a for a in batch_axes if mesh.shape[a] > 1)
    depth = fsdp_prefetch_depth(buckets, prefetch) if axes else 0
    d0 = _spec_axes(axes)
    nrep = replica_count(mesh, axes)
    names = sorted(param_templates)
    shapes = {nm: tuple(param_templates[nm].shape) for nm in names}
    dtypes = {nm: param_templates[nm].dtype for nm in names}
    sizes = {nm: int(np.prod(shapes[nm]) or 1) for nm in names}
    assert [nm for b in buckets for nm in b["names"]] == names
    n = sum(sizes.values())
    s_total = sum(b["shard"] for b in buckets)       # local elems per replica
    soffs = np.concatenate(
        [[0], np.cumsum([b["shard"] for b in buckets])]).astype(np.int64)
    poffs = np.concatenate(
        [[0], np.cumsum([b["pad"] for b in buckets])]).astype(np.int64)
    # flat-index -> parameter-ordinal map per replica row (bucket-shard
    # order); pad slots land in segment P and are dropped by the partial
    seg_ids = None
    if health_partial is not None:
        ordinal = {nm: i for i, nm in enumerate(names)}
        seg_ids = np.full((nrep, s_total), len(names), np.int32)
        for bi, b in enumerate(buckets):
            ids_b = np.full((b["pad"],), len(names), np.int32)
            o = 0
            for nm in b["names"]:
                ids_b[o:o + sizes[nm]] = ordinal[nm]
                o += sizes[nm]
            seg_ids[:, soffs[bi]:soffs[bi + 1]] = ids_b.reshape(
                nrep, b["shard"])

    def _gather_params(p_shards, step_i):
        """L per-bucket all-gathers -> the replicated param dict the
        forward/backward consumes. tiled=True concatenates replica shards
        in row-major replica order — the inverse of the reshape(nrep, shard)
        the scatter side uses, so the contiguous bucket reassembles.

        With prefetch depth >= 2 each gathered bucket is RELEASED through
        a value-identity select pin tied to the NEXT window's gathers:
        ``step_i >= INT32_MIN`` is true for every possible step index, but
        a runtime comparison cannot be constant-folded, so the (never
        taken) other branch makes AG(i+1..i+depth-1) REAL operands of
        bucket i's consumers — every valid schedule, including the
        sequential one the schedule-order contract reads out of the
        optimized HLO, must issue the next bucket's gather before the
        current bucket's compute. (A plain optimization_barrier does not
        survive here: XLA expands barriers before scheduling, so they
        leave no trace in the scheduled module.) Depth 0 emits the bare
        just-in-time gathers of PR 19.

        Returns (params, hold): `hold` is the list of raw gathered
        buffers the window keeps ahead of the first bucket's compute
        (fulls[1:depth]) — the caller pins them live across the
        microbatch scan, which is what makes the analytic window delta
        measurable in the executable's temp bytes."""
        fulls = [jax.lax.all_gather(pl, axes, tiled=True) if axes else pl
                 for pl in p_shards]
        hold = list(fulls[1:depth]) if depth >= 2 else []
        if depth >= 2:
            ok = step_i >= jnp.int32(-2 ** 31)
            pinned = []
            for i, f in enumerate(fulls):
                ahead = fulls[i + 1:i + depth]
                if ahead:
                    probe = sum(a[0] for a in ahead)
                    f = jnp.where(ok, f, jnp.broadcast_to(probe, f.shape))
                pinned.append(f)
            fulls = pinned
        params = {}
        for b, full in zip(buckets, fulls):
            o = 0
            for nm in b["names"]:
                params[nm] = (full[o:o + sizes[nm]].reshape(shapes[nm])
                              .astype(dtypes[nm]))
                o += sizes[nm]
        return params, hold

    @jax.custom_vjp
    def _window_mirror(params):
        return params

    def _window_mirror_fwd(params):
        return params, None

    def _window_mirror_bwd(_, ct):
        # backward twin of the gather window: the backward pass walks the
        # buckets in descending order, so bucket i's param cotangents are
        # released only together with buckets i-1..i-depth+1's — bucket
        # i-1's grad work is forced live under bucket i's grad consumption,
        # mirroring the forward prefetch. Identity on values.
        groups = [[ct[nm] for nm in b["names"]] for b in buckets]
        for i in range(len(groups) - 1, 0, -1):
            behind = [x for g in groups[max(0, i - depth + 1):i] for x in g]
            if behind:
                out = jax.lax.optimization_barrier(
                    tuple(groups[i]) + tuple(behind))
                groups[i] = list(out[:len(groups[i])])
        return ({nm: x for b, g in zip(buckets, groups)
                 for nm, x in zip(b["names"], g)},)

    _window_mirror.defvjp(_window_mirror_fwd, _window_mirror_bwd)

    def _rows(flat):
        """[n] grads in global (sorted-name) order -> [nrep, s_total]
        destination-major rows: row r holds replica r's shard of every
        bucket, in bucket order — the layout psum_scatter(tiled) scatters
        by."""
        segs = []
        for b in buckets:
            seg = jnp.pad(flat[b["off"]:b["off"] + b["n"]],
                          (0, b["pad"] - b["n"]))
            segs.append(seg.reshape(nrep, b["shard"]))
        return jnp.concatenate(segs, axis=1)

    def _scatter(flat, local_loss):
        """The ONE gradient reduce-scatter: [n] f32 local partial-mean
        grads -> ([s_total] reduced MEAN grad shards in bucket-shard order,
        reduced mean loss, new residual [n] | None). Every replica writes
        its local mean loss into the aux column of EVERY destination row,
        so each scattered slice carries the full cross-replica loss sum.
        With no collective axes this degrades to the identity plus the
        quantize/dequantize roundtrip, mirroring the ZeRO _scatter."""
        if dtype == "f32":
            buf = jnp.concatenate(
                [_rows(flat),
                 jnp.full((nrep, 1), local_loss, jnp.float32)],
                axis=1).reshape(-1)
            out = (jax.lax.psum_scatter(buf, axes, scatter_dimension=0,
                                        tiled=True) if axes else buf)
            return out[:s_total] / nrep, out[s_total] / nrep, None
        if dtype == "bf16":
            b16 = flat.astype(jnp.bfloat16)
            res = flat - b16.astype(jnp.float32) if use_residual else None
            buf = jnp.concatenate(
                [_rows(b16),
                 jnp.full((nrep, 1), local_loss, jnp.bfloat16)],
                axis=1).reshape(-1)
            out = (jax.lax.psum_scatter(buf, axes, scatter_dimension=0,
                                        tiled=True) if axes else buf)
            out = out.astype(jnp.float32)
            return out[:s_total] / nrep, out[s_total] / nrep, res
        # int8: quantized reduce-scatter from two all-to-alls over the
        # bucket-padded buffer (every bucket pad is a chunk multiple, so
        # the chunk grid tiles each bucket exactly); the f32 aux loss
        # column rides the scales exchange and dequant-sum reduces it
        padbuf = jnp.concatenate(
            [jnp.pad(flat[b["off"]:b["off"] + b["n"]],
                     (0, b["pad"] - b["n"])) for b in buckets])
        q, scale = _quantize_int8(padbuf, chunk)
        res = None
        if use_residual:
            err = padbuf - _dequantize_int8(q, scale, padbuf.shape[0])
            res = jnp.concatenate(
                [err[poffs[i]:poffs[i] + b["n"]]
                 for i, b in enumerate(buckets)])
        qs = jnp.concatenate(
            [q[poffs[i] // chunk:poffs[i + 1] // chunk]
             .reshape(nrep, b["shard"] // chunk, chunk)
             for i, b in enumerate(buckets)], axis=1)
        ss = jnp.concatenate(
            [scale[poffs[i] // chunk:poffs[i + 1] // chunk]
             .reshape(nrep, b["shard"] // chunk)
             for i, b in enumerate(buckets)], axis=1)
        ss = jnp.concatenate(
            [ss, jnp.full((nrep, 1), local_loss, jnp.float32)], axis=1)
        if axes:
            qs = jax.lax.all_to_all(qs, axes, split_axis=0, concat_axis=0)
            ss = jax.lax.all_to_all(ss, axes, split_axis=0, concat_axis=0)
        g = jnp.sum(qs.astype(jnp.float32) * ss[:, :s_total // chunk, None],
                    axis=0).reshape(s_total)
        return g / nrep, jnp.sum(ss[:, -1]) / nrep, res

    def _local(p_shards, lr, step_i, key, residual, opt, *lbatch):
        params, window_hold = _gather_params(p_shards, step_i)
        mbs = tuple(b.reshape((k, b.shape[0] // k) + b.shape[1:])
                    for b in lbatch)
        zero_flat, _ = ravel_pytree(
            {nm: jnp.zeros(v.shape, jnp.float32)
             for nm, v in params.items()})
        shard_key = key
        for ax in axes:  # decorrelate dropout streams across data replicas
            shard_key = jax.random.fold_in(shard_key,
                                           jax.lax.axis_index(ax))

        def body(carry, mb):
            acc, i = carry
            sub = jax.random.fold_in(shard_key, i)
            loss, g = jax.value_and_grad(
                lambda ps: compute_loss(
                    _window_mirror(ps) if depth >= 2 else ps, sub, *mb)
            )(params)
            gflat, _ = ravel_pytree(g)
            return (acc + gflat.astype(jnp.float32), i + jnp.int32(1)), loss

        (acc, _), losses = jax.lax.scan(body, (zero_flat, jnp.int32(0)), mbs)
        flat = acc / k
        if residual is not None:
            flat = flat + residual[0]
        g_all, loss, new_res = _scatter(flat, losses.mean())
        if window_hold:
            # keep the window's ahead-gathered buffers resident across the
            # microbatch scan: the dead select branch reads each buffer at
            # an index only known after the loss exists, so XLA cannot
            # hoist the read before the while loop or free the buffers
            # under it. This is what tools/mem_report.py measures as the
            # depth-0 -> depth-2 temp-byte delta (fsdp_prefetch_ahead_bytes
            # analytically). Identity on values: the pin branch never runs.
            idx = jnp.clip(jnp.asarray(loss * 0).astype(jnp.int32), 0, 0)
            probe = sum(jax.lax.dynamic_index_in_dim(f, idx, keepdims=False)
                        for f in window_hold)
            loss = jnp.where(step_i >= jnp.int32(-2 ** 31), loss,
                             probe.astype(loss.dtype))
        raw_g = g_all                       # pre-clip: health attribution
        g_all = _clip_shard(g_all, clip, axes)
        new_ps = []
        new_opt_cols = [[] for _ in opt]
        for i, b in enumerate(buckets):
            g_b = g_all[soffs[i]:soffs[i + 1]]
            opt_b = tuple(slot[i] for slot in opt)
            new_p_b, new_opt_b = flat_update(p_shards[i], g_b, opt_b,
                                             lr, step_i)
            new_ps.append(new_p_b)
            for j, col in enumerate(new_opt_b):
                new_opt_cols[j].append(col)
        outs = (loss, tuple(new_ps),
                tuple(tuple(col) for col in new_opt_cols))
        if use_residual:
            outs += (new_res[None],)
        if health_partial is not None:
            r = jnp.int32(0)
            for ax in axes:
                r = r * jnp.int32(mesh.shape[ax]) + jax.lax.axis_index(ax)
            ids = jax.lax.dynamic_slice(
                jnp.asarray(seg_ids), (r, jnp.int32(0)), (1, s_total))[0]
            hp = health_partial(raw_g, jnp.concatenate(list(p_shards)),
                                jnp.concatenate(new_ps), ids)
            outs += (hp[None],)             # [1, 4P] row per replica
        return outs

    def _region_call(p_shards, lr, step_i, key, residual, opt, batch):
        if not axes:
            return _local(p_shards, lr, step_i, key, residual, opt, *batch)
        in_specs = ((P(d0), P(), P(), P())  # per-bucket weight shards first
                    + ((P(d0),) if use_residual else ())
                    + (P(d0),)              # per-slot per-bucket opt shards
                    + tuple(P(d0) for _ in batch))
        out_specs = (P(), P(d0), P(d0))
        if use_residual:
            out_specs += (P(d0),)
        if health_partial is not None:
            out_specs += (P(d0),)           # per-replica health rows

        def region(p_shards, lr, step_i, key, *rest):
            if use_residual:
                return _local(p_shards, lr, step_i, key, rest[0], rest[1],
                              *rest[2:])
            return _local(p_shards, lr, step_i, key, None, rest[0],
                          *rest[1:])

        fn = shard_map(region, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False)
        if use_residual:
            return fn(tuple(p_shards), lr, step_i, key, residual,
                      tuple(opt), *batch)
        return fn(tuple(p_shards), lr, step_i, key, tuple(opt), *batch)

    if use_residual:
        def step(p_shards, opt_shards, residual, lr, step_i, key, *batch):
            return _region_call(p_shards, lr, step_i, key, residual,
                                opt_shards, batch)

        return step

    def step(p_shards, opt_shards, lr, step_i, key, *batch):
        return _region_call(p_shards, lr, step_i, key, None, opt_shards,
                            batch)

    return step


def make_accum_step_gspmd(*, compute_loss: Callable, update: Callable, clip,
                          mesh: Mesh, k: int, batch_specs: Sequence[P],
                          param_specs: Optional[Dict[str, P]] = None,
                          zero_specs: Optional[Dict[str, P]] = None,
                          health_stats: Optional[Callable] = None):
    """Hybrid-mesh (mp/sp) fallback: GSPMD accumulation scan. Still ONE
    compiled dispatch per optimizer step with a microbatch-sized activation
    peak and an f32 accumulator, but the partitioner inserts its own fused
    gradient reduction per microbatch (K combined all-reduces, not 1) and
    the low-precision knob does not apply — the collectives are implicit.
    health_stats appends the packed f32 [4P] stats buffer as the last
    output, same contract as make_accum_step."""

    def step(params, opt_state, lr, step_i, key, *batch):
        mbs = []
        for b, spec in zip(batch, batch_specs):
            r = b.reshape((k, b.shape[0] // k) + b.shape[1:])
            mbs.append(jax.lax.with_sharding_constraint(
                r, NamedSharding(mesh, P(None, *spec))))
        zero_flat, unravel = ravel_pytree(
            {n: jnp.zeros(v.shape, jnp.float32) for n, v in params.items()})

        def body(carry, mb):
            acc, i = carry
            sub = jax.random.fold_in(key, i)
            loss, g = jax.value_and_grad(
                lambda ps: compute_loss(ps, sub, *mb))(params)
            gflat, _ = ravel_pytree(g)
            return (acc + gflat.astype(jnp.float32), i + jnp.int32(1)), loss

        (acc, _), losses = jax.lax.scan(body, (zero_flat, jnp.int32(0)),
                                        tuple(mbs))
        grads = unravel(acc / k)
        raw_grads = grads
        if zero_specs is not None:
            grads = {n: jax.lax.with_sharding_constraint(
                g, NamedSharding(mesh, param_specs[n]))
                for n, g in grads.items()}
            grads = {n: jax.lax.with_sharding_constraint(
                g, NamedSharding(mesh, zero_specs[n]))
                for n, g in grads.items()}
        from ..optimizer import functional as opt_funct

        grads = opt_funct.clip_grads(grads, clip)
        new_params, new_opt = update(params, grads, opt_state, lr, step_i)
        if health_stats is None:
            return losses.mean(), new_params, new_opt
        return losses.mean(), new_params, new_opt, health_stats(
            raw_grads, params, new_params)

    return step

"""Gradient communication: in-program microbatch accumulation with ONE
deferred fused all-reduce, plus opt-in low-precision gradient collectives.

The reference framework's biggest data-parallel lever is the Reducer
(`paddle/fluid/imperative/reducer.cc`): gradients are bucketed into flat
buffers, the per-bucket all-reduce is issued once backward finishes, and
with gradient accumulation the reduce is DEFERRED to the last microbatch
(`fuse_all_reduce_ops` + `_enable_backward_accumulate`). This module is the
XLA-native equivalent, built from three composable pieces:

1. **In-program microbatch accumulation** — the global batch is reshaped to
   [K, B/K] and a `lax.scan` runs forward+backward per microbatch inside ONE
   compiled program, accumulating gradients into a flat f32 buffer. The
   activation peak scales with the microbatch (the scan body is compiled
   once), and there is exactly one dispatch per optimizer step.
2. **Deferred, bucketed reduction** — the per-microbatch `psum` the GSPMD
   partitioner would emit is replaced by a single collective over the
   flattened gradient buffer AFTER the accumulation scan. The data-parallel
   region runs under `shard_map` (manual collectives), so the deferral is
   structural — the compiled HLO carries exactly one gradient all-reduce
   regardless of K (pinned by tests/test_hlo_perf_gates.py).
3. **Opt-in low-precision collectives** (`FLAGS_grad_comm_dtype`):
   - ``f32`` (default): bit-exact f32 all-reduce, one [N+1] buffer (the
     scalar loss rides in the same collective).
   - ``bf16``: the buffer is reduced in bfloat16 — half the wire bytes.
   - ``int8``: EQuARX-style chunk-scaled quantization (arXiv:2506.17615):
     per-chunk absmax scales, int8 payload gathered over the data axis and
     reduced in f32 locally — ~4x fewer wire bytes than f32.
   ``FLAGS_grad_comm_error_feedback=1`` carries the local quantization error
   into the next step (error-feedback residual, 1-bit-Adam style), removing
   the bias of repeated rounding at the cost of one f32 gradient-sized
   buffer per replica.

Topology scope: the shard_map fast path covers pure data-parallel meshes
(dp and/or ZeRO `sharding` axes; every param replicated). Hybrid meshes
(mp/sp > 1) fall back to a GSPMD accumulation scan — still one dispatch and
a microbatch-sized activation peak, but the partitioner re-emits one fused
reduce per microbatch and the precision knob is ignored (f32).
"""
from __future__ import annotations

import math
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import flags as _flags
from ..core import monitor as _monitor
from ..core.jax_compat import shard_map

# grad_comm.* observability: steps through this subsystem, microbatches
# executed, and the collective payload bytes per device (analytic — the
# bytes handed to the wire-facing collective, the number that shrinks when
# the precision knob drops below f32).
STEPS = _monitor.stat("grad_comm.steps")
MICROBATCHES = _monitor.stat("grad_comm.microbatches")
BYTES_MOVED = _monitor.stat("grad_comm.bytes_moved")
LOWP_STEPS = _monitor.stat("grad_comm.lowp_steps")

_CANON = {"f32": "f32", "float32": "f32", "fp32": "f32",
          "bf16": "bf16", "bfloat16": "bf16", "int8": "int8"}


def comm_dtype() -> str:
    """Canonical FLAGS_grad_comm_dtype value: 'f32' | 'bf16' | 'int8'."""
    v = str(_flags.flag("grad_comm_dtype")).lower()
    if v not in _CANON:
        raise ValueError(
            f"FLAGS_grad_comm_dtype={v!r} — expected one of "
            f"{sorted(set(_CANON))}")
    return _CANON[v]


def error_feedback() -> bool:
    return bool(_flags.flag("grad_comm_error_feedback"))


def chunk_size() -> int:
    c = int(_flags.flag("grad_comm_chunk"))
    if c <= 0:
        raise ValueError(f"FLAGS_grad_comm_chunk={c} must be positive")
    return c


def payload_bytes(n_grads: int, dtype: str, chunk: int) -> int:
    """Per-device bytes handed to the gradient collective for one optimizer
    step. f32/bf16 carry the loss scalar in the same buffer; int8 ships the
    quantized payload plus one f32 scale per chunk (+ the loss)."""
    if dtype == "f32":
        return (n_grads + 1) * 4
    if dtype == "bf16":
        return (n_grads + 1) * 2
    n_chunks = -(-n_grads // chunk)
    return n_chunks * chunk * 1 + (n_chunks + 1) * 4


# ---------------------------------------------------------------- quantize --

def _quantize_int8(x, chunk):
    """Chunk-scaled int8 quantization (EQuARX block scaling): returns
    (q [C, chunk] int8, scales [C] f32). Zero-padded to a chunk multiple;
    the pad quantizes to exact zeros."""
    n = x.shape[0]
    pad = (-n) % chunk
    xp = jnp.pad(x, (0, pad)).reshape(-1, chunk)
    scale = jnp.max(jnp.abs(xp), axis=1) / 127.0
    safe = jnp.maximum(scale, jnp.float32(1e-30))
    q = jnp.clip(jnp.round(xp / safe[:, None]), -127, 127).astype(jnp.int8)
    return q, scale


def _dequantize_int8(q, scale, n):
    return (q.astype(jnp.float32) * scale[..., None]).reshape(
        q.shape[:-2] + (-1,))[..., :n]


def _reduce_local(flat, loss, axes, dtype, chunk, residual):
    """The ONE deferred gradient collective, inside the manual (shard_map)
    region. flat: [N] f32 local partial mean-grads; loss: local mean loss.
    Returns (reduced mean grads [N], mean loss, new residual [N] | None).
    With no collective axes (single-replica mesh) this degrades to the
    identity (plus quantize/dequantize for the low-precision dtypes, so the
    numerics a multi-replica run sees stay testable on one device)."""
    nrep = 1
    for ax in axes:
        nrep *= jax.lax.psum(1, ax)
    if residual is not None:
        flat = flat + residual
    if dtype == "f32":
        buf = jnp.concatenate([flat, loss[None]])
        if axes:
            buf = jax.lax.psum(buf, axes)
        return buf[:-1] / nrep, buf[-1] / nrep, None
    if dtype == "bf16":
        b = flat.astype(jnp.bfloat16)
        new_res = flat - b.astype(jnp.float32) if residual is not None else None
        buf = jnp.concatenate([b, loss.astype(jnp.bfloat16)[None]])
        if axes:
            buf = jax.lax.psum(buf, axes)
        buf = buf.astype(jnp.float32)
        return buf[:-1] / nrep, buf[-1] / nrep, new_res
    # int8: quantize the local partial, gather payload+scales over the data
    # axes, dequantize-and-sum in f32 (a quantized all-reduce built from
    # all-gather — per-replica scales survive the trip, matching EQuARX's
    # block-scaled exchange). The loss scalar rides in the f32 scales buffer.
    n = flat.shape[0]
    q, scale = _quantize_int8(flat, chunk)
    new_res = (flat - _dequantize_int8(q, scale, n)
               if residual is not None else None)
    aux = jnp.concatenate([scale, loss[None]])
    if axes:
        gq = jax.lax.all_gather(q, axes)            # [nrep, C, chunk]
        gaux = jax.lax.all_gather(aux, axes)        # [nrep, C+1]
        red = jnp.sum(_dequantize_int8(gq, gaux[:, :-1], n), axis=0)
        loss_sum = jnp.sum(gaux[:, -1])
        return red / nrep, loss_sum / nrep, new_res
    return _dequantize_int8(q, scale, n), loss, new_res


# ---------------------------------------------------------- step builders --

def _spec_axes(axes: Sequence[str]):
    """PartitionSpec dim-0 entry for a tuple of batch axes."""
    axes = tuple(axes)
    if not axes:
        return None
    return axes if len(axes) > 1 else axes[0]


def replica_count(mesh: Mesh, axes: Sequence[str]) -> int:
    n = 1
    for ax in axes:
        n *= mesh.shape[ax]
    return int(n)


def make_accum_step(*, compute_loss: Callable, update: Callable, clip,
                    mesh: Mesh, batch_axes: Sequence[str], k: int,
                    dtype: str, chunk: int, use_residual: bool,
                    param_specs: Optional[Dict[str, P]] = None,
                    zero_specs: Optional[Dict[str, P]] = None,
                    health_stats: Optional[Callable] = None):
    """Build the microbatch-accumulation train step for a pure-dp mesh.

    Returns step(params, opt_state[, residual], lr, step_i, key, *batch) ->
    (loss, new_params, new_opt[, new_residual][, health]). The data-parallel
    region (accumulation scan + the one deferred collective) runs under
    shard_map; clip and the optimizer update run outside it under GSPMD, so
    ZeRO opt-state sharding composes unchanged (the grads are pinned to the
    param spec then the opt spec exactly as the single-shot step does).

    health_stats (observability/health.py make_packed_stats): optional
    in-program stats fn (grads, params, new_params) -> f32 [4P], appended
    as the LAST output. It receives the PRE-clip reduced mean grads — i.e.
    slices of the flat gradient buffer the collective just carried — so
    per-parameter attribution rides the flat-buffer segment map for free
    (no extra collectives, no extra dispatch).
    """
    axes = tuple(a for a in batch_axes if mesh.shape[a] > 1)
    d0 = _spec_axes(axes)

    def _local(params, key, residual, *lbatch):
        # lbatch: per-replica shards [B/nrep, ...] -> [k, B/(nrep*k), ...]
        mbs = tuple(b.reshape((k, b.shape[0] // k) + b.shape[1:])
                    for b in lbatch)
        zero_flat, unravel = ravel_pytree(
            {n: jnp.zeros(v.shape, jnp.float32) for n, v in params.items()})
        shard_key = key
        for ax in axes:  # decorrelate dropout streams across data replicas
            shard_key = jax.random.fold_in(shard_key,
                                           jax.lax.axis_index(ax))

        def body(carry, mb):
            acc, i = carry
            sub = jax.random.fold_in(shard_key, i)
            loss, g = jax.value_and_grad(
                lambda ps: compute_loss(ps, sub, *mb))(params)
            gflat, _ = ravel_pytree(g)
            return (acc + gflat.astype(jnp.float32), i + jnp.int32(1)), loss

        (acc, _), losses = jax.lax.scan(body, (zero_flat, jnp.int32(0)), mbs)
        res_in = residual[0] if residual is not None else None
        red, loss, res_out = _reduce_local(acc / k, losses.mean(), axes,
                                           dtype, chunk, res_in)
        if residual is not None:
            return unravel(red), loss, res_out[None]
        return unravel(red), loss

    def _dp_region(params, key, residual, batch):
        if not axes:
            return _local(params, key, residual, *batch)
        n_extra = 3 if residual is not None else 2
        in_specs = ((P(), P()) + ((P(d0),) if residual is not None else ())
                    + tuple(P(d0) for _ in batch))
        out_specs = ((P(), P(), P(d0)) if residual is not None
                     else (P(), P()))

        def region(params, key, *rest):
            if residual is not None:
                return _local(params, key, rest[0], *rest[1:])
            return _local(params, key, None, *rest)

        fn = shard_map(region, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=False)
        if residual is not None:
            return fn(params, key, residual, *batch)
        return fn(params, key, *batch)

    def _finish(params, opt_state, grads, lr, step_i):
        raw_grads = grads  # pre-clip: what health attribution must see
        if zero_specs is not None:
            # ZeRO boundary, same two-constraint chain as the single-shot
            # step (distributed/engine.py _raw_step): grads at the param
            # spec, then at the opt spec (the reduce-scatter transition)
            grads = {n: jax.lax.with_sharding_constraint(
                g, NamedSharding(mesh, param_specs[n]))
                for n, g in grads.items()}
            grads = {n: jax.lax.with_sharding_constraint(
                g, NamedSharding(mesh, zero_specs[n]))
                for n, g in grads.items()}
        from ..optimizer import functional as opt_funct

        grads = opt_funct.clip_grads(grads, clip)
        new_params, new_opt = update(params, grads, opt_state, lr, step_i)
        if health_stats is None:
            return new_params, new_opt, None
        return new_params, new_opt, health_stats(raw_grads, params,
                                                 new_params)

    if use_residual:
        def step(params, opt_state, residual, lr, step_i, key, *batch):
            grads, loss, new_res = _dp_region(params, key, residual, batch)
            new_params, new_opt, aux = _finish(params, opt_state, grads, lr,
                                               step_i)
            if aux is None:
                return loss, new_params, new_opt, new_res
            return loss, new_params, new_opt, new_res, aux

        return step

    def step(params, opt_state, lr, step_i, key, *batch):
        grads, loss = _dp_region(params, key, None, batch)
        new_params, new_opt, aux = _finish(params, opt_state, grads, lr,
                                           step_i)
        if aux is None:
            return loss, new_params, new_opt
        return loss, new_params, new_opt, aux

    return step


def make_accum_step_gspmd(*, compute_loss: Callable, update: Callable, clip,
                          mesh: Mesh, k: int, batch_specs: Sequence[P],
                          param_specs: Optional[Dict[str, P]] = None,
                          zero_specs: Optional[Dict[str, P]] = None,
                          health_stats: Optional[Callable] = None):
    """Hybrid-mesh (mp/sp) fallback: GSPMD accumulation scan. Still ONE
    compiled dispatch per optimizer step with a microbatch-sized activation
    peak and an f32 accumulator, but the partitioner inserts its own fused
    gradient reduction per microbatch (K combined all-reduces, not 1) and
    the low-precision knob does not apply — the collectives are implicit.
    health_stats appends the packed f32 [4P] stats buffer as the last
    output, same contract as make_accum_step."""

    def step(params, opt_state, lr, step_i, key, *batch):
        mbs = []
        for b, spec in zip(batch, batch_specs):
            r = b.reshape((k, b.shape[0] // k) + b.shape[1:])
            mbs.append(jax.lax.with_sharding_constraint(
                r, NamedSharding(mesh, P(None, *spec))))
        zero_flat, unravel = ravel_pytree(
            {n: jnp.zeros(v.shape, jnp.float32) for n, v in params.items()})

        def body(carry, mb):
            acc, i = carry
            sub = jax.random.fold_in(key, i)
            loss, g = jax.value_and_grad(
                lambda ps: compute_loss(ps, sub, *mb))(params)
            gflat, _ = ravel_pytree(g)
            return (acc + gflat.astype(jnp.float32), i + jnp.int32(1)), loss

        (acc, _), losses = jax.lax.scan(body, (zero_flat, jnp.int32(0)),
                                        tuple(mbs))
        grads = unravel(acc / k)
        raw_grads = grads
        if zero_specs is not None:
            grads = {n: jax.lax.with_sharding_constraint(
                g, NamedSharding(mesh, param_specs[n]))
                for n, g in grads.items()}
            grads = {n: jax.lax.with_sharding_constraint(
                g, NamedSharding(mesh, zero_specs[n]))
                for n, g in grads.items()}
        from ..optimizer import functional as opt_funct

        grads = opt_funct.clip_grads(grads, clip)
        new_params, new_opt = update(params, grads, opt_state, lr, step_i)
        if health_stats is None:
            return losses.mean(), new_params, new_opt
        return losses.mean(), new_params, new_opt, health_stats(
            raw_grads, params, new_params)

    return step

"""Fleet executor: actor-model distributed runtime.

Reference: paddle/fluid/distributed/fleet_executor/ — `FleetExecutor::Run`
(fleet_executor.h:47) hosts a `Carrier` (carrier.h:49) of `Interceptor`s
(interceptor.h:46) per TaskNode; a brpc `MessageBus` moves InterceptorMessages
between ranks; `ComputeInterceptor` implements credit-based flow control over
up/downstream buffers; `DistModel` (dist_model.cc) runs distributed inference
on top.

TPU-native split: the transport (listener, sockets, routing, blocking queues)
is the C++ library core/native/fleet_executor.cc; the interceptor handlers run
on Python threads because compute means dispatching jax — the GIL serializes
Python-side compute anyway, and XLA execution releases it. A pure-Python bus
with the same 6-call surface keeps toolchain-less hosts working.
"""
from __future__ import annotations

import ctypes
import pickle
import queue as _queue
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

# message types (reference interceptor_message.proto MessageType)
DATA_IS_READY = 0
DATA_IS_USELESS = 1  # credit return (downstream consumed a slot)
STOP = 2
START = 3
RESULT = 4


# --------------------------------------------------------------- transports
class _NativeBus:
    def __init__(self, lib, rank, nranks, port, endpoints):
        self._lib = lib
        lib.fe_start.restype = ctypes.c_int
        lib.fe_start.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                 ctypes.c_char_p]
        lib.fe_recv.restype = ctypes.c_int
        lib.fe_recv.argtypes = [ctypes.c_int, ctypes.c_int64,
                                ctypes.POINTER(ctypes.c_int64),
                                ctypes.POINTER(ctypes.c_int), ctypes.c_char_p,
                                ctypes.c_int, ctypes.c_int]
        lib.fe_send.argtypes = [ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
                                ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
        self._h = lib.fe_start(rank, nranks, port,
                               ",".join(endpoints or []).encode())
        if self._h < 0:
            raise RuntimeError(f"fe_start failed: {self._h}")

    @property
    def port(self):
        return self._lib.fe_port(self._h)

    def register(self, interceptor_id):
        self._lib.fe_register(self._h, ctypes.c_int64(interceptor_id))

    def route(self, interceptor_id, rank):
        self._lib.fe_route(self._h, ctypes.c_int64(interceptor_id), rank)

    def send(self, src, dst, mtype, payload=b""):
        rc = self._lib.fe_send(self._h, ctypes.c_int64(src), ctypes.c_int64(dst),
                               mtype, payload, len(payload))
        if rc != 0:
            raise RuntimeError(f"fe_send -> {rc}")

    def recv(self, interceptor_id, timeout_ms=100):
        src = ctypes.c_int64()
        mtype = ctypes.c_int()
        buf = ctypes.create_string_buffer(1 << 20)
        n = self._lib.fe_recv(self._h, ctypes.c_int64(interceptor_id),
                              ctypes.byref(src), ctypes.byref(mtype), buf,
                              len(buf), timeout_ms)
        if n < 0:
            return None
        return src.value, mtype.value, buf.raw[:n]

    def pending(self, interceptor_id):
        return max(0, self._lib.fe_pending(self._h,
                                           ctypes.c_int64(interceptor_id)))

    def stop(self):
        self._lib.fe_stop(self._h)


class _PyBus:
    """In-process fallback with the same surface (single-rank only)."""

    def __init__(self, rank=0, nranks=1, port=0, endpoints=None):
        self._queues: Dict[int, _queue.Queue] = {}
        self.port = 0

    def register(self, interceptor_id):
        self._queues.setdefault(interceptor_id, _queue.Queue())

    def route(self, interceptor_id, rank):
        pass

    def send(self, src, dst, mtype, payload=b""):
        self._queues[dst].put((src, mtype, payload))

    def recv(self, interceptor_id, timeout_ms=100):
        try:
            return self._queues[interceptor_id].get(timeout=timeout_ms / 1000.0)
        except _queue.Empty:
            return None

    def pending(self, interceptor_id):
        return self._queues[interceptor_id].qsize()

    def stop(self):
        pass


def _make_bus(rank=0, nranks=1, port=0, endpoints=None):
    from ..core.native import load_library

    lib = load_library("fleet_executor")
    if lib is None:
        if nranks > 1:
            raise RuntimeError("multi-rank fleet executor needs the native bus")
        return _PyBus(rank, nranks, port, endpoints)
    return _NativeBus(lib, rank, nranks, port, endpoints)


# --------------------------------------------------------------- task graph
@dataclass
class TaskNode:
    """One stage of the pipeline DAG (reference task_node.h)."""

    task_id: int
    rank: int = 0
    max_run_times: int = 1
    run_fn: Optional[Callable] = None  # payload -> payload (ComputeInterceptor)
    downstream: List[int] = field(default_factory=list)
    upstream: List[int] = field(default_factory=list)
    buffer_size: int = 2  # credit slots per downstream (1F1B-style backpressure)


class Interceptor:
    """Message-driven actor: one thread draining its queue (reference
    interceptor.h:46 RegisterMsgHandle + LoopOnce)."""

    def __init__(self, interceptor_id: int, carrier: "Carrier"):
        self.interceptor_id = interceptor_id
        self.carrier = carrier
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    def send(self, dst: int, mtype: int, payload=b""):
        self.carrier.bus.send(self.interceptor_id, dst, mtype, payload)

    def handle(self, src: int, mtype: int, payload: bytes):
        raise NotImplementedError

    def _loop(self):
        while not self._stopped.is_set():
            msg = self.carrier.bus.recv(self.interceptor_id, timeout_ms=100)
            if msg is None:
                continue
            src, mtype, payload = msg
            if mtype == STOP:
                self._stopped.set()
                break
            self._processing = True
            try:
                self.handle(src, mtype, payload)
            finally:
                self._processing = False

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"interceptor-{self.interceptor_id}")
        self._thread.start()

    def join(self, timeout=None):
        if self._thread is not None:
            self._thread.join(timeout)


class ComputeInterceptor(Interceptor):
    """Run the task's fn when upstream data is ready; flow-control downstream
    with credits (reference compute_interceptor.cc: ready/used slot counters)."""

    def __init__(self, node: TaskNode, carrier: "Carrier"):
        super().__init__(node.task_id, carrier)
        self.node = node
        self._pending: Dict[int, List[bytes]] = {u: [] for u in node.upstream}
        self._credits: Dict[int, int] = {d: node.buffer_size
                                         for d in node.downstream}
        self._ran = 0

    def handle(self, src, mtype, payload):
        if mtype == DATA_IS_USELESS:  # downstream freed a slot
            self._credits[src] = self._credits.get(src, 0) + 1
            self._maybe_run()
            return
        if mtype in (DATA_IS_READY, START):
            if src in self._pending:
                self._pending[src].append(payload)
            else:  # source start: synthesize one input
                self._pending.setdefault(-1, []).append(payload)
            self._maybe_run()

    def _ready(self):
        ups = self._pending.values()
        if not ups:
            return False
        if not all(len(v) > 0 for v in ups):
            return False
        return all(c > 0 for c in self._credits.values()) \
            if self._credits else True

    def _maybe_run(self):
        while self._ready() and self._ran < self.node.max_run_times:
            ins = [v.pop(0) for v in self._pending.values()]
            srcs = list(self._pending.keys())
            out = self.node.run_fn(*ins) if self.node.run_fn else (
                ins[0] if ins else b"")
            self._ran += 1
            # return credit upstream (real upstreams only)
            for u in srcs:
                if u >= 0:
                    self.send(u, DATA_IS_USELESS)
            for d in self.node.downstream:
                if d in self._credits:
                    self._credits[d] -= 1
                self.send(d, DATA_IS_READY, out if isinstance(out, bytes)
                          else pickle.dumps(out))
            if not self.node.downstream:
                self.carrier.deposit_result(out)


class SinkInterceptor(Interceptor):
    """Collects RESULT/DATA_IS_READY payloads for the driver."""

    def handle(self, src, mtype, payload):
        self.carrier.deposit_result(payload)
        self.send(src, DATA_IS_USELESS)


class Carrier:
    """Per-rank interceptor host (reference carrier.h:49: CreateInterceptors +
    local routing; remote messages ride the bus)."""

    def __init__(self, rank=0, nranks=1, endpoints=None, port=0):
        self.rank = rank
        self.nranks = nranks
        self.bus = _make_bus(rank, nranks, port, endpoints)
        self.interceptors: Dict[int, Interceptor] = {}
        self.results: "_queue.Queue" = _queue.Queue()

    @property
    def port(self):
        return self.bus.port

    def add_task_node(self, node: TaskNode):
        if node.rank == self.rank:
            ic = ComputeInterceptor(node, self)
            self.interceptors[node.task_id] = ic
            self.bus.register(node.task_id)
        else:
            self.bus.route(node.task_id, node.rank)
        return self

    def add_interceptor(self, ic: Interceptor):
        self.interceptors[ic.interceptor_id] = ic
        self.bus.register(ic.interceptor_id)
        return ic

    def route(self, interceptor_id, rank):
        self.bus.route(interceptor_id, rank)

    def start(self):
        for ic in self.interceptors.values():
            ic.start()

    def deposit_result(self, payload):
        self.results.put(payload)

    def wait_result(self, timeout=30.0):
        return self.results.get(timeout=timeout)

    def quiesce(self, timeout=30.0):
        """Wait until every local interceptor has drained its inputs and all
        downstream credits came back — i.e. everything sent was consumed
        (the analogue of fleet_executor's Run() completing a section)."""
        import time as _time

        deadline = _time.time() + timeout
        while _time.time() < deadline:
            idle = True
            for ic in self.interceptors.values():
                if self.bus.pending(ic.interceptor_id) > 0 or \
                        getattr(ic, "_processing", False):
                    idle = False
                    break
                if isinstance(ic, ComputeInterceptor):
                    if any(len(v) for v in ic._pending.values()):
                        idle = False
                        break
                    if any(c < ic.node.buffer_size
                           for c in ic._credits.values()):
                        idle = False
                        break
            if idle:
                return True
            _time.sleep(0.01)
        return False

    def stop(self):
        self.quiesce(timeout=5.0)
        for ic in self.interceptors.values():
            ic._stopped.set()
        for ic in self.interceptors.values():
            ic.join(timeout=1.0)
        self.bus.stop()


class FleetExecutor:
    """Drive a TaskNode DAG for N micro-batches (reference
    fleet_executor.h:35 Init/Run)."""

    def __init__(self, task_nodes: List[TaskNode], rank=0, nranks=1,
                 endpoints=None, port=0):
        self.nodes = {n.task_id: n for n in task_nodes}
        # complete upstream lists from downstream declarations
        for n in task_nodes:
            for d in n.downstream:
                if d in self.nodes and n.task_id not in self.nodes[d].upstream:
                    self.nodes[d].upstream.append(n.task_id)
        self.carrier = Carrier(rank, nranks, endpoints, port)
        for n in task_nodes:
            self.carrier.add_task_node(n)
        self._sources = [n.task_id for n in task_nodes
                         if not n.upstream and n.rank == rank]
        self.carrier.start()

    def run(self, feed: bytes = b"", num_micro_batches: Optional[int] = None):
        """Inject feed into source nodes; returns the sink outputs."""
        n_mb = num_micro_batches or 1
        for _ in range(n_mb):
            for s in self._sources:
                self.carrier.bus.send(-1, s, DATA_IS_READY,
                                      feed if isinstance(feed, bytes)
                                      else pickle.dumps(feed))
        outs = [self.carrier.wait_result() for _ in range(n_mb)]
        return outs

    def shutdown(self):
        self.carrier.stop()


class DistModel:
    """Distributed inference facade over the executor (reference
    dist_model.cc): each rank wraps its model shard in one ComputeInterceptor;
    activations hop rank-to-rank over the message bus."""

    def __init__(self, stage_fn: Callable, stage_id: int, num_stages: int,
                 endpoints: List[str], port: int = 0):
        nodes = []
        for s in range(num_stages):
            nodes.append(TaskNode(
                task_id=s, rank=s,
                run_fn=(lambda payload, fn=stage_fn:
                        pickle.dumps(fn(pickle.loads(payload))))
                if s == stage_id else None,
                downstream=[s + 1] if s < num_stages - 1 else [],
                max_run_times=1 << 30))
        self.stage_id = stage_id
        self.num_stages = num_stages
        self.exe = FleetExecutor(nodes, rank=stage_id, nranks=num_stages,
                                 endpoints=endpoints, port=port)

    def run(self, inputs):
        if self.stage_id == 0:
            self.exe.carrier.bus.send(-1, 0, DATA_IS_READY, pickle.dumps(inputs))
        if self.stage_id == self.num_stages - 1:
            return pickle.loads(self.exe.carrier.wait_result())
        return None

    def shutdown(self):
        self.exe.shutdown()

"""paddle.distributed.spawn — single-node multiprocess entry (reference spawn.py).

On TPU, a single controller already drives all local chips, so nprocs>1 maps to
multi-host multi-controller launches (one process per host) via the launcher CLI;
spawn with nprocs=1 (or default) simply runs the function.
"""
from __future__ import annotations

import multiprocessing as mp
import os


def _worker(func, args, env):
    os.environ.update(env)
    func(*args)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    if nprocs in (-1, 0, 1):
        func(*args)
        return None

    # spawn (not fork): the parent has initialized JAX, which is multithreaded —
    # forking a multithreaded process can deadlock children on PJRT/threadpool
    # locks. spawn requires func/args to be picklable (same contract as torch).
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nprocs),
        }
        p = ctx.Process(target=_worker, args=(func, args, env), daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
    return procs

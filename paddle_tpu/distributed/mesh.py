"""Device mesh & hybrid-parallel topology.

Reference: `HybridCommunicateGroup` builds a 4-D rank grid (dp/pp/mp/sharding) and carves an
NCCL communicator per sub-group (python/paddle/distributed/fleet/base/topology.py:133,155-165).

TPU-native: the grid *is* a `jax.sharding.Mesh` whose named axes (dp, pp, mp, sharding, sp, ep)
are the communicators — a "ring id" becomes an axis name, and collectives over a group become
XLA collectives over that axis (SURVEY.md §5.8 north star). Sub-groups need no setup: any axis
subset of the mesh is already a valid communication scope for psum/all_gather/ppermute.

Multi-host: the same Mesh spans all processes' devices (multi-controller JAX); ICI carries
intra-slice axes, DCN the inter-slice ones (put dp outermost for that).
"""
from __future__ import annotations

import collections
from typing import Dict, List, Optional

import numpy as np

AXES_ORDER = ("pp", "dp", "sharding", "sp", "ep", "mp")
# mp (tensor parallel) innermost: its collectives are the most latency-sensitive and
# should ride the fastest ICI links; pp outermost: only p2p crosses it.


class CommGroup:
    """A communicator = a named mesh axis (or explicit rank list for new_group)."""

    def __init__(self, axis: Optional[str], ranks: List[int], mesh=None, id: int = 0):
        self.axis = axis
        self.ranks = list(ranks)
        self.nranks = len(ranks)
        self.world_size = self.nranks
        self.mesh = mesh
        self.id = id

    @property
    def rank(self):
        from .env import get_rank

        g = get_rank()
        return self.ranks.index(g) if g in self.ranks else -1

    def get_group_rank(self, global_rank):
        return self.ranks.index(global_rank) if global_rank in self.ranks else -1

    def __repr__(self):
        return f"CommGroup(axis={self.axis}, ranks={self.ranks})"


def build_mesh(degrees: Dict[str, int], devices=None):
    """Create a jax Mesh with the given axis degrees (1-degree axes kept for uniformity)."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = np.array(jax.devices())
    else:
        devices = np.array(devices)
    shape = [int(degrees.get(a, 1)) for a in AXES_ORDER]
    total = int(np.prod(shape))
    if total != devices.size:
        raise ValueError(
            f"mesh degrees {dict(zip(AXES_ORDER, shape))} require {total} devices, "
            f"have {devices.size}")
    return Mesh(devices.reshape(shape), AXES_ORDER)


class HybridCommunicateGroup:
    """Topology facade with the reference's accessor surface (topology.py:209)."""

    def __init__(self, dp_degree=-1, mp_degree=1, pp_degree=1, sharding_degree=1,
                 sp_degree=1, ep_degree=1, devices=None, order=None):
        import jax

        avail = list(devices) if devices is not None else list(jax.devices())
        n = len(avail)
        degrees = {"dp": dp_degree, "mp": mp_degree, "pp": pp_degree,
                   "sharding": sharding_degree, "sp": sp_degree, "ep": ep_degree}
        others = int(np.prod([max(1, d) for k, d in degrees.items() if k != "dp"]))
        if degrees["dp"] is None or degrees["dp"] <= 0:
            # auto-fill dp to use every device (reference launcher behavior)
            if n % others != 0:
                raise ValueError(f"degrees {degrees} do not partition {n} devices")
            degrees["dp"] = n // others
        total = others * max(1, degrees["dp"])
        if total > n:
            raise ValueError(
                f"mesh degrees {degrees} need {total} devices, only {n} available")
        self.degrees = {k: max(1, int(v)) for k, v in degrees.items()}
        # explicit degrees may use a subset of the devices (e.g. a 1-chip debug mesh
        # on an 8-device host)
        self.mesh = build_mesh(self.degrees, avail[:total])
        self.nranks = total
        self._groups = {}
        for i, axis in enumerate(AXES_ORDER):
            self._groups[axis] = CommGroup(axis, list(range(self.degrees[axis])),
                                           self.mesh, id=i)
        self.global_rank = 0  # single-controller: logical rank of this process

    # ---- reference accessor surface ----
    def get_parallel_mode(self):
        if self.degrees["pp"] > 1:
            return "pipeline"
        if self.degrees["sharding"] > 1:
            return "sharding_parallel"
        if self.degrees["mp"] > 1:
            return "tensor_parallel"
        return "data_parallel"

    def topology(self):
        return self.degrees

    def get_global_rank(self):
        return self.global_rank

    def get_data_parallel_world_size(self):
        return self.degrees["dp"]

    def get_data_parallel_rank(self):
        return 0

    def get_data_parallel_group(self):
        return self._groups["dp"]

    def get_model_parallel_world_size(self):
        return self.degrees["mp"]

    def get_model_parallel_rank(self):
        return 0

    def get_model_parallel_group(self):
        return self._groups["mp"]

    def get_pipe_parallel_world_size(self):
        return self.degrees["pp"]

    def get_stage_id(self):
        return 0

    def get_pipe_parallel_group(self):
        return self._groups["pp"]

    def get_sharding_parallel_world_size(self):
        return self.degrees["sharding"]

    def get_sharding_parallel_rank(self):
        return 0

    def get_sharding_parallel_group(self):
        return self._groups["sharding"]

    def get_sep_parallel_world_size(self):
        return self.degrees["sp"]

    def get_sep_parallel_group(self):
        return self._groups["sp"]

    def get_expert_parallel_world_size(self):
        return self.degrees["ep"]

    def get_expert_parallel_group(self):
        return self._groups["ep"]

    def get_check_parallel_group(self):
        return CommGroup(None, list(range(self.nranks)), self.mesh)

    # ---- TPU-native additions ----
    def axis_size(self, axis: str) -> int:
        return self.degrees[axis]

    def data_spec(self, extra_batch_axes=("sharding",)):
        """PartitionSpec for a [batch, ...] input: batch sharded over dp (+sharding)."""
        from jax.sharding import PartitionSpec as P

        axes = tuple(a for a in ("dp",) + tuple(extra_batch_axes) if self.degrees[a] > 1)
        return P(axes if len(axes) > 1 else (axes[0] if axes else None))


_global_hcg: Optional[HybridCommunicateGroup] = None


def set_hybrid_communicate_group(hcg):
    global _global_hcg
    _global_hcg = hcg
    return hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _global_hcg


def fleet_default_mesh():
    """The mesh in effect: the fleet hcg's, else a trivial all-dp mesh."""
    global _global_hcg
    if _global_hcg is None:
        _global_hcg = HybridCommunicateGroup()
    return _global_hcg.mesh

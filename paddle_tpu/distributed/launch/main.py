"""Launcher implementation.

Reference call path: launch/main.py -> Controller.build_pod (collective.py:32)
-> spawn N procs with the PADDLE_TRAINER* env -> watch().  Same shape here:
parse args, rendezvous (multi-node via TCPStore), build the env for each local
process, spawn, watch, tear down on failure. PS mode (--server_num/--trainer_num)
sets the PS env contract (controllers/ps.py:21).
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="paddle_tpu distributed launcher")
    p.add_argument("--master", default=None,
                   help="rendezvous endpoint host:port (rank-0 node hosts the store)")
    p.add_argument("--nnodes", type=int, default=int(os.environ.get("PADDLE_NNODES", 1)))
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", -1)),
                   help="-1 = assign via the store's arrival counter")
    p.add_argument("--nproc_per_node", type=int,
                   default=int(os.environ.get("PADDLE_NPROC_PER_NODE", 1)))
    p.add_argument("--devices", default=os.environ.get("PADDLE_DEVICES", ""),
                   help="comma-separated device ordinals handed to workers")
    p.add_argument("--job_id", default=os.environ.get("PADDLE_JOB_ID", "default"))
    p.add_argument("--log_dir", default=os.environ.get("PADDLE_LOG_DIR", "log"))
    p.add_argument("--run_mode", default="collective",
                   choices=["collective", "ps"])
    p.add_argument("--server_num", type=int, default=0, help="PS mode: #servers")
    p.add_argument("--trainer_num", type=int, default=None, help="PS mode: #trainers")
    p.add_argument("--elastic_level", type=int, default=0,
                   help=">0: restart failed workers in place (single-node)")
    p.add_argument("--max_restarts", type=int, default=3)
    p.add_argument("-m", "--module", default=None,
                   help="run a module (python -m style) instead of a script")
    p.add_argument("training_script", nargs="?", default=None,
                   help="training script to run (or use -m MODULE)")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if args.module is None and args.training_script is None:
        p.error("a training script or -m MODULE is required")
    return args


class ProcList:
    def __init__(self, log_dir: str):
        self.procs: List[subprocess.Popen] = []
        self.specs: List[dict] = []
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)

    def spawn(self, cmd: List[str], env: Dict[str, str], name: str):
        log_path = os.path.join(self.log_dir, f"{name}.log")
        log_f = open(log_path, "ab")
        proc = subprocess.Popen(cmd, env=env, stdout=log_f, stderr=subprocess.STDOUT)
        self.procs.append(proc)
        self.specs.append({"cmd": cmd, "env": env, "name": name, "log": log_path,
                           "file": log_f})
        return proc

    def respawn(self, i: int):
        spec = self.specs[i]
        spec["file"].close()
        spec["file"] = open(spec["log"], "ab")
        self.procs[i] = subprocess.Popen(spec["cmd"], env=spec["env"],
                                         stdout=spec["file"],
                                         stderr=subprocess.STDOUT)

    def poll(self) -> Optional[int]:
        """Index of the first failed proc, or None; -1 when all exited cleanly."""
        all_done = True
        for i, p in enumerate(self.procs):
            rc = p.poll()
            if rc is None:
                all_done = False
            elif rc != 0:
                return i
        return -1 if all_done else None

    def terminate(self):
        self.terminate_alive(grace=10.0)
        for s in self.specs:
            s["file"].close()

    def terminate_alive(self, grace: float = 5.0):
        """SIGTERM then SIGKILL stragglers, keeping log files open so the
        procs can be respawned (terminate() additionally closes the pool)."""
        alive = [p for p in self.procs if p.poll() is None]
        for p in alive:
            p.send_signal(signal.SIGTERM)
        deadline = time.time() + grace
        for p in alive:
            try:
                p.wait(max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    def tail_log(self, i: int, n: int = 30) -> str:
        try:
            with open(self.specs[i]["log"], "r", errors="replace") as f:
                return "".join(f.readlines()[-n:])
        except OSError:
            return "<no log>"


def _advertised_host() -> str:
    """The address peers can reach this node at (the reference reads it from
    POD_IP / the endpoint list; we resolve the hostname with a localhost guard)."""
    ip = os.environ.get("POD_IP")
    if ip:
        return ip
    try:
        ip = socket.gethostbyname(socket.gethostname())
    except OSError:
        ip = "127.0.0.1"
    return ip


def _rendezvous(args, nproc: int):
    """Return (node_rank, master_addr, master_port, all_endpoints, store-or-None).

    Multi-node: node rank is either given (--node_rank) or assigned by arrival
    order through the store's atomic counter (the reference's HTTP/ETCD master,
    controllers/master.py). Every node publishes its worker endpoints — its OWN
    advertised host + locally free ports — and reads back the full list, so all
    ranks agree; rank 0 also publishes a dedicated coordinator port for the
    workers' jax.distributed.initialize (distinct from the store's port)."""
    if args.nnodes <= 1:
        base = _free_port()
        eps = [f"127.0.0.1:{base + i}" for i in range(nproc)]
        return 0, "127.0.0.1", _free_port(), eps, None

    assert args.master, "--master host:port is required when --nnodes > 1"
    host, port_s = args.master.rsplit(":", 1)
    port = int(port_s)
    from ..store import TCPStore

    # The node whose --node_rank is 0 hosts the store. With auto-assign (-1),
    # try joining as a client first; only if no server answers, try to become
    # the host (losing the bind race falls back to client) — so exactly one
    # node ever runs a store server.
    if args.node_rank == 0:
        store = TCPStore(host, port, is_master=True, world_size=args.nnodes)
    elif args.node_rank > 0:
        store = TCPStore(host, port, is_master=False, world_size=args.nnodes)
    else:
        try:
            store = TCPStore(host, port, is_master=False,
                             world_size=args.nnodes, timeout=3.0)
        except (RuntimeError, TimeoutError):
            try:
                store = TCPStore(host, port, is_master=True,
                                 world_size=args.nnodes)
            except RuntimeError:  # lost the bind race to another auto node
                store = TCPStore(host, port, is_master=False,
                                 world_size=args.nnodes)
    rank = args.node_rank
    if rank < 0:
        rank = store.add(f"{args.job_id}/node_arrival", 1) - 1

    my_host = _advertised_host()
    base = _free_port()
    my_eps = ",".join(f"{my_host}:{base + i}" for i in range(nproc))
    store.set(f"{args.job_id}/endpoints/{rank}", my_eps)
    if rank == 0:
        store.set(f"{args.job_id}/worker_master", f"{my_host}:{_free_port()}")
    store.barrier(f"{args.job_id}/nodes_ready", args.nnodes)

    all_endpoints = []
    for n in range(args.nnodes):
        all_endpoints.extend(
            store.get(f"{args.job_id}/endpoints/{n}").decode().split(","))
    master_addr, worker_master_port = \
        store.get(f"{args.job_id}/worker_master").decode().rsplit(":", 1)
    return rank, master_addr, int(worker_master_port), all_endpoints, store


def launch(argv=None) -> int:
    args = _parse_args(argv)
    ps_servers = args.server_num if args.run_mode == "ps" else 0
    trainers = args.trainer_num if (args.run_mode == "ps"
                                    and args.trainer_num is not None) else \
        args.nproc_per_node

    nproc = trainers  # trainer processes per node
    node_rank, master_addr, master_port, all_endpoints, store = \
        _rendezvous(args, nproc)
    world = args.nnodes * nproc
    devices = [d for d in args.devices.split(",") if d]

    procs = ProcList(args.log_dir)
    if args.module is not None:
        script_cmd = [sys.executable, "-m", args.module]
        if args.training_script is not None:  # first arg swallowed the positional
            script_cmd.append(args.training_script)
    else:
        script_cmd = [sys.executable, args.training_script]

    # Children run `python script.py`, which puts the script's dir (not our cwd)
    # on sys.path — make the framework importable from a source checkout by
    # exporting its package root on PYTHONPATH (reference launcher relies on an
    # installed package; launch/controllers/collective.py:23).
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    child_pythonpath = os.pathsep.join(
        p for p in [pkg_root, os.environ.get("PYTHONPATH", "")] if p)

    def worker_env(local_rank: int, role: str = "TRAINER") -> Dict[str, str]:
        global_rank = node_rank * nproc + local_rank
        env = {**os.environ, "PYTHONPATH": child_pythonpath}
        env.update({
            "PADDLE_TRAINER_ID": str(global_rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(all_endpoints),
            "PADDLE_CURRENT_ENDPOINT": all_endpoints[global_rank],
            "PADDLE_NNODES": str(args.nnodes),
            "PADDLE_NODE_RANK": str(node_rank),
            "PADDLE_LOCAL_RANK": str(local_rank),
            "MASTER_ADDR": master_addr,
            "MASTER_PORT": str(master_port),
            "PADDLE_JOB_ID": args.job_id,
            "TRAINING_ROLE": role,
        })
        if devices:
            env["FLAGS_selected_tpus"] = devices[local_rank % len(devices)]
        if args.elastic_level > 0:
            # per-worker preemption flag file: the launcher touches it when a
            # notice arrives; workers poll fleet.elastic.preemption_requested()
            env["PADDLE_ELASTIC_PREEMPT_FILE"] = os.path.join(
                args.log_dir, f".preempt.{role.lower()}.{local_rank}")
        return env

    if args.run_mode == "ps":
        # each node hosts its own ps_servers instances; endpoints are published
        # through the store so every node sees the full, correct list
        server_ports = [_free_port() for _ in range(ps_servers)]
        my_host = _advertised_host() if args.nnodes > 1 else "127.0.0.1"
        my_server_eps = [f"{my_host}:{p}" for p in server_ports]
        if store is not None:
            store.set(f"{args.job_id}/ps_endpoints/{node_rank}",
                      ",".join(my_server_eps))
            store.barrier(f"{args.job_id}/ps_ready", args.nnodes)
            server_eps = []
            for nr in range(args.nnodes):
                server_eps.extend(
                    store.get(f"{args.job_id}/ps_endpoints/{nr}").decode()
                    .split(","))
        else:
            server_eps = my_server_eps
        for i in range(ps_servers):
            env = worker_env(0, role="PSERVER")
            env.update({"PADDLE_PORT": str(server_ports[i]),
                        "PADDLE_PSERVERS_IP_PORT_LIST": ",".join(server_eps),
                        "PADDLE_PSERVER_ID": str(node_rank * ps_servers + i)})
            procs.spawn(script_cmd + args.training_script_args, env, f"server.{i}")
        for i in range(trainers):
            env = worker_env(i, role="TRAINER")
            env["PADDLE_PSERVERS_IP_PORT_LIST"] = ",".join(server_eps)
            procs.spawn(script_cmd + args.training_script_args, env, f"trainer.{i}")
    else:
        for i in range(nproc):
            procs.spawn(script_cmd + args.training_script_args, worker_env(i),
                        f"workerlog.{i}")

    def _preemption_notice():
        """Pending preemption notice for THIS node: a `preempt.notice` file in
        log_dir (single-node / tests / local infra hook) or the elastic store
        key `<job>/preempt/<node_rank>` (multi-node; SURVEY §5.3 maintenance-
        notice contract)."""
        fpath = os.path.join(args.log_dir, "preempt.notice")
        if os.path.exists(fpath):
            return {"source": fpath}
        if store is not None:
            # ElasticManager.announce_preemption keys by HOST; rank is also
            # accepted for infra that addresses nodes by index
            for who in _notice_ids:
                try:
                    store.get(f"{args.job_id}/preempt/{who}", wait=False)
                    return {"source": f"store:{args.job_id}/preempt/{who}"}
                except Exception:
                    pass
        return None

    # host resolved ONCE (DNS can stall); store round-trips throttled to every
    # 4th watch tick so steady-state polling stays cheap
    _notice_ids = ((_advertised_host() if store is not None else ""),
                   str(node_rank))
    _notice_tick = [0]

    def _preemption_notice_throttled():
        _notice_tick[0] += 1
        fpath = os.path.join(args.log_dir, "preempt.notice")
        if os.path.exists(fpath):
            return {"source": fpath}
        if _notice_tick[0] % 4 == 0:
            return _preemption_notice()
        return None

    def _drain_and_respawn():
        """Checkpoint-and-respawn: flag every worker, give it a grace window
        to checkpoint and exit, then restart the whole local pod."""
        for spec in procs.specs:
            flag = spec["env"].get("PADDLE_ELASTIC_PREEMPT_FILE")
            if flag:
                open(flag, "w").close()
        deadline = time.time() + 30.0
        while time.time() < deadline and any(
                p.poll() is None for p in procs.procs):
            time.sleep(0.2)
        procs.terminate_alive()
        fpath = os.path.join(args.log_dir, "preempt.notice")
        if os.path.exists(fpath):
            os.unlink(fpath)
        if store is not None:
            for who in (_advertised_host(), str(node_rank)):
                try:
                    store.delete_key(f"{args.job_id}/preempt/{who}")
                except Exception:
                    pass
        for spec in procs.specs:
            flag = spec["env"].get("PADDLE_ELASTIC_PREEMPT_FILE")
            if flag and os.path.exists(flag):
                os.unlink(flag)
        for i in range(len(procs.procs)):
            procs.respawn(i)

    restarts = 0
    try:
        while True:
            if args.elastic_level > 0 and restarts < args.max_restarts \
                    and _preemption_notice_throttled() is not None:
                restarts += 1
                print(f"paddle_tpu.launch: preemption notice — checkpoint-and-"
                      f"respawn ({restarts}/{args.max_restarts})", flush=True)
                _drain_and_respawn()
                continue
            status = procs.poll()
            if status is None:
                time.sleep(0.5)
                continue
            if status == -1:
                print(f"paddle_tpu.launch: all {len(procs.procs)} processes "
                      f"finished", flush=True)
                return 0
            rc = procs.procs[status].returncode
            name = procs.specs[status]["name"]
            if args.elastic_level > 0 and restarts < args.max_restarts:
                restarts += 1
                print(f"paddle_tpu.launch: {name} exited rc={rc}; restart "
                      f"{restarts}/{args.max_restarts}", flush=True)
                procs.respawn(status)
                continue
            print(f"paddle_tpu.launch: {name} failed rc={rc}; terminating pod.\n"
                  f"--- tail of {procs.specs[status]['log']} ---\n"
                  f"{procs.tail_log(status)}", file=sys.stderr, flush=True)
            procs.terminate()
            return rc or 1
    except KeyboardInterrupt:
        procs.terminate()
        return 130


def main():
    sys.exit(launch())

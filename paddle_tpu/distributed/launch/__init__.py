"""`python -m paddle_tpu.distributed.launch` — the distributed job launcher.

Reference: python/paddle/distributed/launch/ (collective controller at
controllers/collective.py:23, env contract PADDLE_TRAINER_ID /
PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS / PADDLE_CURRENT_ENDPOINT, master
rendezvous at controllers/master.py). TPU-native: one process per host
(multi-controller JAX) instead of one per GPU; a local `--nproc_per_node > 1`
mode still exists for CPU-mesh simulation and tests, and multi-node rendezvous
goes through the C++ TCPStore instead of HTTP/ETCD.
"""
from .main import launch, main  # noqa: F401

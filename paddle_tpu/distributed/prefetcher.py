"""Device-side input prefetch: double/N-buffered sharded host->device staging.

The engine half of the async input pipeline (the io.DataLoader worker pool is
the host half). JAX dispatch is asynchronous: while the current step's XLA
program executes, the host thread is free — so issuing the *next* batches'
sharded ``jax.device_put`` now lets the H2D copies overlap device compute
instead of sitting serially in front of it. This is the input-pipeline
analogue of what MPK does at the kernel level (hide dispatch/transfer latency
behind compute, arXiv:2512.22219) and of FlexLink's keep-the-interconnect-busy
thesis (arXiv:2510.15882); the reference's buffered double-queue is
fluid/operators/reader/buffered_reader.cc.

``DevicePrefetcher`` holds a deque of K batches whose ``device_put`` has been
issued but not consumed. Arrays already placed with a matching sharding are
passed through untouched (counted in ``skipped_puts``). Per-batch H2D issue
wall time and the queue depth at consumption ride along for StepTelemetry.
"""
from __future__ import annotations

import collections
import time
from typing import Callable, Iterable, Iterator, Optional, Sequence, Tuple

__all__ = ["DevicePrefetcher", "is_placed"]


def is_placed(array, sharding) -> bool:
    """True when `array` is a committed device array whose sharding already
    matches `sharding` — re-issuing device_put for it would be redundant."""
    import jax

    try:
        return (isinstance(array, jax.Array)
                and array.committed
                and array.sharding.is_equivalent_to(sharding, array.ndim))
    except Exception:
        return False


class DevicePrefetcher:
    """Issues sharded device_put for the next `depth` batches ahead of use.

    shardings: per-batch-position target shardings, or a callable
        ``arrays -> shardings`` resolved lazily from the first batch (the
        engine passes its spec resolver so shapes drive the default specs).
    depth: how many batches may be in flight (2 = classic double buffer).

    Stats (read after/while iterating): ``batches``, ``puts``,
    ``skipped_puts``, ``h2d_ms_total``, and per-batch ``last_h2d_ms`` /
    ``last_depth`` (queue occupancy when the batch was handed out, i.e. how
    much look-ahead the consumer actually had).
    """

    def __init__(self, shardings, depth: int = 2):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._shardings = shardings
        self.depth = depth
        self.batches = 0
        self.puts = 0
        self.skipped_puts = 0
        self.h2d_ms_total = 0.0
        self.last_h2d_ms = 0.0
        self.last_depth = 0

    def _resolve(self, arrays) -> Sequence:
        if callable(self._shardings):
            self._shardings = tuple(self._shardings(arrays))
        if len(self._shardings) != len(arrays):
            raise ValueError(
                f"prefetcher has {len(self._shardings)} shardings but the "
                f"batch has {len(arrays)} arrays")
        return self._shardings

    def place(self, arrays) -> Tuple[tuple, float]:
        """Issue device_put for one batch (skipping already-placed arrays);
        returns (placed arrays, issue wall ms). device_put is async — the
        returned arrays are futures whose transfer proceeds in the
        background; the wall time is the host-side issue cost."""
        import jax

        shardings = self._resolve(arrays)
        t0 = time.perf_counter()
        out = []
        for a, s in zip(arrays, shardings):
            if is_placed(a, s):
                self.skipped_puts += 1
                out.append(a)
            else:
                self.puts += 1
                out.append(jax.device_put(a, s))
        ms = (time.perf_counter() - t0) * 1000.0
        self.h2d_ms_total += ms
        return tuple(out), ms

    def iterate(self, batches: Iterable) -> Iterator[tuple]:
        """Yield device-placed batches, keeping up to `depth` in flight.

        `batches` yields sequences of arrays (already unwrapped from
        Tensors). The H2D for batch i+1..i+depth is issued before batch i is
        handed to the consumer, so the copies overlap the consumer's device
        compute."""
        it = iter(batches)
        buf = collections.deque()
        exhausted = False
        while True:
            while not exhausted and len(buf) < self.depth:
                try:
                    nxt = next(it)
                except StopIteration:
                    exhausted = True
                    break
                buf.append(self.place(tuple(nxt)))
            if not buf:
                return
            placed, ms = buf.popleft()
            self.batches += 1
            self.last_h2d_ms = ms
            self.last_depth = len(buf) + 1  # this batch + still-in-flight
            yield placed

    def __call__(self, batches: Iterable) -> Iterator[tuple]:
        return self.iterate(batches)

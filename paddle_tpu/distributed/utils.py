"""paddle.distributed.utils: MoE dispatch collectives + helpers.

Reference: python/paddle/distributed/utils.py — global_scatter (:57) /
global_gather (:179) route token rows to/from expert ranks via all-to-all
(operators/collective/global_scatter_op). TPU-native: inside a pjit program
the routing IS lax.all_to_all over the 'ep' axis; eagerly (single process)
the permutation semantics run directly so tests and single-chip code work.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops._helpers import t_


def global_scatter(x, local_count, global_count, group=None, use_calc_stream=True):
    """Send local_count[e] consecutive rows to each expert e; receive
    global_count[e] rows back (single-process semantics: reorder rows into
    expert-major layout; multi-device routing happens through the MoE layer's
    all_to_all inside pjit)."""
    x, lc, gc = t_(x), t_(local_count), t_(global_count)
    lc_np = np.asarray(lc._data).astype(np.int64)
    # expert-major regrouping == identity reordering on one rank
    return Tensor(x._data)


def global_gather(x, local_count, global_count, group=None, use_calc_stream=True):
    """Inverse of global_scatter."""
    x = t_(x)
    return Tensor(x._data)


def expert_count(gate_idx, n_expert) -> Tensor:
    """Rows routed to each expert (reference utils.py expert_count op)."""
    g = t_(gate_idx)

    def count(a):
        return jnp.bincount(a.reshape(-1).astype(jnp.int32), length=n_expert)

    return Tensor(count(g._data).astype(jnp.int64))


def get_cluster(node_ips, node_ip, trainer_endpoints, device_mode="tpu",
                devices_per_proc=None):
    """Launcher helper parity (reference utils.get_cluster)."""
    return {"node_ips": node_ips, "node_ip": node_ip,
            "endpoints": trainer_endpoints, "device_mode": device_mode}

"""Per-slot token sampling with TRACED parameters.

Legacy generate() bakes (temperature, top_k, top_p) into the decode
executable as compile-time constants — one compiled program per sampling
config. The serving decode step instead carries them as per-slot traced
vectors, so ONE executable serves any mix of greedy / top-k / top-p
requests concurrently. Both the bucketed-prefill and the decode-step
programs sample through sample_tokens, so first-token and subsequent-token
sampling cannot drift (pinned by tests/test_serving_engine.py).

Semantics mirror gpt.generate()'s sample(): greedy when temperature == 0;
otherwise scale by temperature, top-k filter (clamped to vocab, <= 0
disables), then top-p nucleus filter over the top-k-filtered distribution
(>= 1 disables), then categorical.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def filter_topk_topp(logits, top_k, top_p):
    """Mask [n, V] logits to the per-row top-k / nucleus top-p support.

    top_k int32 [n] (<= 0 disables; clamped to vocab) and top_p f32 [n]
    (>= 1 disables) are traced, so mixed configs share one executable.
    Returns logits with excluded entries at -inf. Top-p operates on the
    top-k-filtered distribution, matching legacy sample() order.
    """
    vocab = logits.shape[-1]
    top_k = jnp.asarray(top_k, jnp.int32)
    top_p = jnp.asarray(top_p, jnp.float32)
    sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
    k_eff = jnp.clip(top_k, 1, vocab)
    kth = jnp.take_along_axis(sorted_desc, (k_eff - 1)[:, None], axis=-1)
    logits = jnp.where((top_k[:, None] > 0) & (logits < kth),
                       -jnp.inf, logits)
    # nucleus cutoff over the (possibly) top-k-filtered logits
    sorted_f = jnp.sort(logits, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted_f, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.sum(cum < top_p[:, None], axis=-1)
    cutoff = jnp.take_along_axis(
        sorted_f, jnp.clip(cutoff_idx, 0, vocab - 1)[:, None], axis=-1)
    return jnp.where((top_p[:, None] < 1.0) & (logits < cutoff),
                     -jnp.inf, logits)


def sample_tokens(logits, keys, temperature, top_k, top_p):
    """Sample one token per row: [n, V] logits, [n] PRNG keys, per-row
    traced temperature/top_k/top_p. Returns int32 [n]. temperature == 0
    selects greedy argmax for that row (the sampling branch still traces,
    its result is discarded by the select)."""
    logits = jnp.asarray(logits, jnp.float32)
    temperature = jnp.asarray(temperature, jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    filtered = filter_topk_topp(scaled, top_k, top_p)
    sampled = jax.vmap(jax.random.categorical)(keys, filtered)
    return jnp.where(temperature == 0.0, greedy, sampled.astype(jnp.int32))


def request_key(seed, position, base=None):
    """Deterministic per-(request, position) PRNG key: the token emitted at
    sequence position p for a request with seed s is sampled with
    fold_in(fold_in(base, s), p) — identical whether it comes from the
    prefill program (first token) or the decode step (every later token),
    and independent of which slot the request landed in or what its
    neighbors did. Traceable (seed/position may be tracers)."""
    if base is None:
        base = jax.random.key(0)
    return jax.random.fold_in(jax.random.fold_in(base, seed), position)


# Speculative-decode stream salts: the draft proposal and the acceptance
# uniform for position p must each draw from streams DISJOINT from the
# request_key(seed, p) stream — the residual/bonus sample at p reuses the
# plain stream so a fully-accepted window emits the exact token sequential
# decode would have sampled there.
DRAFT_SALT = 0x5BEC
ACCEPT_SALT = 0xACCE


def spec_key(seed, position, salt):
    """request_key folded one level deeper — the draft-proposal and
    acceptance-uniform streams of speculative decoding."""
    return jax.random.fold_in(request_key(seed, position), salt)


def filtered_probs(logits, temperature, top_k, top_p):
    """Per-row post-filter sampling distribution [n, V] — softmax over the
    temperature-scaled, top-k/top-p-masked logits. This is the p(token)
    both sides of the speculative acceptance test u < p_t(d)/p_d(d) must
    agree on (filtering applied to target and draft identically, or the
    leftover-distribution correction loses its exactness)."""
    logits = jnp.asarray(logits, jnp.float32)
    temperature = jnp.asarray(temperature, jnp.float32)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    return jax.nn.softmax(filter_topk_topp(scaled, top_k, top_p), axis=-1)


def residual_sample(keys, p_target, p_draft):
    """Leftover-distribution sample after a rejected draft token: one draw
    per row from normalize(max(p_t - p_d, 0)) (Leviathan et al. speculative
    sampling). Rows where the residual has zero mass (p_t == p_d exactly —
    unreachable in exact arithmetic because the acceptance ratio is then 1)
    fall back to p_t. Returns int32 [n]."""
    res = jnp.maximum(p_target - p_draft, 0.0)
    mass = jnp.sum(res, axis=-1, keepdims=True)
    res = jnp.where(mass > 0.0, res, p_target)
    logp = jnp.log(jnp.maximum(res, 1e-38))
    return jax.vmap(jax.random.categorical)(keys, logp).astype(jnp.int32)

"""Open-loop traffic generator: replayable load scenarios for the serving
fleet.

A ``Scenario`` declares everything about a traffic episode in plain JSON —
the arrival process (Poisson / diurnal / spike / batch), the prompt- and
output-length mixes (heavy-tailed lognormal, weighted choice, deterministic
cycle), and the tenant skew — and compiles it into a *schedule*: a list of
(arrival offset, tenant, prompt_len, max_new, phase) rows. The schedule is
a pure function of the scenario fields and its seed (``random.Random``
only, no wall clock, fixed draw order per event), so the same scenario
file replays byte-identically: ``schedule_doc()`` is canonical JSON and
two runs — or a save/load round-trip of the file — produce the same bytes.
That replayability is what makes autoscale drills pinnable evidence
(tools/elastic_drill.py) rather than flaky load tests.

``LoadGenerator`` drives the schedule *open-loop* against a ReplicaRouter
(or a bare ServingEngine): requests are submitted at their scheduled
offsets regardless of completions — the defining property of an offered-
load harness; a closed loop would throttle itself exactly when the fleet
degrades, hiding the overload the drill exists to create. Between
arrivals it steps the router and invokes an optional ``on_tick`` hook
(SLO engine tick + CapacityController poll in the drills). Per-request
TTFT/TPOT/outcome flow through the engines' existing sinks (tenant
included); ``summary()`` reduces the episode to offered load vs goodput
and per-phase p50/p99.

serve_bench.py builds its mixed-length workload from a Scenario too, so
the repo has exactly one arrival-process/length-mix implementation.

Stdlib-only: no jax, no numpy — prompt token ids are plain int lists
(the engine normalizes).
"""
from __future__ import annotations

import json
import math
import random
import time
from typing import Callable, Dict, List, Optional, Sequence

ARRIVAL_PROCESSES = ("poisson", "diurnal", "spike", "batch")
LENGTH_DISTS = ("fixed", "lognormal", "choice", "cycle")

# hard cap on schedule length: a mis-typed rate must fail loudly, not OOM
MAX_EVENTS = 1_000_000


def _canon(doc) -> str:
    """Canonical JSON — the byte-identity the replay tests pin."""
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))


def _draw_len(spec: dict, rnd: random.Random, index: int) -> int:
    """One length draw. Draw order is part of the replay contract: exactly
    one rnd consumption per call for the stochastic dists, zero for the
    deterministic ones."""
    dist = spec.get("dist", "fixed")
    if dist == "fixed":
        return int(spec["value"])
    if dist == "lognormal":
        # heavy-tailed: median/sigma parameterization (exp(mu) = median)
        v = rnd.lognormvariate(math.log(float(spec["median"])),
                               float(spec.get("sigma", 0.5)))
        lo = int(spec.get("min", 1))
        hi = int(spec.get("max", 1 << 30))
        return max(lo, min(hi, int(round(v))))
    if dist == "choice":
        values = spec["values"]
        weights = spec.get("weights")
        if weights is None:
            return int(values[int(rnd.random() * len(values))
                              % len(values)])
        return int(rnd.choices(values, weights=weights, k=1)[0])
    if dist == "cycle":
        # deterministic: request i takes values[i % n] (serve_bench's
        # mixed-length ladder sweep); consumes no randomness
        values = spec["values"]
        return int(values[index % len(values)])
    raise ValueError(f"unknown length dist {dist!r} "
                     f"(expected one of {LENGTH_DISTS})")


def zipf_tenants(count: int, s: float = 1.1,
                 prefix: str = "t") -> List[dict]:
    """Zipf-skewed tenant table: weight(k) = 1/k^s — the canonical
    multi-tenant shape (a few tenants dominate the traffic)."""
    return [{"name": f"{prefix}{k}", "weight": 1.0 / (k + 1) ** float(s)}
            for k in range(count)]


class Scenario:
    """A replayable load scenario (see module doc for the JSON schema).

    Fields::

        name        str
        seed        int      — the only entropy source
        duration_s  float    — arrival horizon (scenario time)
        arrival     dict     — {"process": "poisson"|"diurnal"|"spike"|
                               "batch", "rate_rps": ..., ...}
        prompt_len  dict     — length dist (fixed|lognormal|choice|cycle)
        max_new     dict     — output-length dist (same grammar)
        tenants     [dict]   — [{"name", "weight"}]; skew = weights

    Arrival parameters: ``diurnal`` adds ``period_s`` + ``amplitude``
    (rate(t) = rate*(1 + A*sin(2πt/P)), phases "peak"/"trough");
    ``spike`` adds ``spike_at_s``, ``spike_len_s``, ``spike_factor``
    (phase "spike" inside the window, "base" outside); ``batch`` adds
    ``count`` (all arrivals at t=0 — the bench's submit-everything shape).
    """

    def __init__(self, name: str, seed: int = 0, duration_s: float = 10.0,
                 arrival: Optional[dict] = None,
                 prompt_len: Optional[dict] = None,
                 max_new: Optional[dict] = None,
                 tenants: Optional[Sequence[dict]] = None):
        self.name = str(name)
        self.seed = int(seed)
        self.duration_s = float(duration_s)
        self.arrival = dict(arrival or {"process": "poisson",
                                        "rate_rps": 1.0})
        proc = self.arrival.get("process")
        if proc not in ARRIVAL_PROCESSES:
            raise ValueError(f"unknown arrival process {proc!r} "
                             f"(expected one of {ARRIVAL_PROCESSES})")
        self.prompt_len = dict(prompt_len or {"dist": "fixed", "value": 8})
        self.max_new = dict(max_new or {"dist": "fixed", "value": 8})
        self.tenants = [dict(t) for t in
                        (tenants or [{"name": "default", "weight": 1.0}])]
        if not self.tenants:
            raise ValueError("Scenario needs at least one tenant")
        total = sum(float(t.get("weight", 1.0)) for t in self.tenants)
        if total <= 0:
            raise ValueError("tenant weights must sum > 0")
        self._cum = []
        acc = 0.0
        for t in self.tenants:
            acc += float(t.get("weight", 1.0)) / total
            self._cum.append((acc, t["name"]))

    # ------------------------------------------------------- serialization
    def to_dict(self) -> dict:
        return {
            "name": self.name, "seed": self.seed,
            "duration_s": self.duration_s, "arrival": dict(self.arrival),
            "prompt_len": dict(self.prompt_len),
            "max_new": dict(self.max_new),
            "tenants": [dict(t) for t in self.tenants],
        }

    @classmethod
    def from_dict(cls, doc: dict) -> "Scenario":
        return cls(**doc)

    def dumps(self) -> str:
        return _canon(self.to_dict())

    @classmethod
    def loads(cls, text: str) -> "Scenario":
        return cls.from_dict(json.loads(text))

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            f.write(json.dumps(self.to_dict(), indent=2, sort_keys=True)
                    + "\n")
        return path

    @classmethod
    def load(cls, path: str) -> "Scenario":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    # ------------------------------------------------------------ arrivals
    def _rate_at(self, t: float) -> float:
        a = self.arrival
        base = float(a.get("rate_rps", 1.0))
        proc = a["process"]
        if proc == "poisson":
            return base
        if proc == "diurnal":
            period = float(a.get("period_s", self.duration_s))
            amp = float(a.get("amplitude", 0.5))
            return base * max(0.0, 1.0 + amp * math.sin(
                2.0 * math.pi * t / period))
        if proc == "spike":
            at = float(a.get("spike_at_s", self.duration_s / 3.0))
            ln = float(a.get("spike_len_s", self.duration_s / 3.0))
            if at <= t < at + ln:
                return base * float(a.get("spike_factor", 10.0))
            return base
        raise ValueError(proc)

    def _peak_rate(self) -> float:
        a = self.arrival
        base = float(a.get("rate_rps", 1.0))
        if a["process"] == "diurnal":
            return base * (1.0 + abs(float(a.get("amplitude", 0.5))))
        if a["process"] == "spike":
            return base * float(a.get("spike_factor", 10.0))
        return base

    def _phase_at(self, t: float) -> str:
        a = self.arrival
        proc = a["process"]
        if proc == "diurnal":
            return ("peak" if self._rate_at(t) >= float(a.get("rate_rps",
                                                              1.0))
                    else "trough")
        if proc == "spike":
            at = float(a.get("spike_at_s", self.duration_s / 3.0))
            ln = float(a.get("spike_len_s", self.duration_s / 3.0))
            return "spike" if at <= t < at + ln else "base"
        return "base"

    def _arrival_times(self, rnd: random.Random) -> List[float]:
        a = self.arrival
        if a["process"] == "batch":
            return [0.0] * int(a.get("count", 1))
        # thinning (Lewis & Shedler): draw a homogeneous Poisson stream at
        # the peak rate, keep each point with prob rate(t)/peak. Exactly
        # two rnd draws per candidate — the replay contract.
        peak = self._peak_rate()
        if peak <= 0:
            return []
        out = []
        t = 0.0
        for _ in range(MAX_EVENTS):
            t += rnd.expovariate(peak)
            if t >= self.duration_s:
                return out
            if rnd.random() * peak < self._rate_at(t):
                out.append(t)
        raise ValueError(
            f"scenario {self.name!r} exceeds {MAX_EVENTS} arrivals "
            f"(rate_rps x duration_s too large)")

    def _tenant(self, rnd: random.Random) -> str:
        r = rnd.random()
        for acc, name in self._cum:
            if r <= acc:
                return name
        return self._cum[-1][1]

    # ------------------------------------------------------------ schedule
    def schedule(self) -> List[dict]:
        """Compile the scenario into arrival rows, strictly deterministic
        in (fields, seed). Row: {"i", "t", "phase", "tenant",
        "prompt_len", "max_new"}."""
        rnd = random.Random(f"loadgen:{self.seed}:{self.name}")
        times = self._arrival_times(rnd)
        rows = []
        for i, t in enumerate(times):
            # fixed per-event draw order: tenant, prompt_len, max_new
            rows.append({
                "i": i, "t": round(t, 9), "phase": self._phase_at(t),
                "tenant": self._tenant(rnd),
                "prompt_len": _draw_len(self.prompt_len, rnd, i),
                "max_new": _draw_len(self.max_new, rnd, i),
            })
        return rows

    def schedule_doc(self) -> str:
        """The schedule as canonical JSON — byte-identical across runs and
        across a scenario-file save/load round-trip."""
        return _canon({"scenario": self.name, "seed": self.seed,
                       "schedule": self.schedule()})

    def prompt_tokens(self, index: int, prompt_len: int,
                      vocab: int) -> List[int]:
        """Deterministic per-request prompt ids: a function of (seed,
        index) only, so replays regenerate identical token streams without
        storing them in the scenario file."""
        rnd = random.Random(f"loadgen:{self.seed}:prompt:{index}")
        return [rnd.randrange(vocab) for _ in range(prompt_len)]


def _pctl(xs: Sequence[float], q: float) -> Optional[float]:
    if not xs:
        return None
    xs = sorted(xs)
    k = (len(xs) - 1) * q
    lo = int(k)
    hi = min(lo + 1, len(xs) - 1)
    return xs[lo] + (xs[hi] - xs[lo]) * (k - lo)


class LoadGenerator:
    """Drive a Scenario's schedule open-loop against a router/engine.

    target: anything with ``submit(prompt_ids, max_new_tokens=...,
    tenant=...)`` + ``step()`` + ``pending()`` — a ReplicaRouter, or a
    bare ServingEngine (``pending()`` falls back to queue+active).
    prompt_fn(row) -> token ids overrides the default seeded prompts
    (vocab required for the default). time_scale compresses scenario
    seconds into wall seconds (0.1 = 10x faster); 0 submits as fast as
    the drive loop allows while preserving arrival *order*.
    """

    def __init__(self, scenario: Scenario, target,
                 prompt_fn: Optional[Callable[[dict], Sequence[int]]] = None,
                 vocab: Optional[int] = None, time_scale: float = 1.0,
                 submit_kwargs: Optional[dict] = None):
        if prompt_fn is None and vocab is None:
            raise ValueError("LoadGenerator needs prompt_fn or vocab")
        self.scenario = scenario
        self.target = target
        self.prompt_fn = prompt_fn
        self.vocab = vocab
        self.time_scale = float(time_scale)
        self.submit_kwargs = dict(submit_kwargs or {})
        self.handles: List = []      # (row, Request) pairs, arrival order
        self.schedule_ms: Optional[float] = None
        self._wall_t0: Optional[float] = None
        self._wall_t1: Optional[float] = None

    def _pending(self) -> int:
        t = self.target
        if hasattr(t, "pending"):
            return t.pending()
        return t.queue_depth() + int(t._active.sum())

    def _prompt(self, row: dict) -> Sequence[int]:
        if self.prompt_fn is not None:
            return self.prompt_fn(row)
        return self.scenario.prompt_tokens(row["i"], row["prompt_len"],
                                           self.vocab)

    def run(self, on_tick: Optional[Callable[[], None]] = None,
            drain: bool = True) -> List:
        """Submit every scheduled arrival at its (scaled) offset, stepping
        the target and calling ``on_tick`` between arrivals; with
        ``drain`` (default) keep driving until the fleet finishes every
        request. Returns the (row, Request) pairs."""
        t0 = time.perf_counter()
        rows = self.scenario.schedule()
        self.schedule_ms = (time.perf_counter() - t0) * 1000.0

        def tick():
            self.target.step()
            if on_tick is not None:
                on_tick()

        self._wall_t0 = time.perf_counter()
        for row in rows:
            due = self._wall_t0 + row["t"] * self.time_scale
            while time.perf_counter() < due:
                if self._pending():
                    tick()
                else:
                    if on_tick is not None:
                        on_tick()
                    time.sleep(min(0.001, max(0.0, due
                                              - time.perf_counter())))
            req = self.target.submit(
                self._prompt(row), max_new_tokens=row["max_new"],
                tenant=row["tenant"], **self.submit_kwargs)
            self.handles.append((row, req))
        while drain and self._pending():
            tick()
        self._wall_t1 = time.perf_counter()
        return self.handles

    # ------------------------------------------------------------- summary
    def summary(self) -> dict:
        """Scenario-summary doc: offered load vs goodput, outcome counts,
        per-phase and per-tenant breakdowns with p50/p99 TTFT/TPOT."""
        rows_reqs = self.handles
        wall_s = ((self._wall_t1 or time.perf_counter())
                  - (self._wall_t0 or time.perf_counter())) or 1e-9
        horizon = max([r["t"] for r, _ in rows_reqs] or [0.0]) or 1e-9
        outcomes: Dict[str, int] = {}
        per_phase: Dict[str, dict] = {}
        per_tenant: Dict[str, int] = {}
        good = 0
        for row, req in rows_reqs:
            o = req.outcome or ("ok" if req.done else "incomplete")
            outcomes[o] = outcomes.get(o, 0) + 1
            if o in ("ok", "eos", "length"):
                good += 1
            per_tenant[row["tenant"]] = per_tenant.get(row["tenant"], 0) + 1
            ph = per_phase.setdefault(row["phase"],
                                      {"n": 0, "ttft_ms": [], "tpot_ms": []})
            ph["n"] += 1
            if req.ttft_s is not None:
                ph["ttft_ms"].append(req.ttft_s * 1e3)
            if req.tpot_s is not None:
                ph["tpot_ms"].append(req.tpot_s * 1e3)
        phases = {}
        for name, ph in sorted(per_phase.items()):
            entry = {"n": ph["n"]}
            for key, xs in (("ttft_ms", ph["ttft_ms"]),
                            ("tpot_ms", ph["tpot_ms"])):
                for q in (50, 99):
                    v = _pctl(xs, q / 100)
                    entry[f"p{q}_{key}"] = (round(v, 3) if v is not None
                                            else None)
            phases[name] = entry
        return {
            "scenario": self.scenario.name, "seed": self.scenario.seed,
            "requests": len(rows_reqs),
            "offered_rps": round(len(rows_reqs) / horizon, 3),
            "goodput_rps": round(good / wall_s, 3),
            "good": good, "outcomes": outcomes,
            "wall_s": round(wall_s, 4),
            "time_scale": self.time_scale,
            "schedule_ms": (round(self.schedule_ms, 3)
                            if self.schedule_ms is not None else None),
            "per_phase": phases,
            "per_tenant": dict(sorted(per_tenant.items())),
        }


def spike_scenario(name: str = "spike10x", seed: int = 7,
                   duration_s: float = 6.0, rate_rps: float = 2.0,
                   spike_factor: float = 10.0,
                   prompt_median: int = 6, max_new: int = 3,
                   tenants: Optional[Sequence[dict]] = None) -> Scenario:
    """The pinned autoscale-drill shape: steady base load, a 10x spike in
    the middle third, heavy-tailed prompts, skewed tenants."""
    return Scenario(
        name=name, seed=seed, duration_s=duration_s,
        arrival={"process": "spike", "rate_rps": rate_rps,
                 "spike_at_s": duration_s / 3.0,
                 "spike_len_s": duration_s / 3.0,
                 "spike_factor": spike_factor},
        prompt_len={"dist": "lognormal", "median": prompt_median,
                    "sigma": 0.4, "min": 2, "max": 24},
        max_new={"dist": "fixed", "value": max_new},
        tenants=list(tenants) if tenants else zipf_tenants(3),
    )

"""Prompt-length bucketing: the shape-stability half of the serving engine.

Real traffic carries a long tail of prompt lengths; jit-keying any decode
artifact on the exact length means one XLA compile per distinct length. The
ladder quantizes lengths into a small geometric set of rungs — prompts are
right-padded to the smallest rung that fits, so the whole traffic
distribution shares O(#rungs) prefill executables. Causal attention makes
right-padding semantically free (see models/gpt.py generate docstring).
"""
from __future__ import annotations

from typing import Iterable, Sequence, Tuple

#: Default geometric rung set; clip to the model's max_seq_len with
#: clip_ladder before use.
DEFAULT_LADDER: Tuple[int, ...] = (64, 128, 256, 512)


def clip_ladder(ladder: Iterable[int], max_len: int,
                reserve: int = 0) -> Tuple[int, ...]:
    """Sorted, deduplicated rungs that fit max_len - reserve (reserve =
    decode headroom, e.g. the per-request max_new_tokens cap). Always keeps
    at least one rung: if every rung is too large, the largest feasible
    length itself becomes the single rung."""
    fit = max_len - reserve
    if fit <= 0:
        raise ValueError(f"max_len {max_len} leaves no room after "
                         f"reserving {reserve}")
    rungs = sorted({int(r) for r in ladder if 0 < int(r) <= fit})
    return tuple(rungs) if rungs else (fit,)


def bucket_for(length: int, ladder: Sequence[int] = DEFAULT_LADDER) -> int:
    """Smallest rung >= length. Raises when the prompt exceeds the ladder."""
    if length <= 0:
        raise ValueError(f"prompt length must be positive, got {length}")
    for rung in sorted(int(r) for r in ladder):
        if length <= rung:
            return rung
    raise ValueError(f"prompt length {length} exceeds the bucket ladder "
                     f"{tuple(sorted(ladder))}")


def resolve_bucket(length: int, bucket) -> int:
    """Resolve a generate(prompt_bucket=...) argument: an int is an explicit
    rung, any iterable is a ladder (smallest fitting rung wins)."""
    if isinstance(bucket, bool):
        raise TypeError("prompt_bucket must be an int rung or a ladder of "
                        "ints, not a bool")
    if isinstance(bucket, int):
        if length > bucket:
            raise ValueError(f"prompt length {length} exceeds prompt_bucket "
                             f"{bucket}")
        return int(bucket)
    return bucket_for(length, tuple(bucket))

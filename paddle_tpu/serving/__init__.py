"""Shape-stable serving: bucketed prefill, slot KV cache, continuous
batching (see engine.py for the design).

Quick start::

    from paddle_tpu.serving import ServingEngine

    eng = ServingEngine(model, slot_count=4, ladder=(16, 32, 64),
                        max_new_cap=32)
    reqs = [eng.submit(prompt, max_new_tokens=24, eos_token_id=eos)
            for prompt in prompts]
    eng.run()                      # continuous batching until drained
    outs = [r.output_ids() for r in reqs]

core.monitor counters: serving.prefill_compiles (bounded by the bucket
ladder), serving.decode_compiles (one executable), serving.steps,
serving.tokens, serving.requests, serving.prefill_dispatches; the paged
layout (kv_pages.py / prefix_cache.py / router.py) adds
serving.prefix_lookups, serving.prefix_hits, serving.prefill_skips;
legacy generate() adds decode.jit_compiles / decode.cache_evictions
(LRU-bounded executable cache).
"""
from .bucketing import (  # noqa: F401
    DEFAULT_LADDER, bucket_for, clip_ladder, resolve_bucket,
)
from .engine import Request, ServingEngine  # noqa: F401
from .kv_pages import PagePool, PoolExhausted  # noqa: F401
from .loadgen import (  # noqa: F401
    LoadGenerator, Scenario, spike_scenario, zipf_tenants,
)
from .prefix_cache import RadixPrefixCache  # noqa: F401
from .router import ReplicaRouter  # noqa: F401
from .sampling import (  # noqa: F401
    filter_topk_topp, request_key, sample_tokens,
)

__all__ = [
    "ServingEngine", "Request", "ReplicaRouter",
    "Scenario", "LoadGenerator", "spike_scenario", "zipf_tenants",
    "PagePool", "PoolExhausted", "RadixPrefixCache",
    "DEFAULT_LADDER", "bucket_for", "clip_ladder", "resolve_bucket",
    "sample_tokens", "filter_topk_topp", "request_key",
]

"""Shape-stable serving engine: bucketed prefill + slot KV cache +
continuous-batching decode.

Legacy generate() compiles one monolithic prefill+scan program per exact
(batch, prompt_len, max_new_tokens, sampling-config) tuple and always burns
max_new_tokens scan steps. Under mixed traffic that is a recompile per
shape class and wasted steps past every early EOS. The engine splits
generation into two shape-stable compiled artifacts instead (the
resident-program philosophy of MPK, arxiv 2512.22219):

- **bucketed prefill**, one executable per prompt-bucket rung: the prompt
  is right-padded to the rung, run through the model with causal masking,
  and its K/V scattered into this request's row of the slot cache. The
  true prompt length, target slot, sampling params, and seed are all
  traced, so a whole traffic distribution shares O(#rungs) executables.
- **a single-token decode step**, ONE executable total: operates on the
  fixed [slots, max_seq_len, nh, hd] donated KV cache with per-slot write
  offsets, per-slot sampling params (traced — mixed greedy/top-k/top-p
  share the program), per-slot EOS/budget masks, and per-slot RNG streams.

On top sits continuous batching: finished sequences retire their slot
mid-flight and queued requests are prefilled into free slots between decode
steps — the decode loop itself never recompiles and never runs a step for
work that is already done (only for idle slots while ANY slot is live,
which is the slot-occupancy metric the telemetry records).

CPU-demonstrable (tools/serve_bench.py); the same two executables are what
a TPU deployment keeps resident.
"""
from __future__ import annotations

import itertools
import signal
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence

from ..core import flags as _flags
from ..core.exec_registry import ExecutableRegistry
from ..observability import exec_introspect as _obs_exec
from ..observability import exporter as _obs_exporter
from ..observability import flight_recorder as _obs_flight
from ..observability import metrics as _obs_metrics
from ..observability import tracer as _obs_tracer
from .bucketing import DEFAULT_LADDER, bucket_for, clip_ladder

_NO_EOS = -1

# slot-occupancy fractions live in (0, 1]: linear buckets, not the default
# log-spaced latency boundaries
_OCCUPANCY_BUCKETS = tuple(round(0.1 * i, 1) for i in range(1, 11))


class Request:
    """One generation request and its lifecycle record."""

    _ids = itertools.count()

    def __init__(self, prompt_ids, max_new_tokens, temperature, top_k, top_p,
                 eos_token_id, seed, trace_ctx=None, tenant=None,
                 speculate_k=0):
        import numpy as np

        self.id = next(Request._ids)
        # multi-tenant attribution (serving/loadgen.py scenarios): carried
        # into the serve_request sink record so per-tenant latency/goodput
        # can be cut offline; None = untagged, zero extra cost
        self.tenant = tenant if tenant is None else str(tenant)
        # fleet trace identity (observability.fleet.TraceContext or any
        # object with span_args()): set by the ReplicaRouter so engine-side
        # spans carry the request id + the placement span as parent_span
        self.trace_ctx = trace_ctx
        self.prompt_ids = np.asarray(prompt_ids, np.int64).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos_token_id = (int(eos_token_id) if eos_token_id is not None
                             else None)
        self.seed = int(seed)
        # speculative decoding opt-in: > 0 asks the engine to draft this
        # many tokens per verify window (snapped up to the engine's
        # spec_ladder rung; requires a draft model). Proposed/accepted/
        # bonus accumulate across the request's verify dispatches.
        self.speculate_k = int(speculate_k)
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.spec_bonus = 0
        self.tokens: List[int] = []      # generated tokens (incl. eos if hit)
        self.prefix_hit = False          # paged: >= 1 page matched the trie
        self.shared_tokens = 0           # paged: prompt tokens served from
        self.tail_bucket: Optional[int] = None  # shared pages (no prefill)
        self.bucket: Optional[int] = None
        self.slot: Optional[int] = None
        self.queue_depth_at_submit = 0
        self.submit_ts: Optional[float] = None
        self.admit_ts: Optional[float] = None
        self.first_token_ts: Optional[float] = None
        self.done_ts: Optional[float] = None
        self.finish_reason: Optional[str] = None  # "eos" | "length"
        # terminal disposition, set when the request leaves the engine:
        # "ok" | "eos" | "length" (normal), "drained" (drain timeout cut
        # it short), "error" (prefill/decode raised) — the error-rate
        # SLI's numerator/denominator
        self.outcome: Optional[str] = None

    @property
    def done(self) -> bool:
        return self.done_ts is not None

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_ts is None or self.submit_ts is None:
            return None
        return self.first_token_ts - self.submit_ts

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.admit_ts is None or self.submit_ts is None:
            return None
        return self.admit_ts - self.submit_ts

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean time-per-output-token after the first (None until done or
        when only one token was generated)."""
        if (self.done_ts is None or self.first_token_ts is None
                or len(self.tokens) < 2):
            return None
        return (self.done_ts - self.first_token_ts) / (len(self.tokens) - 1)

    def trace_args(self, **kw) -> dict:
        """Span-args dict for this request's trace events: local id plus
        the propagated fleet request id / parent placement span (if any)."""
        out = {"request": self.id}
        if self.trace_ctx is not None:
            out.update(self.trace_ctx.span_args())
        out.update(kw)
        return out

    def output_ids(self):
        """[prompt + generated] (no post-EOS padding; pad with eos to
        compare against legacy generate() fixed-length output)."""
        import numpy as np

        return np.concatenate(
            [self.prompt_ids, np.asarray(self.tokens, np.int64)])

    def __repr__(self):
        return (f"Request(id={self.id}, prompt={len(self.prompt_ids)}, "
                f"new={len(self.tokens)}/{self.max_new_tokens}, "
                f"done={self.done})")


class ServingEngine:
    """Continuous-batching GPT serving over a slot-based KV cache.

    model: a GPTForPretraining (eval mode is forced). slot_count fixes the
    decode batch; ladder the prefill rungs (clipped to what fits
    max_seq_len with max_new_cap headroom). Weights are snapshotted (and
    pre-cast to the active AMP compute dtype) at construction — call
    refresh_params() after updating the model.

    sink: StepTelemetry-style sink (write(dict)/close()) receiving one
    "serve_request" record per completed request (TTFT, tokens/s, slot,
    bucket, queue depth) and one "serve_step" record per decode step (slot
    occupancy, queue depth). None = no telemetry, no overhead.

    Single-driver: submit() is thread-safe, step()/run() must be called
    from one thread.
    """

    def __init__(self, model, slot_count: int = 4,
                 ladder: Sequence[int] = DEFAULT_LADDER,
                 max_seq_len: Optional[int] = None,
                 max_new_cap: int = 64, steps_per_dispatch: int = 8,
                 sink=None, kv_layout: str = "contiguous",
                 kv_page_tokens: Optional[int] = None,
                 kv_num_pages: Optional[int] = None,
                 kv_cache_dtype: Optional[str] = None,
                 draft_model=None, spec_ladder: Sequence[int] = (4,)):
        import jax.numpy as jnp
        import numpy as np

        cfg = model.config
        self.model = model
        model.eval()
        # speculative decoding (opt-in per request via submit(speculate_k=)):
        # a small draft GPT proposes k tokens, one shape-stable verify
        # dispatch scores all k+1 positions through the target. The draft
        # shares the target's tokenizer space — vocab agreement is a hard
        # precondition of token-level acceptance.
        self.draft_model = draft_model
        if draft_model is not None:
            draft_model.eval()
            if draft_model.config.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_model.config.vocab_size} != target "
                    f"vocab {cfg.vocab_size}: speculative acceptance "
                    "compares token ids, the vocabularies must agree")
            self.spec_ladder = tuple(sorted(int(k) for k in spec_ladder))
            if not self.spec_ladder or min(self.spec_ladder) < 1:
                raise ValueError(
                    f"spec_ladder must be non-empty positive rungs, got "
                    f"{spec_ladder!r}")
        else:
            self.spec_ladder = ()
        self.slot_count = int(slot_count)
        if self.slot_count < 1:
            raise ValueError(f"slot_count must be >= 1, got {slot_count}")
        self.max_seq_len = int(min(max_seq_len or cfg.max_seq_len,
                                   cfg.max_seq_len))
        self.max_new_cap = int(max_new_cap)
        if self.max_new_cap < 1 or self.max_new_cap >= self.max_seq_len:
            raise ValueError(
                f"max_new_cap {max_new_cap} must be in [1, max_seq_len)")
        self.ladder = clip_ladder(ladder, self.max_seq_len,
                                  reserve=self.max_new_cap)
        # decode steps fused into one dispatch (inner lax.scan): divides the
        # per-step host round-trip by N at the cost of (a) retired slots
        # idling masked until the chunk ends (<= N-1 wasted slot-steps per
        # retirement) and (b) admissions landing on chunk boundaries. Still
        # ONE decode executable; N is static in its key.
        self.steps_per_dispatch = max(1, int(steps_per_dispatch))
        self.sink = sink
        # PADDLE_TPU_METRICS_PORT / PADDLE_TPU_FLIGHT_DIR opt-ins: one
        # getenv each when unset, zero per-step cost while off
        _obs_exporter.ensure_started_from_env()
        _obs_flight.ensure_from_env()

        self._lock = threading.Lock()
        self._queue: deque[Request] = deque()
        self._completed: List[Request] = []
        self._steps = 0
        # elastic drain state (distributed/membership.py protocol): once
        # draining, submit() refuses and _admit() stops pulling the queue —
        # active slots run to completion, then the replica retires
        self._draining = False
        self._replica_agent = None
        self._prev_sigterm = None
        # set by ReplicaRouter (or the owner): when non-None, _finish
        # additionally publishes serve.replica.<name>.* metrics — the
        # per-replica namespace the SLO self-healing hooks key on
        self.replica_name: Optional[str] = None

        self.refresh_params()

        nh = cfg.num_heads
        hd = cfg.hidden_size // cfg.num_heads
        S, T = self.slot_count, self.max_seq_len
        if kv_layout not in ("contiguous", "paged"):
            raise ValueError(
                f"kv_layout must be 'contiguous' or 'paged', got {kv_layout!r}")
        self.kv_layout = kv_layout
        if kv_layout == "paged":
            # paged KV: per-layer page pools + ONE [slots, max_pages] page
            # table traced into prefill/decode as a gather index
            # (kv_pages.py). Shapes stay static so the two-executable
            # design and donation survive; the radix prefix cache
            # (prefix_cache.py) shares whole prompt pages across requests.
            from . import kv_pages as _kvp
            from .prefix_cache import RadixPrefixCache

            pt = int(kv_page_tokens if kv_page_tokens is not None
                     else _flags.flag("kv_page_tokens"))
            if pt < 1:
                raise ValueError(f"kv_page_tokens must be >= 1, got {pt}")
            self.page_tokens = pt
            self.max_pages = -(-T // pt)                  # ceil(T / pt)
            self._t_eff = self.max_pages * pt
            mode = (kv_cache_dtype if kv_cache_dtype is not None
                    else _flags.flag("kv_cache_dtype"))
            self._store_dtype, self._kv_quantized = _kvp.resolve_store_dtype(
                mode, self._cache_dtype)
            # default pool covers the contiguous worst case (every slot at
            # max_seq_len) so it can never exhaust; pass kv_num_pages to
            # trade bytes for admission-time eviction pressure
            self.num_pages = int(kv_num_pages if kv_num_pages is not None
                                 else S * self.max_pages + _kvp.RESERVED_PAGES)
            self._pool = _kvp.PagePool(self.num_pages)
            self._prefix = RadixPrefixCache(self._pool, pt)
            self._pool_state = _kvp.make_pool_state(
                cfg.num_layers, self.num_pages, pt, nh, hd, S,
                self.max_pages, self._store_dtype, self._kv_quantized)
            self._tables = np.zeros((S, self.max_pages), np.int32)
            self._slot_pages: List[List[int]] = [[] for _ in range(S)]
            self._replay = np.zeros(S, bool)
            self._kcs = self._vcs = None
        else:
            self._kcs = [jnp.zeros((S, T, nh, hd), self._cache_dtype)
                         for _ in range(cfg.num_layers)]
            self._vcs = [jnp.zeros((S, T, nh, hd), self._cache_dtype)
                         for _ in range(cfg.num_layers)]

        # draft KV cache: always slot-contiguous (draft rows rewind by
        # offset alone — rejected rows go stale-but-inert under the causal
        # mask, so the draft never needs page bookkeeping even when the
        # target cache is paged)
        if draft_model is not None:
            dcfg = draft_model.config
            dnh = dcfg.num_heads
            dhd = dcfg.hidden_size // dcfg.num_heads
            self._dkcs = [jnp.zeros((S, T, dnh, dhd), self._cache_dtype)
                          for _ in range(dcfg.num_layers)]
            self._dvcs = [jnp.zeros((S, T, dnh, dhd), self._cache_dtype)
                          for _ in range(dcfg.num_layers)]
        else:
            self._dkcs = self._dvcs = None

        # host-side per-slot state (tiny arrays, re-staged every step)
        self._offsets = np.zeros(S, np.int32)
        self._last_tok = np.zeros(S, np.int32)
        self._active = np.zeros(S, bool)
        self._temps = np.zeros(S, np.float32)
        self._topk = np.zeros(S, np.int32)
        self._topp = np.ones(S, np.float32)
        self._eos = np.full(S, _NO_EOS, np.int32)
        self._remaining = np.zeros(S, np.int32)
        self._seeds = np.zeros(S, np.int32)
        # per-slot speculative window rung (0 = plain decode for this slot);
        # mixed spec/non-spec slots share one verify dispatch — non-spec
        # rows run it as a 1-wide window, emitting exactly the decode token
        self._spec_k = np.zeros(S, np.int32)
        self._slot_req: List[Optional[Request]] = [None] * S

        # ONE keyed ExecutableRegistry replaces the four parallel executable
        # dicts this engine used to carry (prefill rungs, draft-prefill
        # rungs, verify (family, k) pairs, decode families). Keys are
        # ("serve.<kind>", ...distinguishers); every entry is admitted
        # PINNED — the serving working set must never be LRU-evicted under
        # a live slot (the ISSUE-18 hazard fix: with a tiny
        # FLAGS_decode_jit_cache_size the registry refuses eviction and
        # counts exec.registry.evict_refusals instead of breaking decode).
        # Decode keys stay off prompt length, max_new_tokens, and sampling
        # values — the family strings ("greedy"/"sample") and the ladder
        # rungs bound the executable count exactly as before.
        self._execs = ExecutableRegistry(
            name="serve",
            capacity=lambda: int(_flags.flag("decode_jit_cache_size")))
        # set by precompile() when the backend probe gates AOT off
        self.aot_skip_reason: Optional[str] = None

    # ------------------------------------------------------------- params
    def refresh_params(self) -> None:
        """Re-snapshot model weights (pre-cast once to the AMP compute
        dtype, the weights-in-compute-dtype inference layout legacy
        generate() establishes per call)."""
        import jax.numpy as jnp

        from ..core.dispatch import _autocast_dtype_for

        state = self.model.state_dict(include_non_persistable_buffer=True)
        params = {k: v._data for k, v in state.items()}
        mm_dtype = _autocast_dtype_for("attention", ())
        self._cache_dtype = (mm_dtype if mm_dtype is not None
                             else self.model.gpt.wte.weight._data.dtype)
        w_dtype = _autocast_dtype_for("matmul", ())

        def _cast(params):
            if w_dtype is None:
                return params
            return {k: (v.astype(w_dtype)
                        if v.ndim >= 2 and jnp.issubdtype(
                            v.dtype, jnp.floating) else v)
                    for k, v in params.items()}

        self._params = _cast(params)
        if getattr(self, "draft_model", None) is not None:
            dstate = self.draft_model.state_dict(
                include_non_persistable_buffer=True)
            self._dparams = _cast({k: v._data for k, v in dstate.items()})
        else:
            self._dparams = None

    # ------------------------------------------------------------- public
    def submit(self, prompt_ids, max_new_tokens: int = 32,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               eos_token_id=None, seed: int = 0, trace_ctx=None,
               tenant=None, speculate_k: int = 0) -> Request:
        """Enqueue a request; returns the live Request handle (tokens fill
        in as the engine runs). max_new_tokens is clamped to the engine cap
        and to the cache room left after the prompt's bucket. trace_ctx
        (fleet.TraceContext) threads a fleet request id + parent span
        through every span this request records. speculate_k > 0 opts this
        request into speculative decoding (snapped up to the engine's
        spec_ladder rung; needs a draft model)."""
        if self._draining:
            raise RuntimeError(
                "ServingEngine is draining (SIGTERM/begin_drain): admission "
                "is closed; submit to a live replica")
        if speculate_k:
            if speculate_k < 0:
                raise ValueError(
                    f"speculate_k must be >= 0, got {speculate_k}")
            if self.draft_model is None:
                raise ValueError(
                    "speculate_k > 0 needs a draft model: construct the "
                    "engine with draft_model=")
        req = Request(prompt_ids, max_new_tokens, temperature, top_k, top_p,
                      eos_token_id, seed, trace_ctx=trace_ctx, tenant=tenant,
                      speculate_k=speculate_k)
        plen = len(req.prompt_ids)
        req.bucket = bucket_for(plen, self.ladder)  # raises if oversize
        room = self.max_seq_len - req.bucket
        req.max_new_tokens = max(1, min(req.max_new_tokens,
                                        self.max_new_cap, room))
        req.submit_ts = time.perf_counter()
        with self._lock:
            req.queue_depth_at_submit = len(self._queue)
            self._queue.append(req)
        tr = _obs_tracer.get_tracer()
        if tr.enabled:
            tr.instant("serve.enqueue", **req.trace_args(
                queue_depth=req.queue_depth_at_submit))
        return req

    def step(self) -> int:
        """Admit queued requests into free slots (bucketed prefill), then
        run ONE decode step for all slots. Returns the number of live
        slots after the step (0 = fully drained)."""
        self._admit()
        if self._active.any():
            self._advance_step()
        return int(self._active.sum())

    def run(self, max_steps: Optional[int] = None) -> List[Request]:
        """Drive until queue and slots drain (or max_steps decode
        dispatches); returns the requests completed during this call."""
        done0 = len(self._completed)
        steps = 0
        while (self._queue and not self._draining) or self._active.any():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        if steps:
            self._emit_registry_rollup()
        return self._completed[done0:]

    # ---------------------------------------------------- elastic replica
    def register_replica(self, store, replica_id: str,
                         lease_s: Optional[float] = None):
        """Join the serving fleet: heartbeat a ``replica/<rid>`` lease under
        the current membership generation (distributed/membership.py) and
        arm nothing else — call install_sigterm_handler() to make SIGTERM
        drain this replica gracefully. Returns the WorkerAgent."""
        from ..distributed.membership import WorkerAgent

        agent = WorkerAgent(store, replica_id, lease_s=lease_s,
                            kind="replica")
        agent.register()
        agent.start_heartbeat()
        self._replica_agent = agent
        return agent

    def begin_drain(self, reason: str = "drain") -> None:
        """Stop admission NOW (submit() refuses, queued requests stay
        queued for a live replica); active slots keep decoding. Idempotent."""
        if self._draining:
            return
        self._draining = True
        if reason == "sigterm":
            from ..distributed import membership as _membership

            _membership.PREEMPTIONS.increase()
            mreg = _obs_metrics.active_registry()
            if mreg is not None:
                mreg.counter("elastic.preemptions").inc()

    def drain(self, timeout_s: Optional[float] = None) -> List[Request]:
        """Run active slots to completion (admission closed), deregister
        the replica lease, and return the requests completed during the
        drain. Bounded by FLAGS_elastic_drain_timeout_s — a wedged decode
        retires the replica anyway rather than hanging the SIGTERM path.
        Records ``elastic.drain_ms`` in the metrics registry."""
        self.begin_drain()
        tmo = float(timeout_s if timeout_s is not None
                    else _flags.flag("elastic_drain_timeout_s"))
        t0 = time.perf_counter()
        done0 = len(self._completed)
        while self._active.any():
            if time.perf_counter() - t0 > tmo:
                # timeout cut the drain short: whatever is still decoding
                # terminates with outcome="drained" (counted, recorded,
                # but not a completion) and its slot is reclaimed
                import numpy as np

                for slot in np.nonzero(self._active)[0]:
                    req = self._slot_req[slot]
                    self._active[slot] = False
                    self._slot_req[slot] = None
                    if self.kv_layout == "paged":
                        self._release_slot(slot)
                    if req is not None and req.done_ts is None:
                        self._finish(req, outcome="drained")
                break
            self._advance_step()
        drain_ms = (time.perf_counter() - t0) * 1000.0
        mreg = _obs_metrics.active_registry()
        if mreg is not None:
            mreg.histogram("elastic.drain_ms").observe(drain_ms)
        self._emit_registry_rollup()
        self.retire()
        return self._completed[done0:]

    def retire(self) -> None:
        """Deregister the replica lease (graceful leave). Idempotent; a
        no-op when register_replica was never called."""
        if self._replica_agent is not None:
            self._replica_agent.announce_leave(
                "sigterm" if self._draining else "leave")
            self._replica_agent = None

    def install_sigterm_handler(self) -> None:
        """SIGTERM → close admission (drain flag) and chain the previous
        handler. The actual drain runs on the driver thread: run() exits
        its loop once active slots empty (queue is no longer admitted), or
        the owner calls drain() explicitly. Signal-handler work is kept to
        a flag flip — no jax dispatch from an async context."""
        def _on_sigterm(signum, frame):
            self.begin_drain("sigterm")
            prev = self._prev_sigterm
            if callable(prev):
                prev(signum, frame)

        self._prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)

    def stats(self) -> Dict[str, Any]:
        out = {
            "steps": self._steps,
            "completed": len(self._completed),
            "queued": len(self._queue),
            "active_slots": int(self._active.sum()),
            "draining": self._draining,
            "slot_count": self.slot_count,
            "ladder": self.ladder,
            "prefill_executables": self._execs.count("serve.prefill"),
            "decode_executables": self._execs.count("serve.decode"),
            "kv_layout": self.kv_layout,
            "kv_cache_bytes": self.kv_cache_bytes(),
        }
        if self.draft_model is not None:
            out.update({
                "spec_ladder": self.spec_ladder,
                "verify_executables": self._execs.count("serve.verify"),
                "draft_prefill_executables":
                    self._execs.count("serve.dprefill"),
            })
        if self.kv_layout == "paged":
            out.update({
                "page_tokens": self.page_tokens,
                "num_pages": self.num_pages,
                "pages_in_use": self._pool.in_use,
                "pages_cached": self._pool.cached,
                "prefix": self._prefix.stats(),
            })
        return out

    # ------------------------------------------------------ paged public
    def kv_cache_bytes(self) -> int:
        """Device bytes held by the KV cache: per-slot rows (contiguous)
        or pools + scales + page tables (paged) — the denominator of
        serve_bench's concurrent-requests-per-MB datum."""
        if self.kv_layout == "paged":
            from . import kv_pages as _kvp

            return _kvp.pool_state_bytes(self._pool_state)
        return sum(int(a.size) * a.dtype.itemsize
                   for a in (*self._kcs, *self._vcs))

    def prefix_match_len(self, prompt_ids) -> int:
        """Tokens of this prompt already cached as shared pages (0 on the
        contiguous layout) — the router's prefix-locality probe; no
        refcount side effects."""
        if self.kv_layout != "paged":
            return 0
        return self._prefix.peek(
            [int(t) for t in prompt_ids])

    def flush_prefix_cache(self) -> int:
        """Evict every refcount-zero cached prefix page; returns the count
        freed. Bench hygiene: measure cold-trie TTFT against warm
        executables."""
        if self.kv_layout != "paged":
            return 0
        return self._prefix.flush()

    def occupancy(self) -> float:
        return float(self._active.sum()) / self.slot_count

    def queue_depth(self) -> int:
        return len(self._queue)

    # ---------------------------------------------------------- internals
    @property
    def _exec_stash(self):
        """label -> (jitted fn, abstract args), now owned by the registry
        (introspect_executables / analysis / mem_report read this view)."""
        return self._execs.stash_map()

    @property
    def _exec_donated(self):
        """label -> donate_argnums of the stashed fn (default_contracts
        derives each label's donation floor from these positions)."""
        return self._execs.donated_map()

    def exec_registry(self) -> ExecutableRegistry:
        """This engine's ExecutableRegistry (every prefill/decode/verify/
        draft executable, plus the AOT fast paths precompile() installs)."""
        return self._execs

    def _stash_exec(self, label: str, fn, call_args,
                    donate: tuple = (1, 2)) -> None:
        """First call per label: remember (jitted fn, abstract args) so
        introspect_executables() can AOT-lower the same program later, and
        auto-capture now when FLAGS_exec_introspect is on. ShapeDtypeStructs
        replace the arrays — no live (or donated) buffer is retained.
        donate records the fn's donate_argnums for default_contracts()."""
        self._execs.stash(label, fn, call_args, donate=donate)

    def introspect_executables(self, force: bool = False) -> Dict[str, dict]:
        """Capture XLA memory_analysis()/cost_analysis() for every prefill/
        decode executable this engine has dispatched (label -> stats dict;
        mirrored into registry gauges exec.<label>.* when metrics are
        active). Costs one extra AOT compile per uncaptured label."""
        out = {}
        for label, (fn, avals) in list(self._exec_stash.items()):
            out[label] = _obs_exec.capture_jit(label, fn, avals, force=force)
        return out

    # ---- static analysis (paddle_tpu.analysis) --------------------------
    def default_contracts(self) -> list:
        """Hygiene on every serve label (a host transfer inside prefill or
        decode would serialize the whole fleet on one Python callback) plus
        per-label KV-cache donation coverage: args 1/2 of every stashed
        signature are the caches this engine donates, so their byte size IS
        the aliasing floor."""
        import numpy as np

        from .. import analysis as _an

        cs = [_an.ProgramContract(label="serve.*", name="serve-hygiene")]
        for label, (fn, avals) in sorted(self._exec_stash.items()):
            try:
                import jax

                # contiguous: args 1/2 are the K/V caches; paged: arg 1 is
                # the whole pool state (pools + scales + page tables) — the
                # recorded donate_argnums say which, and their byte size IS
                # the aliasing floor either way
                dargs = self._exec_donated.get(label, (1, 2))
                caches = jax.tree_util.tree_leaves(
                    tuple(avals[i] for i in dargs))
                donated = sum(int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
                              for a in caches)
            except Exception:
                continue
            if donated:
                cs.append(_an.ProgramContract(
                    label=label, donated_bytes=donated,
                    name=f"{label}-cache-donation"))
        return cs

    def analyze(self, contracts=None, dump=None):
        """Run the static-analysis pass suite over every prefill/decode
        executable this engine has dispatched (see paddle_tpu.analysis).
        Dispatch-free — programs AOT-lower from the stashed signatures."""
        from .. import analysis as _an

        progs = _an.programs_from_stash(self._exec_stash)
        if contracts is None:
            contracts = self.default_contracts()
        return _an.PassManager().run(progs, contracts, dump=dump)

    def _program_device_span(self) -> int:
        """Devices a single serving executable spans. The engine keeps
        params/KV on the default device and compiles no collectives, so
        the span is 1 regardless of how many devices the process exposes;
        a future sharded serving mesh widens this (and the AOT gate with
        it)."""
        return 1

    # ---- AOT ladder precompilation (ISSUE 18) ---------------------------
    def precompile(self, families: Sequence[str] = ("greedy", "sample"),
                   force: bool = False) -> Dict[str, Any]:
        """AOT-compile the full serving ladder before the first request:
        every (prefill rung x sampling family x spec rung) executable is
        lowered at its exact dispatch signature and compiled via
        ``jit(...).lower().compile()``, then installed as the registry
        entry's dispatch fast path. With FLAGS_compile_cache_dir pointing
        at an AOT bundle (tools/aot_bundle.py) every compile deserializes
        WARM — a fresh replica joins the fleet with zero cold compiles.

        Gated by analysis.backend.aot_serving_reason(): cache-served
        multi-device executables are nondeterministic on this jax's CPU, so
        a multi-device CPU serving mesh skips (reason recorded in
        ``aot_skip_reason`` and the returned dict) unless ``force``. The
        probe keys on the device span of the PROGRAMS this engine compiles
        — one device until serving grows a mesh — not the process device
        count: an 8-virtual-device drill process still precompiles its
        single-device replicas.

        Returns {"precompiled", "skipped", "cold", "warm", "wall_ms"}."""
        import jax.numpy as jnp
        import numpy as np

        from ..analysis.backend import aot_serving_reason
        from ..core import monitor

        reason = None if force else aot_serving_reason(
            device_count=self._program_device_span())
        if reason is not None:
            self.aot_skip_reason = reason
            monitor.stat("serving.aot_skipped").increase()
            return {"precompiled": 0, "skipped": reason,
                    "cold": 0, "warm": 0, "wall_ms": 0.0}
        self.aot_skip_reason = None
        paged = self.kv_layout == "paged"
        S = self.slot_count

        def slot_vecs():
            return (jnp.asarray(self._offsets), jnp.asarray(self._last_tok),
                    jnp.asarray(self._active))

        def sampling_vecs():
            return (jnp.asarray(self._temps), jnp.asarray(self._topk),
                    jnp.asarray(self._topp), jnp.asarray(self._eos),
                    jnp.asarray(self._remaining), jnp.asarray(self._seeds))

        def pool_state():
            return dict(self._pool_state, tables=jnp.asarray(self._tables))

        plan = []  # (key, build, label, donate, call_args)
        for bucket in self.ladder:
            padded = jnp.asarray(np.zeros((1, bucket), np.int64))
            if paged:
                args = (self._params, pool_state(), padded, jnp.int32(0),
                        jnp.int32(0), jnp.int32(0), jnp.float32(0.0),
                        jnp.int32(0), jnp.float32(1.0), jnp.int32(0))
                plan.append((("serve.prefill", bucket),
                             (lambda b=bucket:
                              self._build_prefill_paged(b)),
                             f"serve.prefill_b{bucket}", (1,), args))
            else:
                args = (self._params, self._kcs, self._vcs, padded,
                        jnp.int32(0), jnp.int32(0), jnp.float32(0.0),
                        jnp.int32(0), jnp.float32(1.0), jnp.int32(0))
                plan.append((("serve.prefill", bucket),
                             (lambda b=bucket: self._build_prefill(b)),
                             f"serve.prefill_b{bucket}", (1, 2), args))
            if self.draft_model is not None:
                dargs = (self._dparams, self._dkcs, self._dvcs, padded,
                         jnp.int32(0))
                plan.append((("serve.dprefill", bucket),
                             (lambda b=bucket:
                              self._build_draft_prefill(b)),
                             f"serve.dprefill_b{bucket}", (1, 2), dargs))
        for family in families:
            if paged:
                args = (self._params, pool_state(), *slot_vecs(),
                        jnp.asarray(self._replay), *sampling_vecs())
                plan.append((("serve.decode", family),
                             (lambda f=family:
                              self._build_decode_paged(f)),
                             f"serve.decode_{family}", (1,), args))
            else:
                args = (self._params, self._kcs, self._vcs, *slot_vecs(),
                        *sampling_vecs())
                plan.append((("serve.decode", family),
                             (lambda f=family: self._build_decode(f)),
                             f"serve.decode_{family}", (1, 2), args))
            if self.draft_model is None:
                continue
            for k in self.spec_ladder:
                n_draft = jnp.asarray(np.zeros(S, np.int32))
                if paged:
                    args = (self._params, self._dparams, pool_state(),
                            self._dkcs, self._dvcs, *slot_vecs(),
                            jnp.asarray(self._replay), n_draft,
                            *sampling_vecs())
                    donate = (2, 3, 4)
                    build = (lambda f=family, kk=k:
                             self._build_verify_paged(f, kk))
                else:
                    args = (self._params, self._dparams, self._kcs,
                            self._vcs, self._dkcs, self._dvcs,
                            *slot_vecs(), n_draft, *sampling_vecs())
                    donate = (2, 3, 4, 5)
                    build = (lambda f=family, kk=k:
                             self._build_verify(f, kk))
                plan.append((("serve.verify", family, k), build,
                             f"serve.verify_{family}_k{k}", donate, args))

        from ..core import compile_cache as _compile_cache

        cold0 = monitor.stat("engine.compile_cold").get()
        warm0 = monitor.stat("engine.compile_warm").get()
        t0 = time.perf_counter()
        n = 0
        for key, build, label, donate, call_args in plan:
            entry = self._execs.get_or_build(key, build, label=label,
                                             donate=donate, pin=True)
            if entry.aot is None or force:
                self._execs.precompile(entry, call_args)
                n += 1
            self._stash_exec(label, entry.fn, call_args, donate=donate)
        wall_ms = (time.perf_counter() - t0) * 1000.0
        monitor.stat("serving.aot_precompiles").increase(n)
        return {"precompiled": n, "skipped": None,
                "cold": monitor.stat("engine.compile_cold").get() - cold0,
                "warm": monitor.stat("engine.compile_warm").get() - warm0,
                "wall_ms": wall_ms,
                "cache_dir": _compile_cache.cache_dir()}

    def _emit_registry_rollup(self) -> None:
        """Cumulative exec-registry rollup record for the trace sink /
        flight recorder (trace_summary's per-label registry table)."""
        fr = _obs_flight.get()
        if self.sink is None and fr is None:
            return
        rec = dict(self._execs.rollup(), event="exec_registry",
                   ts=time.time())
        if self.sink is not None:
            self.sink.write(rec)
        if fr is not None:
            fr.record(rec)

    def _head_traced(self, params, h_arr):
        """last-position hidden -> logits with weights from traced params."""
        from ..core.autograd import no_grad
        from ..core.tensor import Tensor
        from ..jit import _swapped_state, _tracing

        with _swapped_state(self.model, params), _tracing(), no_grad():
            return self.model._head_logits(Tensor(h_arr))._data

    def _draft_head_traced(self, dparams, h_arr):
        """Draft-model hidden -> logits with weights from traced params."""
        from ..core.autograd import no_grad
        from ..core.tensor import Tensor
        from ..jit import _swapped_state, _tracing

        with _swapped_state(self.draft_model, dparams), _tracing(), \
                no_grad():
            return self.draft_model._head_logits(Tensor(h_arr))._data

    # ---- prefill -------------------------------------------------------
    def _build_prefill(self, bucket: int):
        import jax
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        from ..jit import functional_call
        from .sampling import request_key, sample_tokens

        cfg = self.model.config
        nh = cfg.num_heads
        hd = cfg.hidden_size // cfg.num_heads
        cache_dtype = self._cache_dtype
        gpt = self.model.gpt

        def prefill(params, kcs, vcs, ids, plen, slot, temp, top_k, top_p,
                    seed):
            gpt_params = {k[len("gpt."):]: v for k, v in params.items()
                          if k.startswith("gpt.")}
            # fresh request-local cache sized to the rung; causal masking
            # makes the right-pad inert (queries past plen are discarded)
            caches = [(Tensor(jnp.zeros((1, bucket, nh, hd), cache_dtype)),
                       Tensor(jnp.zeros((1, bucket, nh, hd), cache_dtype)),
                       Tensor(jnp.int32(0))) for _ in range(cfg.num_layers)]
            h, caches = functional_call(gpt, gpt_params, Tensor(ids),
                                        caches=caches)
            last_h = jax.lax.dynamic_index_in_dim(h._data, plen - 1, 1,
                                                  keepdims=False)
            logits = self._head_traced(params, last_h)       # [1, V]
            key = request_key(seed, plen)  # first token sits at position plen
            tok = sample_tokens(logits, key[None], temp[None], top_k[None],
                                top_p[None])[0]
            # scatter this request's K/V into its slot row of the big cache
            new_kcs, new_vcs = [], []
            start = (slot, jnp.int32(0), jnp.int32(0), jnp.int32(0))
            for big_k, big_v, layer in zip(kcs, vcs, caches):
                new_kcs.append(jax.lax.dynamic_update_slice(
                    big_k, layer[0]._data.astype(big_k.dtype), start))
                new_vcs.append(jax.lax.dynamic_update_slice(
                    big_v, layer[1]._data.astype(big_v.dtype), start))
            return new_kcs, new_vcs, tok

        return jax.jit(prefill, donate_argnums=(1, 2))

    def _build_prefill_paged(self, bucket: int):
        """Paged tail-prefill, one executable per TAIL rung: the unshared
        suffix of the prompt (the whole prompt on a trie miss) runs with a
        traced base offset and writes K/V through this slot's page-table
        row. base/tail_len/slot/sampling/seed are all traced, so prefix
        hits of any depth share the same rung executables."""
        import jax
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        from ..jit import functional_call
        from . import kv_pages as _kvp
        from .sampling import request_key, sample_tokens

        gpt = self.model.gpt
        pt = self.page_tokens
        quant = self._kv_quantized
        compute_dtype = self._cache_dtype

        def prefill(params, state, ids, tail_len, base, slot, temp, top_k,
                    top_p, seed):
            gpt_params = {k[len("gpt."):]: v for k, v in params.items()
                          if k.startswith("gpt.")}
            table_row = jax.lax.dynamic_slice_in_dim(
                state["tables"], slot, 1, 0)                 # [1, max_pages]
            # pad positions past the tail redirect to the scratch page:
            # their page-table entries may be unallocated (the zero page
            # must never be written)
            wmask = (jnp.arange(bucket, dtype=jnp.int32)[None, :]
                     < tail_len)                             # [1, bucket]
            caches = _kvp.layer_views(state, table_row, base[None], wmask,
                                      pt, compute_dtype)
            h, caches = functional_call(gpt, gpt_params, Tensor(ids),
                                        caches=caches)
            last_h = jax.lax.dynamic_index_in_dim(h._data, tail_len - 1, 1,
                                                  keepdims=False)
            logits = self._head_traced(params, last_h)       # [1, V]
            key = request_key(seed, base + tail_len)  # abs first-token pos
            tok = sample_tokens(logits, key[None], temp[None], top_k[None],
                                top_p[None])[0]
            new_state = {
                "k": [c.k_pool for c in caches],
                "v": [c.v_pool for c in caches],
                "ks": [c.k_scale for c in caches] if quant else [],
                "vs": [c.v_scale for c in caches] if quant else [],
                "tables": state["tables"],
            }
            return new_state, tok

        return jax.jit(prefill, donate_argnums=(1,))

    # ---- speculative decoding: draft prefill ---------------------------
    def _build_draft_prefill(self, bucket: int):
        """Draft-model prompt prefill, one executable per prompt rung.
        Writes the draft K/V for positions 0..plen-1 into the slot's row
        of the (always contiguous) draft cache — no sampling, no logits:
        the draft's first proposal comes out of the verify program's scan.
        Right-pad junk past plen is inert: every padded position is
        rewritten by a later draft scan step before any query attends it,
        the same argument the target prefill pad relies on."""
        import jax
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        from ..jit import functional_call

        dcfg = self.draft_model.config
        nh = dcfg.num_heads
        hd = dcfg.hidden_size // dcfg.num_heads
        cache_dtype = self._cache_dtype
        dgpt = self.draft_model.gpt

        def prefill(dparams, dkcs, dvcs, ids, slot):
            dgpt_params = {k[len("gpt."):]: v for k, v in dparams.items()
                           if k.startswith("gpt.")}
            caches = [(Tensor(jnp.zeros((1, bucket, nh, hd), cache_dtype)),
                       Tensor(jnp.zeros((1, bucket, nh, hd), cache_dtype)),
                       Tensor(jnp.int32(0))) for _ in range(dcfg.num_layers)]
            _h, caches = functional_call(dgpt, dgpt_params, Tensor(ids),
                                         caches=caches)
            new_kcs, new_vcs = [], []
            start = (slot, jnp.int32(0), jnp.int32(0), jnp.int32(0))
            for big_k, big_v, layer in zip(dkcs, dvcs, caches):
                new_kcs.append(jax.lax.dynamic_update_slice(
                    big_k, layer[0]._data.astype(big_k.dtype), start))
                new_vcs.append(jax.lax.dynamic_update_slice(
                    big_v, layer[1]._data.astype(big_v.dtype), start))
            return new_kcs, new_vcs

        return jax.jit(prefill, donate_argnums=(1, 2))

    def _seat_spec(self, req: Request, slot: int) -> None:
        """Per-seat speculative setup, called at every seating site (slot
        reuse must clear a predecessor's rung). Spec requests snap their
        speculate_k UP to the nearest ladder rung and get a draft-model
        prompt prefill; for paged full-hit replay seats the draft still
        prefills the whole prompt (the draft cache is contiguous and has
        no prefix sharing — position plen-1's verify-scan rewrite is a
        same-value overwrite)."""
        import jax.numpy as jnp
        import numpy as np

        from ..core import monitor

        if req.speculate_k <= 0 or self.draft_model is None:
            self._spec_k[slot] = 0
            return
        rung = self.spec_ladder[-1]
        for r in self.spec_ladder:
            if r >= req.speculate_k:
                rung = r
                break
        self._spec_k[slot] = rung
        bucket = req.bucket
        plen = len(req.prompt_ids)
        entry = self._execs.get_or_build(
            ("serve.dprefill", bucket),
            lambda: self._build_draft_prefill(bucket),
            label=f"serve.dprefill_b{bucket}", donate=(1, 2), pin=True)
        padded = np.zeros((1, bucket), np.int64)
        padded[0, :plen] = req.prompt_ids
        call_args = (self._dparams, self._dkcs, self._dvcs,
                     jnp.asarray(padded), jnp.int32(slot))
        self._stash_exec(f"serve.dprefill_b{bucket}", entry.fn, call_args)
        monitor.stat("serving.draft_prefill_dispatches").increase()
        p0 = self._execs.persistent_before(entry)
        t0 = time.perf_counter()
        self._dkcs, self._dvcs = entry(*call_args)
        self._execs.note_compiles(
            entry, wall_s=time.perf_counter() - t0, persistent_before=p0,
            counter="serving.draft_prefill_compiles")

    def _admit(self) -> None:
        import jax.numpy as jnp
        import numpy as np

        if self._draining:
            return
        while True:
            with self._lock:
                if not self._queue:
                    return
                free = [i for i in range(self.slot_count)
                        if not self._active[i] and self._slot_req[i] is None]
                if not free:
                    return
                req = self._queue.popleft()
            slot = free[0]
            if self.kv_layout == "paged":
                if not self._admit_paged(req, slot):
                    return
                continue
            bucket = req.bucket
            plen = len(req.prompt_ids)
            req.admit_ts = time.perf_counter()    # queue wait ends here
            entry = self._execs.get_or_build(
                ("serve.prefill", bucket),
                lambda: self._build_prefill(bucket),
                label=f"serve.prefill_b{bucket}", donate=(1, 2), pin=True)
            padded = np.zeros((1, bucket), np.int64)
            padded[0, :plen] = req.prompt_ids
            call_args = (self._params, self._kcs, self._vcs,
                         jnp.asarray(padded), jnp.int32(plen),
                         jnp.int32(slot), jnp.float32(req.temperature),
                         jnp.int32(req.top_k), jnp.float32(req.top_p),
                         jnp.int32(req.seed))
            self._stash_exec(f"serve.prefill_b{bucket}", entry.fn, call_args)
            from ..core import monitor

            monitor.stat("serving.prefill_dispatches").increase()
            p0 = self._execs.persistent_before(entry)
            t0 = time.perf_counter()
            try:
                self._kcs, self._vcs, tok = entry(*call_args)
                self._execs.note_compiles(
                    entry, wall_s=time.perf_counter() - t0,
                    persistent_before=p0,
                    counter="serving.prefill_compiles")
                first = int(tok)                  # device sync = first token
            except Exception as e:
                fr = _obs_flight.get()
                if fr is not None:
                    fr.dump("serve_prefill_exception",
                            {"request": req.id, "bucket": bucket,
                             "error": repr(e)})
                self._finish(req, outcome="error")
                raise
            req.first_token_ts = time.perf_counter()
            tr = _obs_tracer.get_tracer()
            if tr.enabled:
                tr.record_complete("serve.queue_wait", req.submit_ts,
                                   req.admit_ts, req.trace_args())
                tr.record_complete("serve.prefill", req.admit_ts,
                                   req.first_token_ts,
                                   req.trace_args(bucket=bucket, slot=slot))
            mreg = _obs_metrics.active_registry()
            if mreg is not None:
                mreg.histogram("serve.queue_wait_ms").observe(
                    req.queue_wait_s * 1e3)
                mreg.histogram("serve.prefill_ms").observe(
                    (req.first_token_ts - req.admit_ts) * 1e3)
            req.slot = slot
            req.tokens.append(first)
            self._count_tokens(1)
            eos = req.eos_token_id if req.eos_token_id is not None else _NO_EOS
            if (eos != _NO_EOS and first == eos) or req.max_new_tokens <= 1:
                req.finish_reason = ("eos" if eos != _NO_EOS and first == eos
                                     else "length")
                self._finish(req)
                continue
            self._offsets[slot] = plen
            self._last_tok[slot] = first
            self._active[slot] = True
            self._temps[slot] = req.temperature
            self._topk[slot] = req.top_k
            self._topp[slot] = req.top_p
            self._eos[slot] = eos
            self._remaining[slot] = req.max_new_tokens - 1
            self._seeds[slot] = req.seed
            self._slot_req[slot] = req
            self._seat_spec(req, slot)

    # ---- paged admission ----------------------------------------------
    def _pages_reserved_inflight(self) -> int:
        """Worst-case pages still to be allocated by active slots (each
        slot's final offset is offsets + remaining; shared and own pages
        already in its table row don't count)."""
        import numpy as np

        pt = self.page_tokens
        total = 0
        for i in np.nonzero(self._active)[0]:
            end = min(int(self._offsets[i]) + int(self._remaining[i]),
                      self.max_seq_len)
            need = -(-end // pt) - int((self._tables[i] != 0).sum())
            total += max(0, need)
        return total

    def _release_slot(self, slot: int) -> None:
        """Drop the slot's page references (shared pages decref; own pages
        free or park for prefix reuse) and reset its table row to the zero
        page."""
        for p in self._slot_pages[slot]:
            self._prefix.release(int(p))
        self._slot_pages[slot] = []
        self._tables[slot, :] = 0
        self._replay[slot] = False

    def _admit_paged(self, req: Request, slot: int) -> bool:
        """Seat a request on the paged cache. Three admission shapes:

        - trie miss: allocate prompt pages, prefill the whole prompt
          (base 0) — the contiguous flow, just scattered through pages.
        - partial hit: copy the matched pages into the table row and
          prefill only the unshared tail rung at base = matched tokens.
        - full hit (prompt length is page-aligned and fully cached): NO
          prefill dispatch at all — the slot seats directly into decode at
          offset plen-1 feeding prompt[-1], with a per-row replay flag
          that redirects that first step's (already-cached) K/V write to
          the scratch page. The first token then falls out of the decode
          chunk, sampled with the same request_key(seed, plen) the prefill
          program would have used.

        Returns False (request requeued) when the pool can't cover this
        request's worst case plus in-flight reservations — admission
        retries once decode retires a slot and frees pages."""
        import jax.numpy as jnp
        import numpy as np

        from ..core import monitor
        from . import kv_pages as _kvp

        pt = self.page_tokens
        plen = len(req.prompt_ids)
        req.admit_ts = time.perf_counter()    # queue wait ends here
        shared = self._prefix.match(req.prompt_ids)
        k_shared = len(shared)
        monitor.stat("serving.prefix_lookups").increase()
        # reservation check: this request's unshared worst case on top of
        # what active slots may still allocate must fit free + evictable
        need_new = -(-(plen + req.max_new_tokens) // pt) - k_shared
        avail = self._pool.available
        if avail < self._pages_reserved_inflight() + need_new:
            for p in shared:
                self._prefix.release(int(p))
            if not self._active.any():
                raise _kvp.PoolExhausted(
                    f"pool of {self.num_pages} pages cannot fit one request "
                    f"needing {need_new} fresh pages ({avail} available) — "
                    "raise kv_num_pages or lower max_new_cap")
            req.admit_ts = None
            with self._lock:
                self._queue.appendleft(req)
            return False
        if shared:
            monitor.stat("serving.prefix_hits").increase()
            req.prefix_hit = True
            req.shared_tokens = k_shared * pt
        self._tables[slot, :] = 0
        self._tables[slot, :k_shared] = shared
        self._slot_pages[slot] = [int(p) for p in shared]
        eos = req.eos_token_id if req.eos_token_id is not None else _NO_EOS
        tr = _obs_tracer.get_tracer()
        mreg = _obs_metrics.active_registry()
        if tr.enabled:
            tr.record_complete("serve.queue_wait", req.submit_ts,
                               req.admit_ts, req.trace_args())
        if mreg is not None:
            mreg.histogram("serve.queue_wait_ms").observe(
                req.queue_wait_s * 1e3)

        if k_shared * pt >= plen:
            # full hit: replay seat, zero prefill dispatches
            monitor.stat("serving.prefill_skips").increase()
            req.tail_bucket = 0
            req.slot = slot
            if tr.enabled:
                tr.instant("serve.prefix_replay", **req.trace_args(
                    slot=slot, shared_tokens=req.shared_tokens))
            self._offsets[slot] = plen - 1
            self._last_tok[slot] = int(req.prompt_ids[-1])
            self._active[slot] = True
            self._replay[slot] = True
            self._temps[slot] = req.temperature
            self._topk[slot] = req.top_k
            self._topp[slot] = req.top_p
            self._eos[slot] = eos
            self._remaining[slot] = req.max_new_tokens
            self._seeds[slot] = req.seed
            self._slot_req[slot] = req
            self._seat_spec(req, slot)
            return True

        # partial hit / miss: allocate the prompt's unshared pages and
        # prefill the tail rung at base = shared tokens
        base = k_shared * pt
        tail = plen - base
        tbucket = bucket_for(tail, self.ladder)
        req.tail_bucket = tbucket
        npages_prompt = -(-plen // pt)
        if not self._prefix.ensure_free(npages_prompt - k_shared):
            raise _kvp.PoolExhausted(     # reservation check above makes
                "page reservation accounting violated")  # this unreachable
        for pi in range(k_shared, npages_prompt):
            page = self._pool.alloc()
            self._tables[slot, pi] = page
            self._slot_pages[slot].append(page)
        entry = self._execs.get_or_build(
            ("serve.prefill", tbucket),
            lambda: self._build_prefill_paged(tbucket),
            label=f"serve.prefill_b{tbucket}", donate=(1,), pin=True)
        padded = np.zeros((1, tbucket), np.int64)
        padded[0, :tail] = req.prompt_ids[base:]
        state = dict(self._pool_state, tables=jnp.asarray(self._tables))
        call_args = (self._params, state, jnp.asarray(padded),
                     jnp.int32(tail), jnp.int32(base), jnp.int32(slot),
                     jnp.float32(req.temperature), jnp.int32(req.top_k),
                     jnp.float32(req.top_p), jnp.int32(req.seed))
        self._stash_exec(f"serve.prefill_b{tbucket}", entry.fn, call_args,
                         donate=(1,))
        monitor.stat("serving.prefill_dispatches").increase()
        p0 = self._execs.persistent_before(entry)
        t0 = time.perf_counter()
        try:
            new_state, tok = entry(*call_args)
            self._execs.note_compiles(
                entry, wall_s=time.perf_counter() - t0, persistent_before=p0,
                counter="serving.prefill_compiles")
            first = int(tok)                  # device sync = first token
        except Exception as e:
            fr = _obs_flight.get()
            if fr is not None:
                fr.dump("serve_prefill_exception",
                        {"request": req.id, "bucket": tbucket,
                         "base": base, "error": repr(e)})
            self._finish(req, outcome="error")
            raise
        self._pool_state = new_state
        req.first_token_ts = time.perf_counter()
        if tr.enabled:
            tr.record_complete("serve.prefill", req.admit_ts,
                               req.first_token_ts,
                               req.trace_args(bucket=tbucket, base=base,
                                              slot=slot))
        if mreg is not None:
            mreg.histogram("serve.prefill_ms").observe(
                (req.first_token_ts - req.admit_ts) * 1e3)
        # publish this prompt's fully-written pages for future sharers
        full_pages = plen // pt
        if full_pages > k_shared:
            self._prefix.insert(
                req.prompt_ids[:full_pages * pt],
                [int(p) for p in self._tables[slot, :full_pages]])
        req.slot = slot
        req.tokens.append(first)
        self._count_tokens(1)
        if (eos != _NO_EOS and first == eos) or req.max_new_tokens <= 1:
            req.finish_reason = ("eos" if eos != _NO_EOS and first == eos
                                 else "length")
            self._release_slot(slot)
            self._finish(req)
            return True
        self._offsets[slot] = plen
        self._last_tok[slot] = first
        self._active[slot] = True
        self._replay[slot] = False
        self._temps[slot] = req.temperature
        self._topk[slot] = req.top_k
        self._topp[slot] = req.top_p
        self._eos[slot] = eos
        self._remaining[slot] = req.max_new_tokens - 1
        self._seeds[slot] = req.seed
        self._slot_req[slot] = req
        self._seat_spec(req, slot)
        return True

    # ---- decode --------------------------------------------------------
    def _build_decode(self, family: str):
        import jax
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        from ..jit import functional_call
        from .sampling import request_key, sample_tokens

        gpt = self.model.gpt
        T = self.max_seq_len
        n_inner = self.steps_per_dispatch
        greedy_only = family == "greedy"

        def step_chunk(params, kcs, vcs, off, tok, active, temps, top_k,
                       top_p, eos, remaining, seeds):
            gpt_params = {k[len("gpt."):]: v for k, v in params.items()
                          if k.startswith("gpt.")}

            def one(carry, _):
                kcs, vcs, off, tok, active, remaining = carry
                # idle slots keep writing their (ignored) tip row; clamp so
                # a full slot can never index past the cache
                off_m = jnp.minimum(off, jnp.int32(T - 1))
                caches = [(Tensor(kc), Tensor(vc), Tensor(off_m))
                          for kc, vc in zip(kcs, vcs)]
                h, caches = functional_call(
                    gpt, gpt_params, Tensor(tok[:, None].astype(jnp.int64)),
                    caches=caches)
                logits = self._head_traced(params, h._data[:, 0])  # [S, V]
                act = active.astype(jnp.int32)
                new_off = off + act         # the sampled token's position
                if greedy_only:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                else:
                    keys = jax.vmap(request_key)(seeds, new_off)
                    nxt = sample_tokens(logits, keys, temps, top_k, top_p)
                nxt = jnp.where(active, nxt, tok)
                new_remaining = remaining - act
                hit_eos = active & (eos != _NO_EOS) & (nxt == eos)
                new_active = (active & ~hit_eos & (new_remaining > 0)
                              & (new_off < T))
                new_kcs = [c[0]._data for c in caches]
                new_vcs = [c[1]._data for c in caches]
                return ((new_kcs, new_vcs, new_off, nxt, new_active,
                         new_remaining), (nxt, active, hit_eos))

            carry = (kcs, vcs, off, tok, active, remaining)
            (kcs, vcs, off, tok, active, remaining), (toks, was_active,
                                                      hits) = jax.lax.scan(
                one, carry, None, length=n_inner)
            # toks/was_active/hits: [n_inner, S]
            return (kcs, vcs, off, tok, active, remaining, toks, was_active,
                    hits)

        return jax.jit(step_chunk, donate_argnums=(1, 2))

    def _build_decode_paged(self, family: str):
        """Paged decode chunk: same continuous-batching scan as the dense
        decode, but K/V flows through the donated pool state (per-layer
        pools + scales + the page table). Extra per-row ``replay`` flag:
        a full-prefix-hit slot's first step re-derives a position whose
        K/V already sits in a shared page, so its write is redirected to
        the scratch page; the flag clears after the row's first active
        step and the row behaves like any other from then on."""
        import jax
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        from ..jit import functional_call
        from . import kv_pages as _kvp
        from .sampling import request_key, sample_tokens

        gpt = self.model.gpt
        T = self.max_seq_len
        t_eff = self._t_eff
        n_inner = self.steps_per_dispatch
        greedy_only = family == "greedy"
        pt = self.page_tokens
        quant = self._kv_quantized
        compute_dtype = self._cache_dtype

        def step_chunk(params, state, off, tok, active, replay, temps,
                       top_k, top_p, eos, remaining, seeds):
            gpt_params = {k[len("gpt."):]: v for k, v in params.items()
                          if k.startswith("gpt.")}
            tables = state["tables"]

            def one(carry, _):
                ks, vs, kss, vss, off, tok, active, replay, remaining = carry
                off_m = jnp.clip(off, 0, jnp.int32(t_eff - 1))
                st = {"k": ks, "v": vs, "ks": kss, "vs": vss}
                # idle rows and replaying rows write to the scratch page
                caches = _kvp.layer_views(st, tables, off_m,
                                          active & ~replay, pt,
                                          compute_dtype)
                h, caches = functional_call(
                    gpt, gpt_params, Tensor(tok[:, None].astype(jnp.int64)),
                    caches=caches)
                logits = self._head_traced(params, h._data[:, 0])  # [S, V]
                act = active.astype(jnp.int32)
                new_off = off + act         # the sampled token's position
                if greedy_only:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                else:
                    keys = jax.vmap(request_key)(seeds, new_off)
                    nxt = sample_tokens(logits, keys, temps, top_k, top_p)
                nxt = jnp.where(active, nxt, tok)
                new_remaining = remaining - act
                hit_eos = active & (eos != _NO_EOS) & (nxt == eos)
                new_active = (active & ~hit_eos & (new_remaining > 0)
                              & (new_off < T))
                new_replay = replay & ~active
                new_ks = [c.k_pool for c in caches]
                new_vs = [c.v_pool for c in caches]
                new_kss = [c.k_scale for c in caches] if quant else []
                new_vss = [c.v_scale for c in caches] if quant else []
                return ((new_ks, new_vs, new_kss, new_vss, new_off, nxt,
                         new_active, new_replay, new_remaining),
                        (nxt, active, hit_eos))

            carry = (state["k"], state["v"], state["ks"], state["vs"], off,
                     tok, active, replay, remaining)
            ((ks, vs, kss, vss, off, tok, active, replay, remaining),
             (toks, was_active, hits)) = jax.lax.scan(
                one, carry, None, length=n_inner)
            new_state = {"k": ks, "v": vs, "ks": kss, "vs": vss,
                         "tables": tables}
            return (new_state, off, tok, active, replay, remaining, toks,
                    was_active, hits)

        return jax.jit(step_chunk, donate_argnums=(1,))

    def _prealloc_decode_pages(self) -> None:
        """Host-side, between dispatches: make sure every active slot's
        table row covers the positions the next chunk may write (the
        table is static within a dispatch). Evicts LRU cached prefixes
        under pressure; admission reservations guarantee success."""
        import numpy as np

        from . import kv_pages as _kvp

        pt = self.page_tokens
        for i in np.nonzero(self._active)[0]:
            first = int(self._offsets[i]) + (1 if self._replay[i] else 0)
            last = min(int(self._offsets[i]) + self.steps_per_dispatch,
                       self.max_seq_len) - 1
            for pi in range(first // pt, last // pt + 1):
                if self._tables[i, pi] == 0:
                    if not self._prefix.ensure_free(1):
                        raise _kvp.PoolExhausted(
                            f"decode needs a page for slot {i} and none is "
                            "free or evictable (reservation accounting "
                            "violated)")
                    page = self._pool.alloc()
                    self._tables[i, pi] = page
                    self._slot_pages[i].append(page)

    # ---- speculative decoding: verify ----------------------------------
    def _spec_commit(self, jax, jnp, logits, dlogits_sk, props, off, tok,
                     active, n_draft, temps, top_k, top_p, eos, remaining,
                     seeds, k, greedy_only):
        """Acceptance + commit math shared by both verify layouts (runs
        inside the jitted verify program).

        logits [S, k+1, V] are the target's window scores: column j was
        computed from the token at position off+j, so it predicts the
        token at position off+j+1. Greedy: accept the longest prefix where
        the draft agrees with the target argmax; the emitted row IS the
        target argmax row, so greedy speculative output is bit-identical
        to sequential greedy decode. Sampled: standard leftover-
        distribution speculative sampling — accept d_i when
        u_i < p_t(d_i)/p_d(d_i) (u_i from the ACCEPT_SALT stream), resample
        a rejection column from normalize(max(p_t - p_d, 0)). The bonus /
        rejection column draws with the PLAIN request_key stream, so a
        fully-accepted window's bonus token — and every n_draft==0 row —
        emits the exact token a sequential decode step would have."""
        from .sampling import (ACCEPT_SALT, filtered_probs, request_key,
                               residual_sample, sample_tokens, spec_key)

        S = logits.shape[0]
        T = self.max_seq_len
        cols = jnp.arange(k + 1, dtype=jnp.int32)[None, :]       # [1, k+1]
        colk = jnp.arange(k, dtype=jnp.int32)[None, :]           # [1, k]
        in_window = colk < n_draft[:, None]                      # [S, k]
        tgt_greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if greedy_only:
            accept = (tgt_greedy[:, :k] == props) & in_window
            a = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                        axis=1)                                  # [S]
            emit = tgt_greedy
        else:
            V = logits.shape[-1]
            t_rep = jnp.repeat(temps, k)
            k_rep = jnp.repeat(top_k, k)
            p_rep = jnp.repeat(top_p, k)
            p_t = filtered_probs(logits[:, :k].reshape((S * k, V)),
                                 t_rep, k_rep, p_rep).reshape((S, k, V))
            p_d = filtered_probs(dlogits_sk.reshape((S * k, V)),
                                 t_rep, k_rep, p_rep).reshape((S, k, V))
            pt_d = jnp.take_along_axis(p_t, props[..., None],
                                       axis=-1)[..., 0]          # [S, k]
            pd_d = jnp.take_along_axis(p_d, props[..., None],
                                       axis=-1)[..., 0]
            positions = (off[:, None] + 1 + colk).reshape(-1)    # [S*k]
            akeys = jax.vmap(spec_key, in_axes=(0, 0, None))(
                jnp.repeat(seeds, k), positions, ACCEPT_SALT)
            u = jax.vmap(jax.random.uniform)(akeys).reshape((S, k))
            ratio = pt_d / jnp.maximum(pd_d, 1e-38)
            exact = tgt_greedy[:, :k] == props
            accept = (jnp.where(temps[:, None] == 0.0, exact, u < ratio)
                      & in_window)
            a = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                        axis=1)
            # column a's replacement token: greedy rows take the target
            # argmax; full-accept (and n_draft==0) rows sample the plain
            # per-position stream — the exact sequential draw — and
            # rejections take the residual distribution
            greedy_fix = jnp.take_along_axis(tgt_greedy, a[:, None],
                                             axis=1)[:, 0]
            La = jnp.take_along_axis(logits, a[:, None, None],
                                     axis=1)[:, 0]               # [S, V]
            rkeys = jax.vmap(request_key)(seeds, off + 1 + a)
            bonus_tok = sample_tokens(La, rkeys, temps, top_k, top_p)
            a_k = jnp.clip(a, 0, k - 1)
            pt_a = jnp.take_along_axis(p_t, a_k[:, None, None],
                                       axis=1)[:, 0]
            pd_a = jnp.take_along_axis(p_d, a_k[:, None, None],
                                       axis=1)[:, 0]
            resampled = residual_sample(rkeys, pt_a, pd_a)
            final_tok = jnp.where(
                temps == 0.0, greedy_fix,
                jnp.where(a >= n_draft, bonus_tok, resampled))
            props_pad = jnp.concatenate([props, props[:, -1:]], axis=1)
            emit = jnp.where(cols < a[:, None], props_pad,
                             final_tok[:, None])
        # commit: cut at the first emitted EOS, then the token budget —
        # the same order a sequential decode would stop in
        m_raw = a + 1
        is_eos = ((eos[:, None] != _NO_EOS) & (emit == eos[:, None])
                  & (cols < m_raw[:, None]))
        any_eos = jnp.any(is_eos, axis=1)
        m = jnp.where(any_eos, jnp.argmax(is_eos, axis=1) + 1, m_raw)
        m = jnp.minimum(m, remaining) * active.astype(jnp.int32)
        new_off = off + m
        last_emit = jnp.take_along_axis(
            emit, jnp.clip(m - 1, 0, k)[:, None], axis=1)[:, 0]
        new_tok = jnp.where(active, last_emit, tok)
        new_remaining = remaining - m
        hit_eos = active & (eos != _NO_EOS) & (new_tok == eos)
        new_active = (active & ~hit_eos & (new_remaining > 0)
                      & (new_off < T))
        return (new_off, new_tok, new_active, new_remaining, emit, m, a,
                hit_eos)

    def _build_verify(self, family: str, k: int):
        """Contiguous-layout verify program, one executable per (sampling
        family, ladder rung k): a draft scan proposes k tokens, then
        ONE [S, k+1] window forward through the target scores every
        proposal plus the bonus position, and the commit math accepts the
        longest agreeing prefix. Rejected rows need no cache surgery —
        the offset rewind leaves them as inert stale rows (causal masking
        hides them, and they are rewritten before any query attends them,
        the same argument decode's idle-row tip writes rely on)."""
        import jax
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        from ..jit import functional_call
        from .sampling import DRAFT_SALT, sample_tokens, spec_key

        gpt = self.model.gpt
        dgpt = self.draft_model.gpt
        greedy_only = family == "greedy"

        def verify(params, dparams, kcs, vcs, dkcs, dvcs, off, tok, active,
                   n_draft, temps, top_k, top_p, eos, remaining, seeds):
            gpt_params = {n[len("gpt."):]: v for n, v in params.items()
                          if n.startswith("gpt.")}
            dgpt_params = {n[len("gpt."):]: v for n, v in dparams.items()
                           if n.startswith("gpt.")}

            def dstep(carry, i):
                dkcs, dvcs, cur = carry
                caches = [(Tensor(kc), Tensor(vc), Tensor(off + i))
                          for kc, vc in zip(dkcs, dvcs)]
                h, caches = functional_call(
                    dgpt, dgpt_params,
                    Tensor(cur[:, None].astype(jnp.int64)), caches=caches)
                dlogits = self._draft_head_traced(dparams, h._data[:, 0])
                if greedy_only:
                    d = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
                    out = d
                else:
                    keys = jax.vmap(spec_key, in_axes=(0, 0, None))(
                        seeds, off + i + 1, DRAFT_SALT)
                    d = sample_tokens(dlogits, keys, temps, top_k, top_p)
                    out = (d, dlogits)
                return ([c[0]._data for c in caches],
                        [c[1]._data for c in caches], d), out

            # k+1 steps, last proposal discarded: the extra step feeds d_k
            # so the draft cache stays dense through position off+k — a
            # fully-accepted window advances the frontier past off+k, and
            # a hole there would poison every later window's draft
            # attention (accept-rate collapse, not a correctness bug)
            (dkcs, dvcs, _), outs = jax.lax.scan(
                dstep, (dkcs, dvcs, tok),
                jnp.arange(k + 1, dtype=jnp.int32))
            if greedy_only:
                props = outs.T[:, :k]                            # [S, k]
                dlogits_sk = None
            else:
                props = outs[0].T[:, :k]
                dlogits_sk = jnp.moveaxis(outs[1], 0, 1)[:, :k]  # [S, k, V]

            win = jnp.concatenate([tok[:, None], props], axis=1)
            caches = [(Tensor(kc), Tensor(vc), Tensor(off))
                      for kc, vc in zip(kcs, vcs)]
            h, caches = functional_call(gpt, gpt_params,
                                        Tensor(win.astype(jnp.int64)),
                                        caches=caches)
            S = win.shape[0]
            logits = self._head_traced(
                params, h._data.reshape((S * (k + 1), -1))
            ).reshape((S, k + 1, -1))
            kcs = [c[0]._data for c in caches]
            vcs = [c[1]._data for c in caches]
            (new_off, new_tok, new_active, new_remaining, emit, m, a,
             hit_eos) = self._spec_commit(
                jax, jnp, logits, dlogits_sk, props, off, tok, active,
                n_draft, temps, top_k, top_p, eos, remaining, seeds, k,
                greedy_only)
            return (kcs, vcs, dkcs, dvcs, new_off, new_tok, new_active,
                    new_remaining, emit, m, a, hit_eos)

        return jax.jit(verify, donate_argnums=(2, 3, 4, 5))

    def _build_verify_paged(self, family: str, k: int):
        """Paged-layout verify: target K/V flows through the donated pool
        state with a 2-D [S, k+1] write mask — columns past a row's
        n_draft have no pages allocated and redirect to the scratch page,
        and a prefix-replay row's column 0 (position plen-1, living in a
        SHARED page) takes the same scratch redirect the decode replay
        seat uses. The draft cache stays contiguous. Rollback beyond the
        accepted frontier is host-side page-table truncation."""
        import jax
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        from ..jit import functional_call
        from . import kv_pages as _kvp
        from .sampling import DRAFT_SALT, sample_tokens, spec_key

        gpt = self.model.gpt
        dgpt = self.draft_model.gpt
        greedy_only = family == "greedy"
        pt = self.page_tokens
        quant = self._kv_quantized
        compute_dtype = self._cache_dtype

        def verify(params, dparams, state, dkcs, dvcs, off, tok, active,
                   replay, n_draft, temps, top_k, top_p, eos, remaining,
                   seeds):
            gpt_params = {n[len("gpt."):]: v for n, v in params.items()
                          if n.startswith("gpt.")}
            dgpt_params = {n[len("gpt."):]: v for n, v in dparams.items()
                           if n.startswith("gpt.")}
            tables = state["tables"]

            def dstep(carry, i):
                dkcs, dvcs, cur = carry
                caches = [(Tensor(kc), Tensor(vc), Tensor(off + i))
                          for kc, vc in zip(dkcs, dvcs)]
                h, caches = functional_call(
                    dgpt, dgpt_params,
                    Tensor(cur[:, None].astype(jnp.int64)), caches=caches)
                dlogits = self._draft_head_traced(dparams, h._data[:, 0])
                if greedy_only:
                    d = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
                    out = d
                else:
                    keys = jax.vmap(spec_key, in_axes=(0, 0, None))(
                        seeds, off + i + 1, DRAFT_SALT)
                    d = sample_tokens(dlogits, keys, temps, top_k, top_p)
                    out = (d, dlogits)
                return ([c[0]._data for c in caches],
                        [c[1]._data for c in caches], d), out

            # k+1 steps, last proposal discarded — keeps the draft cache
            # dense through off+k (see the contiguous builder)
            (dkcs, dvcs, _), outs = jax.lax.scan(
                dstep, (dkcs, dvcs, tok),
                jnp.arange(k + 1, dtype=jnp.int32))
            if greedy_only:
                props = outs.T[:, :k]
                dlogits_sk = None
            else:
                props = outs[0].T[:, :k]
                dlogits_sk = jnp.moveaxis(outs[1], 0, 1)[:, :k]

            win = jnp.concatenate([tok[:, None], props], axis=1)
            cols = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
            wmask = (active[:, None] & (cols <= n_draft[:, None])
                     & ~(replay[:, None] & (cols == 0)))
            st = {"k": state["k"], "v": state["v"], "ks": state["ks"],
                  "vs": state["vs"]}
            caches = _kvp.layer_views(st, tables, off, wmask, pt,
                                      compute_dtype)
            h, caches = functional_call(gpt, gpt_params,
                                        Tensor(win.astype(jnp.int64)),
                                        caches=caches)
            S = win.shape[0]
            logits = self._head_traced(
                params, h._data.reshape((S * (k + 1), -1))
            ).reshape((S, k + 1, -1))
            new_state = {
                "k": [c.k_pool for c in caches],
                "v": [c.v_pool for c in caches],
                "ks": [c.k_scale for c in caches] if quant else [],
                "vs": [c.v_scale for c in caches] if quant else [],
                "tables": tables,
            }
            (new_off, new_tok, new_active, new_remaining, emit, m, a,
             hit_eos) = self._spec_commit(
                jax, jnp, logits, dlogits_sk, props, off, tok, active,
                n_draft, temps, top_k, top_p, eos, remaining, seeds, k,
                greedy_only)
            new_replay = replay & ~active
            return (new_state, dkcs, dvcs, new_off, new_tok, new_active,
                    new_replay, new_remaining, emit, m, a, hit_eos)

        return jax.jit(verify, donate_argnums=(2, 3, 4))

    def _spec_dispatch_rung(self) -> int:
        """Window rung for the next dispatch: the max ladder rung among
        active speculating slots, or 0 when the dispatch must fall back to
        plain decode. Contiguous layout falls back while any active slot
        sits on the last cache row — the window's unmasked per-row writes
        would collapse onto row T-1 and corrupt the position the bonus
        column reads (bounded: only the final token of a max-length
        sequence takes the slow path)."""
        import numpy as np

        if self.draft_model is None or not self._active.any():
            return 0
        rungs = self._spec_k[self._active]
        if not rungs.any():
            return 0
        if (self.kv_layout != "paged"
                and int(self._offsets[self._active].max())
                >= self.max_seq_len - 1):
            return 0
        return int(rungs.max())

    def _advance_step(self) -> None:
        """One generation dispatch: the speculative verify program when
        any active slot opted in (non-spec slots ride along with a zero
        draft window and emit bit-identically to decode), plain decode
        otherwise."""
        k = self._spec_dispatch_rung()
        if k:
            self._verify_step(k)
        else:
            self._decode_step()

    def _prealloc_verify_pages(self, n_draft) -> None:
        """Paged pre-verify: cover every position the window may write —
        off..off+n_draft per active slot (a replay slot's column 0 is
        scratch-redirected, so its coverage starts at off+1). n_draft is
        clamped to remaining-1 on the host, so this never exceeds the
        admission reservation (end = off + remaining)."""
        import numpy as np

        from . import kv_pages as _kvp

        pt = self.page_tokens
        for i in np.nonzero(self._active)[0]:
            first = int(self._offsets[i]) + (1 if self._replay[i] else 0)
            last = min(int(self._offsets[i]) + int(n_draft[i]),
                       self.max_seq_len - 1)
            for pi in range(first // pt, last // pt + 1):
                if self._tables[i, pi] == 0:
                    if not self._prefix.ensure_free(1):
                        raise _kvp.PoolExhausted(
                            f"verify needs a page for slot {i} and none is "
                            "free or evictable (reservation accounting "
                            "violated)")
                    page = self._pool.alloc()
                    self._tables[i, pi] = page
                    self._slot_pages[i].append(page)

    def _verify_step(self, k: int) -> None:
        """Host driver for one speculative verify dispatch: draft scan +
        [S, k+1] target window + accept/commit on device, then per-slot
        token append, paged page-table truncation past the accepted
        frontier, and spec telemetry."""
        import jax.numpy as jnp
        import numpy as np

        from ..core import monitor
        from . import kv_pages as _kvp

        family = ("greedy"
                  if not self._temps[self._active].any() else "sample")
        paged = self.kv_layout == "paged"
        entry = self._execs.get_or_build(
            ("serve.verify", family, k),
            lambda: (self._build_verify_paged(family, k) if paged
                     else self._build_verify(family, k)),
            label=f"serve.verify_{family}_k{k}",
            donate=(2, 3, 4) if paged else (2, 3, 4, 5), pin=True)
        # per-slot draft window: the request's rung, clamped so the window
        # never outruns the token budget (keeps paged writes inside the
        # admission reservation) or the cache end, and zero on non-spec
        # rows — which then emit exactly one sequentially-sampled token
        n_draft = np.minimum(self._spec_k,
                             np.maximum(self._remaining - 1, 0))
        n_draft = np.minimum(
            n_draft, np.maximum(self.max_seq_len - 2 - self._offsets, 0))
        n_draft = np.where(self._active, n_draft, 0).astype(np.int32)
        if paged:
            self._prealloc_verify_pages(n_draft)
            state = dict(self._pool_state,
                         tables=jnp.asarray(self._tables))
            call_args = (self._params, self._dparams, state, self._dkcs,
                         self._dvcs, jnp.asarray(self._offsets),
                         jnp.asarray(self._last_tok),
                         jnp.asarray(self._active),
                         jnp.asarray(self._replay), jnp.asarray(n_draft),
                         jnp.asarray(self._temps), jnp.asarray(self._topk),
                         jnp.asarray(self._topp), jnp.asarray(self._eos),
                         jnp.asarray(self._remaining),
                         jnp.asarray(self._seeds))
            self._stash_exec(f"serve.verify_{family}_k{k}", entry.fn,
                             call_args, donate=(2, 3, 4))
        else:
            call_args = (self._params, self._dparams, self._kcs, self._vcs,
                         self._dkcs, self._dvcs,
                         jnp.asarray(self._offsets),
                         jnp.asarray(self._last_tok),
                         jnp.asarray(self._active), jnp.asarray(n_draft),
                         jnp.asarray(self._temps), jnp.asarray(self._topk),
                         jnp.asarray(self._topp), jnp.asarray(self._eos),
                         jnp.asarray(self._remaining),
                         jnp.asarray(self._seeds))
            self._stash_exec(f"serve.verify_{family}_k{k}", entry.fn,
                             call_args, donate=(2, 3, 4, 5))
        active_before = self._active.copy()
        p0 = self._execs.persistent_before(entry)
        t0 = time.perf_counter()
        try:
            if paged:
                (self._pool_state, self._dkcs, self._dvcs, off, tok, active,
                 replay, remaining, emit, m, a, hits) = entry(*call_args)
                self._replay = np.array(replay)
            else:
                (self._kcs, self._vcs, self._dkcs, self._dvcs, off, tok,
                 active, remaining, emit, m, a, hits) = entry(*call_args)
            self._execs.note_compiles(
                entry, wall_s=time.perf_counter() - t0, persistent_before=p0,
                counter="serving.verify_compiles")
            self._offsets = np.array(off)
            self._last_tok = np.array(tok)
            self._active = np.array(active)
            self._remaining = np.array(remaining)
            emit = np.asarray(emit)                 # [S, k+1]
            m = np.asarray(m)
            a = np.asarray(a)
            hits = np.asarray(hits)
        except Exception as e:
            fr = _obs_flight.get()
            if fr is not None:
                fr.dump("serve_verify_exception",
                        {"step": self._steps, "family": family, "k": k,
                         "error": repr(e)})
            for slot in np.nonzero(self._active)[0]:
                req = self._slot_req[slot]
                if req is not None and req.done_ts is None:
                    self._finish(req, outcome="error")
            raise
        t1 = time.perf_counter()
        tr = _obs_tracer.get_tracer()
        if tr.enabled:
            tr.record_complete("serve.verify_step", t0, t1,
                               {"step": self._steps, "family": family,
                                "k": k})
        self._steps += 1
        now = time.perf_counter()
        mreg = _obs_metrics.active_registry()
        emitted = proposed = accepted = bonus = 0
        for slot in np.nonzero(active_before)[0]:
            req = self._slot_req[slot]
            ms = int(m[slot])
            for j in range(ms):
                req.tokens.append(int(emit[slot, j]))
            emitted += ms
            if req.first_token_ts is None:   # prefix-replay first token
                req.first_token_ts = now
            nd = int(n_draft[slot])
            acc = int(min(ms, int(a[slot])))
            bn = int(ms > int(a[slot]))
            req.spec_proposed += nd
            req.spec_accepted += acc
            req.spec_bonus += bn
            proposed += nd
            accepted += acc
            bonus += bn
            if nd and mreg is not None:
                mreg.histogram("spec.accept_rate",
                               boundaries=_OCCUPANCY_BUCKETS).observe(
                    acc / nd)
            if paged:
                # rollback: any page whose positions lie wholly past the
                # accepted frontier was only touched by rejected draft
                # rows — truncate it out of the table and free it (always
                # slot-private: shared prompt pages sit below the frontier)
                _kvp.truncate_row(
                    self._tables, self._slot_pages[slot],
                    self._prefix.release, slot,
                    int(self._offsets[slot]) // self.page_tokens + 1)
            if not self._active[slot]:
                req.finish_reason = "eos" if hits[slot] else "length"
                self._slot_req[slot] = None
                if paged:
                    self._release_slot(slot)
                self._finish(req, now)
        self._count_tokens(emitted)
        monitor.stat("serving.steps").increase()
        monitor.stat("serving.verify_dispatches").increase()
        monitor.stat("serving.spec.proposed").increase(proposed)
        monitor.stat("serving.spec.accepted").increase(accepted)
        monitor.stat("serving.spec.bonus").increase(bonus)
        occupancy = float(active_before.mean())
        if mreg is not None:
            mreg.counter("serve.spec.proposed").inc(proposed)
            mreg.counter("serve.spec.accepted").inc(accepted)
            mreg.counter("serve.spec.bonus").inc(bonus)
            mreg.histogram("serve.decode_step_ms").observe((t1 - t0) * 1e3)
            mreg.histogram("serve.occupancy",
                           boundaries=_OCCUPANCY_BUCKETS).observe(occupancy)
            mreg.gauge("serve.queue_depth").set(len(self._queue))
            mreg.gauge("serve.active_slots").set(int(self._active.sum()))
            if paged:
                mreg.gauge("serve.pages_in_use").set(self._pool.in_use)
                mreg.gauge("serve.pages_cached").set(self._pool.cached)
                mreg.gauge("serve.prefix_hit_rate").set(
                    self._prefix.hit_rate)
        fr = _obs_flight.get()
        if self.sink is not None or fr is not None:
            rec = {
                "event": "serve_step", "step": self._steps,
                "ts": time.time(),
                # one target forward per verify dispatch — trace_summary
                # derives dispatches-per-token from this field
                "steps_per_dispatch": 1,
                "active_slots": int(active_before.sum()),
                "slot_count": self.slot_count,
                "occupancy": round(occupancy, 4),
                "queue_depth": len(self._queue),
                "tokens": emitted,
                "spec": True, "spec_window": k,
                "spec_proposed": proposed, "spec_accepted": accepted,
                "spec_bonus": bonus,
            }
            if paged:
                rec["pages_in_use"] = self._pool.in_use
                rec["pages_cached"] = self._pool.cached
                rec["prefix_hit_rate"] = round(self._prefix.hit_rate, 4)
            if self.sink is not None:
                self.sink.write(rec)
            if fr is not None:
                fr.record(rec)

    def _decode_step(self) -> None:
        import jax.numpy as jnp
        import numpy as np

        # per-dispatch family pick: an all-greedy slot set runs the slim
        # executable; any sampling slot routes to the full one. Two decode
        # executables max, regardless of traffic mix.
        family = ("greedy"
                  if not self._temps[self._active].any() else "sample")
        paged = self.kv_layout == "paged"
        entry = self._execs.get_or_build(
            ("serve.decode", family),
            lambda: (self._build_decode_paged(family) if paged
                     else self._build_decode(family)),
            label=f"serve.decode_{family}",
            donate=(1,) if paged else (1, 2), pin=True)
        if paged:
            self._prealloc_decode_pages()
            state = dict(self._pool_state,
                         tables=jnp.asarray(self._tables))
            call_args = (self._params, state, jnp.asarray(self._offsets),
                         jnp.asarray(self._last_tok),
                         jnp.asarray(self._active),
                         jnp.asarray(self._replay),
                         jnp.asarray(self._temps), jnp.asarray(self._topk),
                         jnp.asarray(self._topp), jnp.asarray(self._eos),
                         jnp.asarray(self._remaining),
                         jnp.asarray(self._seeds))
            self._stash_exec(f"serve.decode_{family}", entry.fn, call_args,
                             donate=(1,))
        else:
            call_args = (self._params, self._kcs, self._vcs,
                         jnp.asarray(self._offsets),
                         jnp.asarray(self._last_tok),
                         jnp.asarray(self._active),
                         jnp.asarray(self._temps), jnp.asarray(self._topk),
                         jnp.asarray(self._topp), jnp.asarray(self._eos),
                         jnp.asarray(self._remaining),
                         jnp.asarray(self._seeds))
            self._stash_exec(f"serve.decode_{family}", entry.fn, call_args)
        p0 = self._execs.persistent_before(entry)
        t0 = time.perf_counter()
        try:
            if paged:
                (self._pool_state, off, tok, active, replay, remaining,
                 toks, was_active, hits) = entry(*call_args)
                self._replay = np.array(replay)
            else:
                (self._kcs, self._vcs, off, tok, active, remaining, toks,
                 was_active, hits) = entry(*call_args)
            self._execs.note_compiles(
                entry, wall_s=time.perf_counter() - t0, persistent_before=p0,
                counter="serving.decode_compiles")
            # np.array (copy): zero-copy views of jax buffers are read-only,
            # and _admit mutates these in place when it seats the next request
            self._offsets = np.array(off)
            self._last_tok = np.array(tok)
            self._active = np.array(active)
            self._remaining = np.array(remaining)
            toks = np.asarray(toks)           # [n_inner, S]
            was_active = np.asarray(was_active)
            hits = np.asarray(hits)
        except Exception as e:
            fr = _obs_flight.get()
            if fr is not None:
                fr.dump("serve_decode_exception",
                        {"step": self._steps, "family": family,
                         "error": repr(e)})
            # a failed decode dispatch takes every in-flight request with
            # it: record each as a terminal error before re-raising so the
            # availability SLI sees the blast radius
            for slot in np.nonzero(self._active)[0]:
                req = self._slot_req[slot]
                if req is not None and req.done_ts is None:
                    self._finish(req, outcome="error")
            raise
        t1 = time.perf_counter()
        tr = _obs_tracer.get_tracer()
        if tr.enabled:
            tr.record_complete("serve.decode_step", t0, t1,
                               {"step": self._steps, "family": family})
        n_inner = toks.shape[0]
        self._steps += n_inner
        now = time.perf_counter()
        for j in range(n_inner):
            alive_after = (was_active[j + 1] if j + 1 < n_inner
                           else self._active)
            for slot in np.nonzero(was_active[j])[0]:
                req = self._slot_req[slot]
                req.tokens.append(int(toks[j, slot]))
                if req.first_token_ts is None:   # prefix-replay first token
                    req.first_token_ts = now
                if not alive_after[slot]:     # retired at this inner step
                    req.finish_reason = "eos" if hits[j, slot] else "length"
                    self._slot_req[slot] = None
                    if paged:
                        self._release_slot(slot)
                    self._finish(req, now)
        emitted = int(was_active.sum())
        self._count_tokens(emitted)
        from ..core import monitor

        monitor.stat("serving.steps").increase(n_inner)
        occupancy = float(was_active.mean())
        mreg = _obs_metrics.active_registry()
        if mreg is not None:
            mreg.histogram("serve.decode_step_ms").observe((t1 - t0) * 1e3)
            mreg.histogram("serve.occupancy",
                           boundaries=_OCCUPANCY_BUCKETS).observe(occupancy)
            mreg.gauge("serve.queue_depth").set(len(self._queue))
            mreg.gauge("serve.active_slots").set(int(self._active.sum()))
            if paged:
                mreg.gauge("serve.pages_in_use").set(self._pool.in_use)
                mreg.gauge("serve.pages_cached").set(self._pool.cached)
                mreg.gauge("serve.prefix_hit_rate").set(
                    self._prefix.hit_rate)
        fr = _obs_flight.get()
        if self.sink is not None or fr is not None:
            rec = {
                "event": "serve_step", "step": self._steps, "ts": time.time(),
                "steps_per_dispatch": n_inner,
                "active_slots": int(was_active[0].sum()),
                "slot_count": self.slot_count,
                # mean occupancy across the fused steps: retired slots are
                # masked (idle) until the chunk boundary
                "occupancy": round(occupancy, 4),
                "queue_depth": len(self._queue),
                "tokens": emitted,
            }
            if paged:
                rec["pages_in_use"] = self._pool.in_use
                rec["pages_cached"] = self._pool.cached
                rec["prefix_hit_rate"] = round(self._prefix.hit_rate, 4)
            if self.sink is not None:
                self.sink.write(rec)
            if fr is not None:
                fr.record(rec)

    # ---- bookkeeping ---------------------------------------------------
    def _count_tokens(self, n: int) -> None:
        if n:
            from ..core import monitor

            monitor.stat("serving.tokens").increase(n)

    def _finish(self, req: Request, now: Optional[float] = None,
                outcome: Optional[str] = None) -> None:
        from ..core import monitor

        req.done_ts = now if now is not None else time.perf_counter()
        # terminal disposition: normal completions inherit finish_reason
        # ("eos"/"length", "ok" as the fallback); abnormal exits pass
        # outcome="error"/"drained" explicitly and stay out of _completed
        req.outcome = outcome or req.outcome or req.finish_reason or "ok"
        if req.outcome not in ("error", "drained"):
            self._completed.append(req)
        monitor.stat("serving.requests").increase()
        monitor.stat("serving.outcome." + req.outcome).increase()
        tr = _obs_tracer.get_tracer()
        if tr.enabled:
            # the request's full span lifecycle: enqueue (instant at submit)
            # -> queue_wait -> prefill (both recorded at admit) -> decode ->
            # request envelope -> retire marker
            if req.first_token_ts is not None:
                tr.record_complete("serve.decode", req.first_token_ts,
                                   req.done_ts,
                                   req.trace_args(tokens=len(req.tokens)))
            tr.record_complete("serve.request", req.submit_ts, req.done_ts,
                               req.trace_args(finish=req.finish_reason))
            tr.instant("serve.retire", **req.trace_args(slot=req.slot))
        mreg = _obs_metrics.active_registry()
        if mreg is not None:
            mreg.counter("serve.requests").inc()
            if req.outcome == "error":
                mreg.counter("serve.errors").inc()
            if req.ttft_s is not None:
                mreg.histogram("serve.ttft_ms").observe(req.ttft_s * 1e3)
            if req.tpot_s is not None:
                mreg.histogram("serve.tpot_ms").observe(req.tpot_s * 1e3)
            if self.replica_name:
                pfx = f"serve.replica.{self.replica_name}."
                mreg.counter(pfx + "requests").inc()
                if req.outcome == "error":
                    mreg.counter(pfx + "errors").inc()
                if req.ttft_s is not None:
                    mreg.histogram(pfx + "ttft_ms").observe(req.ttft_s * 1e3)
        fr = _obs_flight.get()
        if self.sink is not None or fr is not None:
            wall = max(req.done_ts - req.submit_ts, 1e-9)
            rec = {
                "event": "serve_request", "request_id": req.id,
                "ts": time.time(),
                "prompt_len": int(len(req.prompt_ids)),
                "bucket": req.bucket, "slot": req.slot,
                "new_tokens": len(req.tokens),
                "finish_reason": req.finish_reason,
                "outcome": req.outcome,
                "ttft_s": (round(req.ttft_s, 6)
                           if req.ttft_s is not None else None),
                "queue_wait_s": (round(req.queue_wait_s, 6)
                                 if req.queue_wait_s is not None else None),
                "tpot_s": (round(req.tpot_s, 6)
                           if req.tpot_s is not None else None),
                "wall_s": round(wall, 6),
                "tokens_per_sec": round(len(req.tokens) / wall, 2),
                "queue_depth_at_submit": req.queue_depth_at_submit,
                "layout": self.kv_layout,
                "prefix_hit": req.prefix_hit,
                "shared_tokens": req.shared_tokens,
            }
            if req.speculate_k:
                rec["spec_k"] = req.speculate_k
                rec["spec_proposed"] = req.spec_proposed
                rec["spec_accepted"] = req.spec_accepted
                rec["spec_bonus"] = req.spec_bonus
            if req.tenant is not None:
                rec["tenant"] = req.tenant
            if req.trace_ctx is not None:
                rec["fleet_request_id"] = req.trace_ctx.request_id
            if self.sink is not None:
                self.sink.write(rec)
            if fr is not None:
                fr.record(rec)

"""Radix prefix cache: a trie over page-aligned token chunks mapping
shared prompt prefixes to refcounted read-only KV pages.

Same-prefix traffic (system prompts, few-shot templates) is the dominant
real-serving pattern, and the contiguous engine recomputes prefill for
every copy. With the paged layout (kv_pages.py) a prefix is just a list of
pages, so sharing is a page-table copy:

- the trie is keyed on **whole pages** of tokens (``page_tokens`` per
  edge): only fully-written prompt pages are ever inserted, so a shared
  page is immutable by construction — decode for the inserting request
  writes from position ``prompt_len`` onward, which is past every
  inserted page, and later sharers have their own fresh pages for
  everything after the match.
- ``match()`` walks the longest aligned chunk path, increfs each matched
  page on the caller's behalf, and returns the pages: the admitting
  request copies them into its page-table row and prefills only the
  unshared tail (or skips prefill entirely on a full match — the engine's
  "replay" seat).
- a page whose last slot reference drops and that still has a trie node
  parks in the pool's LRU ``evictable`` set instead of freeing: the bytes
  are a cache, not a leak. ``evict()`` frees least-recently-used
  refcount-zero **leaves** (a child's pages incref nothing in the parent,
  but any live descendant path was matched through the parent, so
  leaf-first order never frees a page a live slot can still gather).

The trie is host-side pure Python — admission-time work, nothing traced.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence, Tuple

from .kv_pages import PagePool


class _Node:
    __slots__ = ("chunk", "page", "parent", "children", "last_use")

    def __init__(self, chunk: Tuple[int, ...], page: int,
                 parent: Optional["_Node"]):
        self.chunk = chunk
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_use = 0


class RadixPrefixCache:
    """Trie of page-sized token chunks over a :class:`PagePool`.

    All slot-page lifecycle flows through here (``release`` consults the
    trie to decide park-vs-free), so the engine never touches pool
    refcounts directly.
    """

    def __init__(self, pool: PagePool, page_tokens: int):
        self.pool = pool
        self.page_tokens = int(page_tokens)
        self._root: Dict[Tuple[int, ...], _Node] = {}
        self._page_node: Dict[int, _Node] = {}
        self._clock = itertools.count(1)
        self.lookups = 0
        self.hit_tokens = 0
        self.full_hits = 0
        self.partial_hits = 0
        self.inserted_pages = 0
        self.evicted_pages = 0

    # ----------------------------------------------------------- queries
    def _chunks(self, tokens) -> List[Tuple[int, ...]]:
        pt = self.page_tokens
        n = len(tokens) // pt
        return [tuple(int(t) for t in tokens[i * pt:(i + 1) * pt])
                for i in range(n)]

    def peek(self, tokens) -> int:
        """Matched-prefix length in tokens, no refcount side effects (the
        router's prefix-locality probe)."""
        matched = 0
        children = self._root
        for chunk in self._chunks(tokens):
            node = children.get(chunk)
            if node is None:
                break
            matched += self.page_tokens
            children = node.children
        return matched

    def match(self, tokens) -> List[int]:
        """Longest aligned-chunk match; increfs every matched page for the
        caller (release each through :meth:`release` at slot retirement)
        and stamps the path for LRU."""
        self.lookups += 1
        pages: List[int] = []
        children = self._root
        tick = next(self._clock)
        for chunk in self._chunks(tokens):
            node = children.get(chunk)
            if node is None:
                break
            self.pool.incref(node.page)
            node.last_use = tick
            pages.append(node.page)
            children = node.children
        nshared = len(pages) * self.page_tokens
        self.hit_tokens += nshared
        if pages:
            if nshared >= len(tokens):
                self.full_hits += 1
            else:
                self.partial_hits += 1
        return pages

    @property
    def hit_rate(self) -> float:
        """Fraction of lookups that matched at least one page."""
        if not self.lookups:
            return 0.0
        return (self.full_hits + self.partial_hits) / self.lookups

    # ----------------------------------------------------------- updates
    def insert(self, tokens, pages: Sequence[int]) -> None:
        """Publish a request's fully-written prompt pages: ``pages[i]``
        holds chunk ``i`` of ``tokens``. Chunks already present keep the
        incumbent page (ours stays slot-private and frees at retirement);
        new chunks get a node pointing at our page — the slot's reference
        keeps it alive for now, and release() parks it when that drops."""
        children = self._root
        parent: Optional[_Node] = None
        tick = next(self._clock)
        for chunk, page in zip(self._chunks(tokens), pages):
            node = children.get(chunk)
            if node is None:
                if page in self._page_node:   # page already published
                    break                     # (shouldn't happen; be safe)
                node = _Node(chunk, int(page), parent)
                children[chunk] = node
                self._page_node[int(page)] = node
                self.inserted_pages += 1
            node.last_use = tick
            parent = node
            children = node.children

    def release(self, page: int) -> None:
        """Drop one slot reference. At refcount zero the page either parks
        as evictable (it has a trie node — content stays reusable) or goes
        straight back to the free list."""
        if self.pool.decref(page) == 0:
            if page in self._page_node:
                self.pool.park(page, next(self._clock))
            else:
                self.pool.release(page)

    # ---------------------------------------------------------- eviction
    def _evict_one(self) -> bool:
        """Free the least-recently-used refcount-zero leaf. Evicting a
        leaf may expose its parent; callers loop."""
        for page in list(self.pool.evictable):
            node = self._page_node.get(page)
            if node is None or node.children:
                continue
            siblings = (node.parent.children if node.parent is not None
                        else self._root)
            siblings.pop(node.chunk, None)
            del self._page_node[page]
            self.pool.release(page)
            self.evicted_pages += 1
            return True
        return False

    def evict(self, n: int = 1) -> int:
        """Try to free n pages from the evictable set; returns how many
        were actually freed."""
        freed = 0
        while freed < n and self._evict_one():
            freed += 1
        return freed

    def flush(self) -> int:
        """Drop every refcount-zero cached prefix (bench hygiene: measure
        a cold trie against warm executables)."""
        freed = 0
        while self._evict_one():
            freed += 1
        return freed

    def ensure_free(self, n: int) -> bool:
        """Make sure the pool has >= n free pages, evicting cached
        prefixes LRU-first. False if the pool simply isn't big enough."""
        while self.pool.free_count < n:
            if not self._evict_one():
                return False
        return True

    def stats(self) -> Dict[str, float]:
        return {
            "lookups": self.lookups,
            "full_hits": self.full_hits,
            "partial_hits": self.partial_hits,
            "hit_rate": round(self.hit_rate, 4),
            "hit_tokens": self.hit_tokens,
            "inserted_pages": self.inserted_pages,
            "evicted_pages": self.evicted_pages,
            "cached_pages": self.pool.cached,
        }

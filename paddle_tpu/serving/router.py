"""Replica router: queue-depth / occupancy / prefix-locality-aware
admission over K ServingEngine replicas.

One engine replica saturates at slot_count concurrent decodes; the
"millions of users" tier is K replicas behind a router. Placement uses
the telemetry the engines already export (PR 6) plus the paged engines'
prefix trie (kv_pages/prefix_cache):

    score = w_queue * queue_depth / slots
          + w_occupancy * occupancy
          - w_prefix * (matched prefix tokens / prompt tokens)

Lowest score wins (ties break deterministically by replica name), so an
idle replica that already holds this prompt's prefix pages beats an
equally idle cold one — prefix locality is worth real TTFT (the replica
skips straight to decode on a full hit). The prefix probe is
``engine.prefix_match_len`` (a refcount-free trie peek; contiguous
replicas score 0).

Drain integration (PR 12): a replica whose ``_draining`` flag is set —
by ``begin_drain()``, ``drain()``, or the SIGTERM handler — stops
receiving admissions immediately but keeps being stepped so its active
slots run to completion. ``submit()`` raises only when NO live replica
remains.

Metrics (route.*, PR 6 registry when active): ``route.requests``,
``route.prefix_routed`` counters, ``route.replicas_live`` gauge, and a
``route.queue_depth`` histogram of the chosen replica's depth at
admission. The sink (if any) gets one ``route`` record per placement.

Distributed tracing (ISSUE 14): when the tracer is enabled, each
placement records a ``route.place`` span carrying a minted span id and a
fleet request id, and hands the engine a ``fleet.TraceContext`` so every
engine-side span of that request (queue wait, prefill, decode, retire)
is tagged ``request_id=...`` with ``parent_span`` pointing back at the
placement — one chrome trace then renders routing decision + replica
execution as a single parented timeline. Dark path unchanged: tracer
off means no context allocation, no extra span args.

Host-side only — the router never touches device state.
"""
from __future__ import annotations

import collections
import time
from typing import Dict, List, Optional, Sequence, Union

from ..observability import fleet as _obs_fleet
from ..observability import metrics as _obs_metrics
from ..observability import tracer as _obs_tracer
from .engine import Request, ServingEngine


class ReplicaRouter:
    """Front K in-process ServingEngine replicas with placement-aware
    admission and a shared drive loop.

    replicas: list (auto-named r0..rK-1) or dict name -> engine.
    """

    def __init__(self, replicas: Union[Sequence[ServingEngine],
                                       Dict[str, ServingEngine]],
                 sink=None, w_queue: float = 1.0, w_occupancy: float = 1.0,
                 w_prefix: float = 2.0):
        if not isinstance(replicas, dict):
            replicas = {f"r{i}": e for i, e in enumerate(replicas)}
        if not replicas:
            raise ValueError("ReplicaRouter needs at least one replica")
        self.replicas: Dict[str, ServingEngine] = dict(replicas)
        self.sink = sink
        self.w_queue = float(w_queue)
        self.w_occupancy = float(w_occupancy)
        self.w_prefix = float(w_prefix)
        self.routed: Dict[str, int] = {name: 0 for name in self.replicas}
        self.prefix_routed = 0
        # SLO self-healing (observability.slo): firing per-replica alerts
        # add a score penalty here so traffic flows away from the sick
        # replica; resolution removes it. See attach_slo().
        self._shed: Dict[str, float] = {}
        for name, eng in self.replicas.items():
            if eng.replica_name is None:
                eng.replica_name = name
        # bounded tail of placement decisions: flight dumps embed it via
        # fleet.flight_context() so a crash shows where traffic was going
        self._placements: collections.deque = collections.deque(maxlen=64)
        _obs_fleet.register_router(self)

    # ---------------------------------------------------------- placement
    def live_replicas(self) -> Dict[str, ServingEngine]:
        """Replicas currently accepting admissions (not draining)."""
        return {n: e for n, e in self.replicas.items() if not e._draining}

    def _score(self, name: str, eng: ServingEngine, prompt_ids) -> Dict:
        qd = eng.queue_depth()
        occ = eng.occupancy()
        plen = max(1, len(prompt_ids))
        matched = min(eng.prefix_match_len(prompt_ids), plen)
        frac = matched / plen
        return {
            "replica": name,
            "queue_depth": qd,
            "occupancy": round(occ, 4),
            "prefix_tokens": matched,
            "score": (self.w_queue * qd / eng.slot_count
                      + self.w_occupancy * occ
                      - self.w_prefix * frac
                      + self._shed.get(name, 0.0)),
        }

    def submit(self, prompt_ids, trace_ctx=None, _replaced=False,
               **kwargs) -> Request:
        """Place one request on the best live replica (see module doc for
        the score). Raises RuntimeError when every replica is draining.

        With the tracer enabled, the placement itself becomes a
        ``route.place`` span whose minted span id is the ``parent_span``
        of every engine-side span this request records; ``trace_ctx``
        lets a re-placement (begin_drain) keep the original request id.
        ``_replaced`` marks a begin_drain re-placement: the same logical
        request, already counted at first submission — it must not
        re-increment ``route.requests`` (the capacity controller's
        scale-in signal reads that counter; double counting would read as
        phantom load). It counts under ``route.replaced`` instead.
        """
        tr = _obs_tracer.get_tracer()
        t0 = time.perf_counter() if tr.enabled else None
        live = self.live_replicas()
        if not live:
            raise RuntimeError(
                "ReplicaRouter: all replicas are draining; no admission "
                "target remains")
        scored = [self._score(n, e, prompt_ids)
                  for n, e in sorted(live.items())]
        best = min(scored, key=lambda s: (s["score"], s["replica"]))
        name = best["replica"]
        ctx = trace_ctx
        if tr.enabled:
            if ctx is None:
                ctx = _obs_fleet.TraceContext()
            ctx.parent_span = _obs_tracer.new_span_id()
        req = live[name].submit(prompt_ids, trace_ctx=ctx, **kwargs)
        self.routed[name] += 1
        if best["prefix_tokens"] > 0:
            self.prefix_routed += 1
        if tr.enabled:
            # span_id (not parent_span): the placement IS the parent the
            # engine-side children point back at
            tr.record_complete("route.place", t0, time.perf_counter(), {
                "request": req.id, "request_id": ctx.request_id,
                "span_id": ctx.parent_span, "replica": name,
                "score": round(best["score"], 4),
                "prefix_tokens": best["prefix_tokens"],
            })
        self._placements.append({
            "ts": time.time(), "request": req.id, "replica": name,
            "score": round(best["score"], 4),
            "queue_depth": best["queue_depth"],
            "occupancy": best["occupancy"],
            "prefix_tokens": best["prefix_tokens"],
            **({"request_id": ctx.request_id} if ctx is not None else {}),
        })
        mreg = _obs_metrics.active_registry()
        if mreg is not None:
            if _replaced:
                mreg.counter("route.replaced").inc()
            else:
                mreg.counter("route.requests").inc()
            if best["prefix_tokens"] > 0:
                mreg.counter("route.prefix_routed").inc()
            mreg.gauge("route.replicas_live").set(len(live))
            mreg.histogram("route.queue_depth").observe(best["queue_depth"])
        if self.sink is not None:
            rec = {
                "event": "route", "ts": time.time(), "request_id": req.id,
                "replica": name, "score": round(best["score"], 4),
                "queue_depth": best["queue_depth"],
                "occupancy": best["occupancy"],
                "prefix_tokens": best["prefix_tokens"],
                "replicas_live": len(live),
                "candidates": len(scored),
            }
            if _replaced:
                rec["replaced"] = True
            if ctx is not None:
                rec["fleet_request_id"] = ctx.request_id
            self.sink.write(rec)
        return req

    def recent_placements(self) -> List[Dict]:
        """Bounded tail of placement decisions, oldest first (embedded in
        flight-recorder state.json via fleet.flight_context())."""
        return list(self._placements)

    # ------------------------------------------------------ SLO shedding
    def shed(self, name: str, penalty: float = 10.0) -> None:
        """Deprioritize one replica: add a flat score penalty so every
        other live replica wins placement while it recovers. Idempotent;
        the replica still serves (it is not draining) if every other
        replica is worse by more than the penalty."""
        if name not in self.replicas:
            raise KeyError(f"unknown replica {name!r}")
        self._shed[name] = float(penalty)
        mreg = _obs_metrics.active_registry()
        if mreg is not None:
            mreg.counter("route.sheds").inc()
            mreg.gauge("route.shedding").set(float(len(self._shed)))

    def unshed(self, name: str) -> None:
        if self._shed.pop(name, None) is not None:
            mreg = _obs_metrics.active_registry()
            if mreg is not None:
                mreg.gauge("route.shedding").set(float(len(self._shed)))

    def shedding(self) -> List[str]:
        return sorted(self._shed)

    def attach_slo(self, slo_engine, penalty: float = 10.0,
                   drain: bool = False) -> None:
        """Close the loop from per-replica SLOs to placement: register a
        hook on ``slo_engine`` (observability.slo.SloEngine) that sheds a
        replica while an alert labeled ``{"replica": <name>}`` is firing
        and unsheds it on resolve. With ``drain=True``, a *page*-severity
        fire also begins draining the replica (its queued work re-places
        on healthy replicas) — only while at least one other live replica
        remains, so healing never closes the last admission target."""
        def _hook(ev: Dict) -> None:
            name = (ev.get("labels") or {}).get("replica")
            if name is None or name not in self.replicas:
                return
            if ev.get("state") == "firing":
                self.shed(name, penalty)
                if (drain and ev.get("severity") == "page"
                        and not self.replicas[name]._draining
                        and len(self.live_replicas()) > 1):
                    self.begin_drain(name, reason="slo")
            elif ev.get("state") == "resolved":
                self.unshed(name)

        slo_engine.add_hook(_hook)

    # -------------------------------------------------------------- drive
    def step(self) -> int:
        """One engine step on every replica (draining ones included — their
        active slots must finish). Returns total live slots after."""
        return sum(e.step() for e in self.replicas.values())

    def pending(self) -> int:
        return sum(len(e._queue) + int(e._active.sum())
                   for e in self.replicas.values())

    def run(self, max_steps: Optional[int] = None) -> None:
        """Drive all replicas until queues and slots drain everywhere."""
        steps = 0
        while self.pending():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                return

    # -------------------------------------------------------------- drain
    def begin_drain(self, name: str, reason: str = "drain") -> List[Request]:
        """Close admission on one replica. Its active slots keep decoding
        to completion under step()/run(), but queued-not-yet-admitted work
        would strand (a draining engine stops pulling its queue), so it is
        re-placed on the remaining live replicas. Returns the re-placed
        Request handles (the stranded originals never produce tokens).

        Counter audit (capacity controller reads these): the drained
        replica's ``routed`` credit for never-admitted requests moves with
        them, and the re-submission goes through the ``_replaced`` path —
        ``route.requests`` counts each logical request exactly once, and
        ``serve.replica.<name>.requests`` (finish-time) only ever counts
        the replica that actually served it."""
        eng = self.replicas[name]
        requeue = []
        with eng._lock:
            while eng._queue:
                requeue.append(eng._queue.popleft())
        self.routed[name] -= len(requeue)
        eng.begin_drain(reason)
        return [self.submit(req.prompt_ids, trace_ctx=req.trace_ctx,
                            _replaced=True,
                            max_new_tokens=req.max_new_tokens,
                            temperature=req.temperature, top_k=req.top_k,
                            top_p=req.top_p, eos_token_id=req.eos_token_id,
                            seed=req.seed, tenant=req.tenant)
                for req in requeue]

    def drained(self, name: str) -> bool:
        eng = self.replicas[name]
        return bool(eng._draining) and not eng._active.any()

    # ------------------------------------------------- elastic replica set
    def add_replica(self, name: str, engine: ServingEngine) -> None:
        """Grow the fleet in place (capacity controller scale-out): the new
        replica is eligible for placement on the very next submit()."""
        if name in self.replicas:
            raise ValueError(f"replica {name!r} already exists")
        if engine.replica_name is None:
            engine.replica_name = name
        self.replicas[name] = engine
        self.routed.setdefault(name, 0)
        mreg = _obs_metrics.active_registry()
        if mreg is not None:
            mreg.gauge("route.replicas_live").set(len(self.live_replicas()))

    def remove_replica(self, name: str) -> ServingEngine:
        """Retire a fully drained replica (capacity controller scale-in):
        refuses while it still holds queued or active work — drain first
        (begin_drain + step until drained()). Calls engine.retire() so a
        registered membership lease is released (graceful leave)."""
        eng = self.replicas[name]
        if not eng._draining or eng._active.any() or eng._queue:
            raise RuntimeError(
                f"replica {name!r} is not drained (draining="
                f"{eng._draining}, active={int(eng._active.sum())}, "
                f"queued={len(eng._queue)}); begin_drain and step first")
        del self.replicas[name]
        self.routed.pop(name, None)
        self._shed.pop(name, None)
        eng.retire()
        mreg = _obs_metrics.active_registry()
        if mreg is not None:
            mreg.gauge("route.replicas_live").set(len(self.live_replicas()))
        return eng

    def stats(self) -> Dict:
        return {
            "replicas": {n: {"draining": e._draining,
                             "queued": e.queue_depth(),
                             "active": int(e._active.sum()),
                             "routed": self.routed[n],
                             "completed": len(e._completed)}
                         for n, e in self.replicas.items()},
            "prefix_routed": self.prefix_routed,
            "total_routed": sum(self.routed.values()),
            "shedding": sorted(self._shed),
        }

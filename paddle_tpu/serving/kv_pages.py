"""Paged KV cache: fixed-size pages + a block allocator + a per-slot page
table traced into the serving executables as an integer gather index.

The PR 4 engine pins one slot-contiguous ``[slots, max_seq_len, nh, hd]``
cache row per slot, so every request reserves worst-case bytes and no two
requests can share anything. The paged layout (vLLM's PagedAttention block
table, arXiv 2309.06180) breaks each sequence into ``page_tokens``-sized
pages drawn from one shared pool:

- **device state** (per layer): a page pool ``[num_pages, page_tokens, nh,
  hd]`` plus, for all layers at once, ONE page table ``[slots, max_pages]``
  of int32 pool indices. Both shapes are static, so the two-executable
  (bucketed prefill + single decode) design and buffer donation survive
  unchanged — the page table is just another traced integer operand.
- **read** = gather: ``pool[table]`` reassembles each slot's logical
  ``[max_pages * page_tokens, nh, hd]`` K/V, and the existing causal mask
  (``col <= query_pos``) makes everything past a slot's offset inert.
- **write** = scatter: token position ``p`` lands in page ``table[slot,
  p // page_tokens]`` at row ``p % page_tokens``.

Two pool pages are reserved:

- page 0 is the **zero page**: every unallocated page-table entry points
  here and it is never written, so gathering an unallocated region reads
  exact zeros — the same values a freshly zero-initialized contiguous
  cache holds, which is what makes paged attention bit-identical to the
  contiguous engine (masked columns contribute exp(-1e9) == 0.0 either
  way).
- page 1 is the **scratch page**: rows that must not write (idle slots,
  prefix-replay steps re-deriving an already-cached position) have their
  scatter redirected here. It is never read through any table.

Quantized pages (``FLAGS_kv_cache_dtype``): 'bf16' casts the pool;
'int8' stores EQuARX-style chunk-scaled int8 (grad_comm's absmax/127
scheme, PAPERS.md 2506.17615) with one f32 scale per (page, token, head),
dequantized inside the attention read.

Host side, :class:`PagePool` is a refcounting block allocator (free list +
LRU-evictable set of refcount-zero pages still referenced by the radix
prefix cache — see prefix_cache.py).
"""
from __future__ import annotations

from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence

ZERO_PAGE = 0
SCRATCH_PAGE = 1
RESERVED_PAGES = 2


class PoolExhausted(RuntimeError):
    """No free page and nothing evictable — the pool is undersized for the
    admitted load (raise kv_num_pages or lower slot_count/max_new_cap)."""


class PagePool:
    """Host-side page accounting: a free list plus per-page refcounts.

    The pool tracks *references held by live slots* only — the prefix
    cache holds pages weakly (a refcount-0 page with a trie node parks in
    the LRU ``evictable`` set, still allocated, content preserved, until
    either re-matched or evicted to satisfy an allocation).
    """

    def __init__(self, num_pages: int):
        import numpy as np

        if num_pages < RESERVED_PAGES + 1:
            raise ValueError(f"num_pages must be > {RESERVED_PAGES}, "
                             f"got {num_pages}")
        self.num_pages = int(num_pages)
        self.free: deque = deque(range(RESERVED_PAGES, self.num_pages))
        self.ref = np.zeros(self.num_pages, np.int32)
        # page -> monotonic clock at last release (LRU eviction order);
        # maintained by the prefix cache via park()/unpark()
        self.evictable: "OrderedDict[int, int]" = OrderedDict()
        self.allocs = 0
        self.evictions = 0

    # -- capacity -------------------------------------------------------
    @property
    def free_count(self) -> int:
        return len(self.free)

    @property
    def available(self) -> int:
        """Pages an allocation could obtain (free + evictable-cached)."""
        return len(self.free) + len(self.evictable)

    @property
    def in_use(self) -> int:
        """Pages referenced by at least one live slot."""
        return int((self.ref > 0).sum())

    @property
    def cached(self) -> int:
        """Refcount-zero pages parked for prefix reuse."""
        return len(self.evictable)

    # -- alloc / refs ---------------------------------------------------
    def alloc(self) -> int:
        """Pop a free page with refcount 1. Caller must have ensured a
        free page exists (evicting through the prefix cache if needed)."""
        if not self.free:
            raise PoolExhausted(
                f"KV page pool exhausted: {self.num_pages} pages, "
                f"{self.in_use} in use, {self.cached} cached (nothing "
                "evictable was freed) — raise kv_num_pages")
        p = self.free.popleft()
        self.ref[p] = 1
        self.allocs += 1
        return p

    def incref(self, page: int) -> int:
        self.ref[page] += 1
        if page in self.evictable:      # back in use: no longer evictable
            del self.evictable[page]
        return int(self.ref[page])

    def decref(self, page: int) -> int:
        if self.ref[page] <= 0:
            raise RuntimeError(f"decref of unreferenced page {page}")
        self.ref[page] -= 1
        return int(self.ref[page])

    def release(self, page: int) -> None:
        """Return a refcount-zero page to the free list."""
        if self.ref[page] != 0:
            raise RuntimeError(
                f"release of page {page} with refcount {self.ref[page]}")
        self.evictable.pop(page, None)
        self.free.append(page)

    def park(self, page: int, clock: int) -> None:
        """Park a refcount-zero page as evictable (prefix-cached)."""
        self.evictable[page] = clock
        self.evictable.move_to_end(page)


def resolve_store_dtype(mode: str, compute_dtype):
    """Map FLAGS_kv_cache_dtype to (storage dtype, quantized?)."""
    import jax.numpy as jnp

    if mode in (None, "", "auto"):
        return compute_dtype, False
    if mode == "bf16":
        return jnp.bfloat16, False
    if mode == "int8":
        return jnp.int8, True
    raise ValueError(f"kv_cache_dtype must be auto|bf16|int8, got {mode!r}")


def quantize_kv_int8(x):
    """[..., hd] -> (int8 [..., hd], f32 scale [...]) — grad_comm's
    EQuARX absmax/127 chunk scaling with the head_dim as the chunk."""
    import jax.numpy as jnp

    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1) / 127.0
    safe = jnp.maximum(scale, 1e-30)
    q = jnp.clip(jnp.round(xf / safe[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


class PagedLayerCache:
    """Traced per-layer view of the paged KV state, duck-compatible with
    the dense ``(k_cache, v_cache, offset)`` cache tuple GPTModel indexes
    (``cache[2]`` -> per-row offsets). Built fresh inside each traced
    prefill/decode step from the donated pool-state operands.

    offset: int32 [b] — count of already-cached positions per row (the
    write position of this step's token), pre-clamped by the engine.
    write_mask: bool [b] or [b, s] — rows/positions whose scatter goes to
    a real page; everything else is redirected to the scratch page.
    """

    def __init__(self, k_pool, v_pool, page_table, offset, write_mask,
                 page_tokens: int, compute_dtype, k_scale=None, v_scale=None):
        self.k_pool = k_pool            # [P, pt, nh, hd] storage dtype
        self.v_pool = v_pool
        self.page_table = page_table    # [b, max_pages] int32
        self.offset = offset            # [b] int32
        self.write_mask = write_mask    # [b] or [b, s] bool
        self.page_tokens = int(page_tokens)
        self.compute_dtype = compute_dtype
        self.k_scale = k_scale          # [P, pt, nh] f32 (int8 mode only)
        self.v_scale = v_scale

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    def __getitem__(self, i):
        # GPTModel reads caches[0][2] for position embeddings
        if i == 2:
            from ..core.tensor import Tensor

            return Tensor(self.offset)
        raise IndexError(f"PagedLayerCache exposes only [2] (offset), "
                         f"got [{i}]")


def update_and_read(cache: PagedLayerCache, k, v):
    """Scatter this step's K/V into the pools through the page table, then
    gather the full logical cache back out in compute dtype.

    k, v: [b, s, nh, hd]. Returns (kc, vc, new_cache) where kc/vc are the
    dense [b, max_pages * page_tokens, nh, hd] views attention consumes
    and new_cache carries the updated pools with offset advanced by s.
    """
    import jax.numpy as jnp

    b, s = k.shape[0], k.shape[1]
    pt = cache.page_tokens
    table = cache.page_table
    max_pages = table.shape[1]
    t_eff = max_pages * pt

    pos = cache.offset[:, None] + jnp.arange(s, dtype=jnp.int32)[None, :]
    pos_c = jnp.clip(pos, 0, t_eff - 1)                       # [b, s]
    pidx = pos_c // pt
    within = pos_c % pt
    gpage = jnp.take_along_axis(table, pidx, axis=1)          # [b, s]
    wm = cache.write_mask
    if wm.ndim == 1:
        wm = wm[:, None]
    # out-of-range positions (idle slot at the cache tip) always redirect
    wm = wm & (pos < t_eff)
    target = jnp.where(wm, gpage, jnp.int32(SCRATCH_PAGE))    # [b, s]

    k_pool, v_pool = cache.k_pool, cache.v_pool
    k_scale, v_scale = cache.k_scale, cache.v_scale
    if cache.quantized:
        qk, sk = quantize_kv_int8(k)                          # [b,s,nh,hd]/[b,s,nh]
        qv, sv = quantize_kv_int8(v)
        k_pool = k_pool.at[target, within].set(qk)
        v_pool = v_pool.at[target, within].set(qv)
        k_scale = k_scale.at[target, within].set(sk)
        v_scale = v_scale.at[target, within].set(sv)
    else:
        k_pool = k_pool.at[target, within].set(k.astype(k_pool.dtype))
        v_pool = v_pool.at[target, within].set(v.astype(v_pool.dtype))

    # gather: [b, max_pages, pt, nh, hd] -> [b, t_eff, nh, hd]
    def _gather(pool, scale):
        g = pool[table]
        if scale is not None:
            g = g.astype(jnp.float32) * scale[table][..., None]
        g = g.reshape((b, t_eff) + g.shape[3:])
        return g.astype(cache.compute_dtype)

    kc = _gather(k_pool, k_scale)
    vc = _gather(v_pool, v_scale)
    new_cache = PagedLayerCache(
        k_pool, v_pool, table, cache.offset + jnp.int32(s), cache.write_mask,
        pt, cache.compute_dtype, k_scale, v_scale)
    return kc, vc, new_cache


def truncate_row(tables, slot_pages: List[int], release, slot: int,
                 keep_pages: int) -> int:
    """Speculative-decode rollback for a paged slot: drop the page-table
    entries past ``keep_pages`` and return their pages to the pool.

    After a verify window is partially rejected the slot's offset rewinds
    to the accepted frontier; pages past ``keep_pages`` (the page holding
    the next write position) hold only rejected rows. They are always
    slot-private — shared prefix pages and trie-published prompt pages all
    sit at indices below ``new_off // page_tokens`` because generation
    positions start at the prompt length — so releasing them through the
    prefix cache frees them outright (no trie node, refcount hits zero).

    tables: host [slots, max_pages] int32; slot_pages: the slot's owned/
    shared page list (mutated); release: RadixPrefixCache.release.
    Returns the number of pages freed.
    """
    freed = 0
    for pi in range(keep_pages, tables.shape[1]):
        page = int(tables[slot, pi])
        if page == ZERO_PAGE:
            continue
        tables[slot, pi] = ZERO_PAGE
        slot_pages.remove(page)
        release(page)
        freed += 1
    return freed


def make_pool_state(num_layers: int, num_pages: int, page_tokens: int,
                    num_heads: int, head_dim: int, slots: int,
                    max_pages: int, store_dtype, quantized: bool) -> Dict:
    """Device-side paged state as one donated pytree: per-layer K/V pools,
    optional per-layer scale pools, and the shared page table."""
    import jax.numpy as jnp

    shape = (num_pages, page_tokens, num_heads, head_dim)
    state = {
        "k": [jnp.zeros(shape, store_dtype) for _ in range(num_layers)],
        "v": [jnp.zeros(shape, store_dtype) for _ in range(num_layers)],
        "ks": [], "vs": [],
        "tables": jnp.zeros((slots, max_pages), jnp.int32),
    }
    if quantized:
        sshape = (num_pages, page_tokens, num_heads)
        state["ks"] = [jnp.zeros(sshape, jnp.float32)
                       for _ in range(num_layers)]
        state["vs"] = [jnp.zeros(sshape, jnp.float32)
                       for _ in range(num_layers)]
    return state


def pool_state_bytes(state: Dict) -> int:
    """Total device bytes of pools + scales + tables (the paged engine's
    KV-cache footprint, what serve_bench's per-MB concurrency divides by)."""
    import jax

    return sum(a.size * a.dtype.itemsize
               for a in jax.tree_util.tree_leaves(state))


def layer_views(state: Dict, table, offset, write_mask, page_tokens: int,
                compute_dtype) -> List[PagedLayerCache]:
    """One PagedLayerCache per layer over a (possibly sliced) table."""
    n = len(state["k"])
    ks = state["ks"] or [None] * n
    vs = state["vs"] or [None] * n
    return [PagedLayerCache(state["k"][i], state["v"][i], table, offset,
                            write_mask, page_tokens, compute_dtype,
                            ks[i], vs[i])
            for i in range(n)]

"""paddle.hub (reference python/paddle/hub.py): load models from a repo dir's
hubconf.py. Zero-egress build: only source='local' works; github/gitee sources
raise with a clear message."""
from __future__ import annotations

import importlib.util
import os
import sys


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no hubconf.py in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    # hubconf files import sibling modules relative to the repo (reference hub
    # inserts repo_dir into sys.path around the import)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        try:
            sys.path.remove(repo_dir)
        except ValueError:
            pass
    return mod


def _check_source(source):
    if source != "local":
        raise RuntimeError(
            f"hub source {source!r} needs network access; this build is "
            f"zero-egress — clone the repo and use source='local'")


def list(repo_dir, source="local", force_reload=False):
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    _check_source(source)
    return getattr(_load_hubconf(repo_dir), model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    _check_source(source)
    return getattr(_load_hubconf(repo_dir), model)(**kwargs)

"""paddle.utils.unique_name (reference python/paddle/utils/unique_name.py →
fluid/unique_name.py): process-wide name generator with guard scoping."""
from __future__ import annotations

import contextlib
import threading

_state = threading.local()


def _counters():
    if not hasattr(_state, "counters"):
        _state.counters = [{}]
    return _state.counters


def generate(key: str) -> str:
    c = _counters()[-1]
    c[key] = c.get(key, -1) + 1
    return f"{key}_{c[key]}"


def switch(new_generator=None):
    old = _counters()[-1]
    _counters()[-1] = new_generator if new_generator is not None else {}
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    _counters().append(new_generator if isinstance(new_generator, dict) else {})
    try:
        yield
    finally:
        _counters().pop()

"""paddle.utils.dlpack: zero-copy tensor interchange via the DLPack protocol.

Reference: python/paddle/utils/dlpack.py:26,62 (to_dlpack/from_dlpack over
LoDTensor._to_dlpack / from_dlpack capsules). TPU-native design: jax arrays
already speak DLPack natively (``__dlpack__`` / ``jax.dlpack``), so the
exchange object IS the jax array — `to_dlpack` returns a capsule for legacy
consumers, and `from_dlpack` accepts anything exporting ``__dlpack__``
(numpy, torch, jax, cupy) or a raw capsule. On CPU the import is zero-copy;
across devices (e.g. torch-cpu -> TPU HBM) jax falls back to a copy, which
matches the reference's cross-device semantics.
"""
from __future__ import annotations

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Export a paddle Tensor as a DLPack capsule.

    The capsule follows the standard lifetime rules: consume it exactly once
    (``from_dlpack``), after which it is renamed "used_dltensor" and owned by
    the consumer. Prefer passing the Tensor itself to modern consumers —
    ``torch.from_dlpack(t)`` / ``np.from_dlpack(t)`` work directly because
    Tensor forwards ``__dlpack__``.
    """
    from ..core.tensor import Tensor

    if not isinstance(x, Tensor):
        raise TypeError(
            f"The type of 'x' in to_dlpack must be paddle Tensor, got "
            f"{type(x)}")
    return x._data.__dlpack__()


def from_dlpack(dlpack):
    """Import a DLPack-compatible object (numpy/torch/jax array, a paddle
    Tensor, or a legacy capsule from ``to_dlpack``) as a paddle Tensor.

    Zero-copy when producer and consumer share a device + layout; otherwise
    jax copies to the default device.
    """
    import jax.numpy as jnp

    from ..core.tensor import Tensor

    if isinstance(dlpack, Tensor):
        return Tensor(dlpack._data)
    if hasattr(dlpack, "__dlpack__"):
        return Tensor(_to_default_backend(jnp.from_dlpack(dlpack)))
    # legacy path: a raw PyCapsule produced by to_dlpack / torch's
    # to_dlpack. jax dropped direct capsule ingestion, so wrap the capsule
    # in a one-shot protocol shim; the DLDevice is read straight from the
    # DLManagedTensor header (void* data, then {i32 device_type, i32
    # device_id} — the stable DLPack ABI).
    type_name = type(dlpack).__name__
    if type_name != "PyCapsule":
        raise TypeError(
            f"from_dlpack needs a DLPack-exporting object or capsule, got "
            f"{type(dlpack)}")
    return Tensor(_to_default_backend(jnp.from_dlpack(_CapsuleShim(dlpack))))


def _to_default_backend(arr):
    """Re-home an imported array on the default backend when the producer
    lives elsewhere (e.g. torch-cpu capsule imported in a TPU process): the
    import commits the array to the producer's device, and jax refuses mixed
    -device math. Same-backend imports stay zero-copy."""
    import jax

    default = jax.devices()[0]
    src = next(iter(arr.devices()))
    if src.platform == default.platform:
        return arr
    return jax.device_put(arr, default)


class _CapsuleShim:
    """Adapts a legacy DLPack capsule to the modern __dlpack__ protocol.

    The DLDevice (and the versioned-vs-legacy flavor) is parsed eagerly at
    construction, while the capsule is guaranteed live — so
    ``__dlpack_device__`` keeps answering after the one-shot ``__dlpack__``
    hand-off consumed the capsule."""

    def __init__(self, capsule):
        import ctypes

        api = ctypes.pythonapi
        api.PyCapsule_GetPointer.restype = ctypes.c_void_p
        api.PyCapsule_GetPointer.argtypes = [ctypes.py_object, ctypes.c_char_p]
        ptr, versioned = None, False
        for name in (b"dltensor", b"dltensor_versioned"):
            try:
                ptr = api.PyCapsule_GetPointer(capsule, name)
                versioned = name.endswith(b"versioned")
                break
            except ValueError:
                ctypes.pythonapi.PyErr_Clear()
        if not ptr:
            raise ValueError("not a DLPack capsule")
        # DLManagedTensorVersioned prepends {DLPackVersion (2*u32), void*
        # manager_ctx, void* deleter, u64 flags} before the DLTensor
        base = ptr + (8 + 8 + 8 + 8 if versioned else 0)
        dev = (ctypes.c_int32 * 2).from_address(base + 8)  # after void* data
        self._device = (int(dev[0]), int(dev[1]))
        self._versioned = versioned
        self._capsule = capsule

    def __dlpack__(self, *args, **kwargs):
        cap, self._capsule = self._capsule, None
        if cap is None:
            raise RuntimeError("DLPack capsule already consumed")
        if self._versioned and kwargs.get("max_version") is None:
            # the consumer negotiated for a legacy 'dltensor' capsule; the
            # one we hold is versioned and cannot be downgraded in place
            raise BufferError(
                "producer capsule is DLPack-versioned but the consumer "
                "requested the legacy format")
        return cap

    def __dlpack_device__(self):
        return self._device

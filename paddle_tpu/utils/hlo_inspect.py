"""Shared helpers for classifying compiled-HLO text in perf gates and probes.

Used by tests/test_hlo_perf_gates.py and tools/decode_hlo_probe.py so the
fragile text heuristics (XLA metadata tags, shape regexes) live in ONE place.
The reference's analogue is the IR-pass test utilities that grep ProgramDesc
text (test/ir mem_opt pass tests); here the inspected artifact is XLA's
optimized HLO.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple


def cost_analysis_dict(compiled) -> Dict:
    """`compiled.cost_analysis()` normalized to ONE flat dict across jax
    versions: older releases return a list with one dict per device program,
    newer ones the dict itself. Every cost-model consumer goes through here
    so the version drift is absorbed in one place."""
    ca = compiled.cost_analysis() or {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca

_SHAPE_RE = re.compile(r"=\s*\S*\s*(bf16|f32|f16|s32|s64)\[([\d,]*)\]")
_BF16_CONVERT_RE = re.compile(r"=\s*bf16\[([\d,]+)\]\S*\s+convert\(")


def while_body_lines(hlo_text: str) -> List[str]:
    """Ops belonging to a jitted loop body, identified by the `while/body`
    op_name metadata (robust across XLA computation-naming schemes; fusion
    roots inherit the metadata of the op they fuse)."""
    return [ln for ln in hlo_text.splitlines() if "while/body" in ln]


def shape_elems(line: str) -> Tuple[Optional[str], int]:
    """(dtype, element-count) of the op result on `line`, or (None, 0)."""
    m = _SHAPE_RE.search(line)
    if not m:
        return None, 0
    n = 1
    for d in m.group(2).split(","):
        if d:
            n *= int(d)
    return m.group(1), n


def copies_of_shape(lines: List[str], shape_csv: str) -> List[str]:
    """copy/copy-start ops whose text mentions the given `d0,d1,...` shape."""
    return [ln.strip() for ln in lines
            if shape_csv in ln and ("copy(" in ln or "copy-start" in ln)]


def count_dynamic_update_slices(lines: List[str]) -> int:
    return sum("dynamic-update-slice" in ln for ln in lines)


def jaxpr_loop_report(closed_jaxpr, min_elems: int):
    """Backend-independent loop audit: find scan/while eqns (recursively) and
    report (big_loop_inputs, weight_sized_converts_in_bodies).

    big_loop_inputs: list of "dtype[shape]" strings for loop invars whose
    element count >= min_elems. converts: count of convert_element_type eqns
    inside loop bodies whose INPUT is that large. Compiled-HLO carry checks
    are backend-contaminated (XLA CPU upcasts bf16 dots to f32 and LICM
    hoists the upcasts into the carry); the jaxpr is the traced truth."""
    import numpy as _np

    big_inputs: List[str] = []
    n_converts = 0

    def _sub_jaxprs(eqn):
        for v in eqn.params.values():
            if hasattr(v, "jaxpr"):
                yield v.jaxpr
            elif isinstance(v, (list, tuple)):
                for x in v:
                    if hasattr(x, "jaxpr"):
                        yield x.jaxpr

    def _count_converts(jxp):
        nonlocal n_converts
        for eqn in jxp.eqns:
            if eqn.primitive.name == "convert_element_type":
                a = eqn.invars[0].aval
                if a.shape and int(_np.prod(a.shape)) >= min_elems:
                    n_converts += 1
            for sub in _sub_jaxprs(eqn):
                _count_converts(sub)

    def _walk(jxp):
        for eqn in jxp.eqns:
            if eqn.primitive.name in ("scan", "while"):
                for v in eqn.invars:
                    a = getattr(v, "aval", None)
                    if (a is not None and a.shape
                            and int(_np.prod(a.shape)) >= min_elems):
                        big_inputs.append(f"{a.dtype}{list(a.shape)}")
                for sub in _sub_jaxprs(eqn):
                    _count_converts(sub)
            else:
                for sub in _sub_jaxprs(eqn):
                    _walk(sub)

    _walk(closed_jaxpr.jaxpr)
    return big_inputs, n_converts


def bf16_converts_of_min_size(lines: List[str], min_elems: int,
                              exclude_shape_csv: Optional[str] = None
                              ) -> List[str]:
    """f32->bf16 convert ops at/above `min_elems`, optionally excluding a
    shape (e.g. the KV cache, whose bf16 converts on CPU are f32-legalization
    noise — CPU dots have no native bf16)."""
    out = []
    for ln in lines:
        m = _BF16_CONVERT_RE.search(ln)
        if not m:
            continue
        n = 1
        for d in m.group(1).split(","):
            n *= int(d)
        if n >= min_elems and (exclude_shape_csv is None
                               or exclude_shape_csv not in ln):
            out.append(ln.strip())
    return out

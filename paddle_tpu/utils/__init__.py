"""paddle.utils: misc utilities + the custom-op extension mechanism.

Reference: python/paddle/utils/__init__.py ('deprecated', 'run_check',
'require_version', 'try_import') and utils/cpp_extension/ (runtime-built
user C++ ops, PD_BUILD_OP — framework/custom_operator.cc)."""
from __future__ import annotations

import importlib
import warnings

from . import cpp_extension  # noqa: F401
from .cpp_extension import custom_op, register_custom_op  # noqa: F401
from . import dlpack  # noqa: F401
from . import unique_name  # noqa: F401


def try_import(module_name, err_msg=None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"Failed to import {module_name}")


def require_version(min_version, max_version=None):
    from ..version import full_version

    def parse(v):
        return tuple(int(x) for x in str(v).split(".")[:3] if x.isdigit())

    cur = parse(full_version)
    if parse(min_version) > cur:
        raise Exception(f"requires version >= {min_version}, got {full_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(f"requires version <= {max_version}, got {full_version}")


def deprecated(update_to="", since="", reason="", level=0):
    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            warnings.warn(
                f"{fn.__name__} is deprecated since {since}: {reason}. "
                f"Use {update_to} instead.", DeprecationWarning)
            return fn(*args, **kwargs)

        return wrapper

    return deco


def run_check():
    """Smoke-check the install: one matmul on the default device, one on a
    2-device mesh if available (reference paddle.utils.run_check)."""
    import jax
    import numpy as np

    from .. import to_tensor

    x = to_tensor(np.ones((4, 4), np.float32))
    y = (x @ x).numpy()
    assert (y == 4).all()
    n = jax.device_count()
    print(f"paddle_tpu is installed successfully! {n} device(s) available, "
          f"platform={jax.devices()[0].platform}")

"""Custom op extension.

Reference: paddle/fluid/framework/custom_operator.cc (`PD_BUILD_OP` runtime-
registered C++ ops loaded via utils/cpp_extension) and phi custom kernels
(phi/core/custom_kernel.cc).

TPU-native contract, two tiers:

1. `@custom_op` / `register_custom_op` — the op is a jnp/lax (or Pallas)
   function. It registers into the same kernel registry as built-in ops and
   dispatches through `apply`, so it gets autograd (jax.vjp of the lowering),
   AMP, symbolic capture, and jit tracing for free. This is the phi custom
   *kernel* analogue: new device code on TPU is XLA/Pallas, not CUDA.

2. `load(name, sources)` — compile user C++ with the repo's toolchain and wrap
   exported functions as *host* ops: eagerly via ctypes on numpy buffers, and
   inside jit via `jax.pure_callback`. This is the PD_BUILD_OP analogue for
   code that genuinely must run native host-side (CPU pre/post-processing,
   table lookups). Exported C symbols must follow:
       void NAME(const float* x, float* y, long long n)   # y same shape as x
"""
from __future__ import annotations

import ctypes
from typing import Callable, Dict, Optional

from ..core.dispatch import KERNELS, apply, register_kernel
from ..core.tensor import Tensor
from ..ops._helpers import t_

CUSTOM_OPS: Dict[str, Callable] = {}


def register_custom_op(name: str, forward: Callable, backward: Optional[Callable] = None,
                       differentiable: bool = True):
    """Register `forward(*arrays, **attrs) -> array(s)` as op `name`.

    backward: optional custom vjp `(grads, *inputs) -> input_grads`; without it
    the op differentiates through jax.vjp of `forward` (the common case).
    """
    if name in KERNELS:
        raise ValueError(f"op {name!r} already registered")

    if backward is not None:
        import jax

        @jax.custom_vjp
        def kernel(*arrays, **attrs):
            return forward(*arrays, **attrs)

        def fwd(*arrays, **attrs):
            return forward(*arrays, **attrs), arrays

        def bwd(saved, g):
            out = backward(g, *saved)
            return tuple(out) if isinstance(out, (tuple, list)) else (out,)

        kernel.defvjp(fwd, bwd)
    else:
        kernel = forward

    register_kernel(name)(kernel)

    def op(*args, **attrs):
        tensors = [t_(a) for a in args]
        return apply(name, kernel, tensors, attrs, differentiable=differentiable)

    op.__name__ = name
    CUSTOM_OPS[name] = op
    return op


def custom_op(name: str, backward: Optional[Callable] = None,
              differentiable: bool = True):
    """Decorator form: `@custom_op("my_relu")` over a jnp function."""

    def deco(fn):
        return register_custom_op(name, fn, backward, differentiable)

    return deco


def get_custom_op(name: str):
    return CUSTOM_OPS[name]


class _LoadedModule:
    def __init__(self, ops):
        self.__dict__.update(ops)


def load(name: str, sources, extra_cflags=None, functions=None, verbose=False):
    """Compile user C++ sources and expose `functions` (exported C symbols with
    the elementwise host contract) as paddle ops. Returns a module-like object
    with one callable per function."""
    import numpy as np

    from ..core import native

    lib_path = native.build_library(
        name, sources=list(sources), extra_flags=tuple(extra_cflags or ()))
    lib = ctypes.CDLL(lib_path)

    functions = functions or [name]
    ops = {}
    for fn_name in functions:
        cfunc = getattr(lib, fn_name)
        cfunc.argtypes = [ctypes.POINTER(ctypes.c_float),
                          ctypes.POINTER(ctypes.c_float), ctypes.c_longlong]
        cfunc.restype = None

        def host_call(x, _cfunc=cfunc):
            x = np.ascontiguousarray(np.asarray(x, dtype=np.float32))
            y = np.empty_like(x)
            _cfunc(x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                   y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                   x.size)
            return y

        def kernel(a, _host=host_call):
            import jax

            # host op: runs natively via callback; under jit this becomes a
            # host callback embedded in the XLA program
            return jax.pure_callback(
                _host, jax.ShapeDtypeStruct(a.shape, a.dtype), a,
                vmap_method="sequential")

        op_name = f"{name}.{fn_name}"
        register_kernel(op_name)(kernel)

        def op(x, _kernel=kernel, _op_name=op_name):
            return apply(_op_name, _kernel, [t_(x)], differentiable=False)

        op.__name__ = fn_name
        ops[fn_name] = op

    return _LoadedModule(ops)

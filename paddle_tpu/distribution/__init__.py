"""paddle.distribution equivalent.

Reference: python/paddle/distribution/ (Distribution base, Normal, Uniform,
Beta, Dirichlet, Categorical, Multinomial, ExponentialFamily, Independent,
TransformedDistribution, kl_divergence registry). TPU-native: sampling uses the
framework RNG (jax.random under the hood), densities are jnp/jax.scipy.stats.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as random_mod
from ..core.tensor import Tensor


def _t(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x, dtype=jnp.float32) if not hasattr(x, "dtype") else jnp.asarray(x)


def _key():
    return random_mod.next_key()


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(self.log_prob(value)._data))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        eps = jax.random.normal(_key(), shape, dtype=jnp.float32)
        return Tensor(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = _t(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi)
                      + jnp.log(self.scale) + jnp.zeros(self.batch_shape))

    def cdf(self, value):
        v = _t(value)
        return Tensor(0.5 * (1 + jax.scipy.special.erf(
            (v - self.loc) / (self.scale * math.sqrt(2)))))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _t(low)
        self.high = _t(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    def sample(self, shape=(), seed=0):
        shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(_key(), shape, dtype=jnp.float32)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        v = _t(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low) + jnp.zeros(self.batch_shape))


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        assert (probs is None) != (logits is None), "give exactly one of probs/logits"
        if probs is not None:
            self.probs = _t(probs)
            self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        else:
            self.logits = _t(logits)
            self.probs = jax.nn.sigmoid(self.logits)
        super().__init__(self.probs.shape)

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.bernoulli(_key(), self.probs, shape)
                      .astype(jnp.float32))

    def log_prob(self, value):
        v = _t(value)
        return Tensor(v * jax.nn.log_sigmoid(self.logits)
                      + (1 - v) * jax.nn.log_sigmoid(-self.logits))

    def entropy(self):
        p = self.probs
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _t(logits)
        self._log_norm = jax.nn.log_softmax(self.logits, axis=-1)
        super().__init__(self.logits.shape[:-1])

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.categorical(_key(), self.logits, shape=shape)
                      .astype(jnp.int64))

    def log_prob(self, value):
        v = jnp.asarray(_t(value), jnp.int32)
        return Tensor(jnp.take_along_axis(self._log_norm, v[..., None],
                                          axis=-1)[..., 0])

    def probs(self, value):
        return Tensor(jnp.exp(self.log_prob(value)._data))

    def entropy(self):
        p = jnp.exp(self._log_norm)
        return Tensor(-(p * self._log_norm).sum(-1))


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _t(alpha)
        self.beta = _t(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.beta(_key(), self.alpha, self.beta, shape))

    def _log_beta(self):
        return (jax.scipy.special.gammaln(self.alpha)
                + jax.scipy.special.gammaln(self.beta)
                - jax.scipy.special.gammaln(self.alpha + self.beta))

    def log_prob(self, value):
        v = _t(value)
        return Tensor((self.alpha - 1) * jnp.log(v)
                      + (self.beta - 1) * jnp.log1p(-v) - self._log_beta())

    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    def entropy(self):
        a, b = self.alpha, self.beta
        dg = jax.scipy.special.digamma
        return Tensor(self._log_beta() - (a - 1) * dg(a) - (b - 1) * dg(b)
                      + (a + b - 2) * dg(a + b))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _t(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.dirichlet(_key(), self.concentration, shape))

    def _log_norm(self):
        c = self.concentration
        return (jax.scipy.special.gammaln(c).sum(-1)
                - jax.scipy.special.gammaln(c.sum(-1)))

    def log_prob(self, value):
        v = _t(value)
        c = self.concentration
        return Tensor(((c - 1) * jnp.log(v)).sum(-1) - self._log_norm())

    def entropy(self):
        c = self.concentration
        dg = jax.scipy.special.digamma
        c0 = c.sum(-1)
        return Tensor(self._log_norm() + (c0 - c.shape[-1]) * dg(c0)
                      - ((c - 1) * dg(c)).sum(-1))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _t(probs)
        super().__init__(self.probs.shape[:-1], self.probs.shape[-1:])

    def sample(self, shape=()):
        shape = tuple(shape) + self.batch_shape
        logits = jnp.log(self.probs)
        draws = jax.random.categorical(
            _key(), logits, shape=(self.total_count,) + shape)
        k = self.probs.shape[-1]
        return Tensor(jax.nn.one_hot(draws, k).sum(0))

    def log_prob(self, value):
        v = _t(value)
        logits = jnp.log(self.probs)
        return Tensor(jax.scipy.special.gammaln(self.total_count + 1)
                      - jax.scipy.special.gammaln(v + 1).sum(-1)
                      + (v * logits).sum(-1))


# ---- kl registry (reference python/paddle/distribution/kl.py) ----
_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(
            f"kl_divergence not registered for ({type(p).__name__}, "
            f"{type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Categorical, Categorical)
def _kl_cat_cat(p, q):
    pp = jnp.exp(p._log_norm)
    return Tensor((pp * (p._log_norm - q._log_norm)).sum(-1))


@register_kl(Bernoulli, Bernoulli)
def _kl_bern_bern(p, q):
    pr, qr = p.probs, q.probs
    return Tensor(pr * (jnp.log(pr) - jnp.log(qr))
                  + (1 - pr) * (jnp.log1p(-pr) - jnp.log1p(-qr)))


__all__ = ["Distribution", "Normal", "Uniform", "Bernoulli", "Categorical",
           "Beta", "Dirichlet", "Multinomial", "kl_divergence", "register_kl"]

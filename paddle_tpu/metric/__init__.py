"""paddle.metric equivalent. Reference: python/paddle/metric/metrics.py."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred_np = np.asarray(pred._data if isinstance(pred, Tensor) else pred)
        label_np = np.asarray(label._data if isinstance(label, Tensor) else label)
        if label_np.ndim == pred_np.ndim:
            label_np = label_np.squeeze(-1)
        top = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        correct = top == label_np[..., None]
        return correct

    def update(self, correct, *args):
        if isinstance(correct, Tensor):
            correct = np.asarray(correct._data)
        n = correct.shape[0] if correct.ndim else 1
        accs = []
        for i, k in enumerate(self.topk):
            c = correct[..., :k].any(-1).sum()
            self.total[i] += float(c)
            self.count[i] += n
            accs.append(float(c) / n)
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name="precision"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.asarray(preds._data if isinstance(preds, Tensor) else preds)
        labels = np.asarray(labels._data if isinstance(labels, Tensor) else labels)
        pred_pos = (preds.round() if preds.dtype.kind == "f" else preds) == 1
        self.tp += int(((pred_pos) & (labels == 1)).sum())
        self.fp += int(((pred_pos) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.asarray(preds._data if isinstance(preds, Tensor) else preds)
        labels = np.asarray(labels._data if isinstance(labels, Tensor) else labels)
        pred_pos = (preds.round() if preds.dtype.kind == "f" else preds) == 1
        self.tp += int((pred_pos & (labels == 1)).sum())
        self.fn += int((~pred_pos & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds._data if isinstance(preds, Tensor) else preds)
        labels = np.asarray(labels._data if isinstance(labels, Tensor) else labels).reshape(-1)
        pos_prob = preds[:, 1] if preds.ndim == 2 and preds.shape[1] == 2 else preds.reshape(-1)
        bins = np.minimum((pos_prob * self.num_thresholds).astype(int), self.num_thresholds)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return auc / denom if denom else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1):
    import jax.numpy as jnp

    pred = np.asarray(input._data)
    lab = np.asarray(label._data)
    if lab.ndim == pred.ndim:
        lab = lab.squeeze(-1)
    top = np.argsort(-pred, axis=-1)[..., :k]
    correct = (top == lab[..., None]).any(-1).mean()
    return Tensor(jnp.asarray(np.float32(correct)))

"""paddle.inference equivalent: Config + Predictor over jit.save artifacts.

Reference: paddle/fluid/inference/api/analysis_predictor.h:93 — AnalysisPredictor
loads a ProgramDesc, runs an IR pass pipeline, executes via NaiveExecutor with
zero-copy in/out tensors. TPU-native: the artifact is serialized StableHLO
(already optimized by XLA at export; the pass pipeline role), execution is the
compiled XLA program; handles expose the same copy_from_cpu/copy_to_cpu API.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class Config:
    """paddle.inference.Config parity (api/paddle_analysis_config.h)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # accept either the artifact prefix or the explicit .pdmodel path
        if prog_file and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[:-len(".pdmodel")]
        self.model_path = prog_file
        self.params_file = params_file
        self._device = "tpu"
        self._device_id = 0

    def set_prog_file(self, path: str):
        self.model_path = path[:-len(".pdmodel")] if path.endswith(".pdmodel") \
            else path

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device, self._device_id = "gpu", device_id

    def enable_tpu(self, device_id=0):
        self._device, self._device_id = "tpu", device_id

    def disable_gpu(self):
        self._device = "cpu"

    def enable_memory_optim(self):
        pass  # XLA already optimizes the exported program

    def switch_ir_optim(self, enable=True):
        pass

    def prog_file(self):
        return self.model_path


class _IOHandle:
    """Zero-copy-style tensor handle (reference ZeroCopyTensor)."""

    def __init__(self, name: str):
        self.name = name
        self._array: Optional[np.ndarray] = None

    def copy_from_cpu(self, data: np.ndarray):
        self._array = np.asarray(data)

    def copy_to_cpu(self) -> np.ndarray:
        assert self._array is not None, f"output {self.name!r}: run() first"
        return np.asarray(self._array)

    def reshape(self, shape):
        if self._array is not None:
            self._array = self._array.reshape(shape)

    @property
    def shape(self):
        return None if self._array is None else tuple(self._array.shape)


class Predictor:
    def __init__(self, config: Config):
        from ..jit import load as jit_load

        assert config.model_path, "Config needs the model path prefix"
        self._layer = jit_load(config.model_path)
        n_in = len(self._layer._input_specs)
        self._input_names = [f"input_{i}" for i in range(n_in)]
        self._inputs: Dict[str, _IOHandle] = {
            n: _IOHandle(n) for n in self._input_names}
        # output arity is known from the exported program's signature, so
        # GetOutputNames works BEFORE the first Run (reference semantics)
        n_out = len(getattr(self._layer._exported, "out_avals", ())) or 1
        self._outputs: List[_IOHandle] = [
            _IOHandle(f"output_{i}") for i in range(n_out)]

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> _IOHandle:
        return self._inputs[name]

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """AnalysisPredictor::Run: execute the loaded program. Either feed
        through handles (copy_from_cpu) or pass arrays directly."""
        if inputs is not None:
            if len(inputs) != len(self._input_names):
                raise ValueError(
                    f"run() got {len(inputs)} inputs; the model has "
                    f"{len(self._input_names)} ({self._input_names})")
            for n, a in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(a)
        args = [self._inputs[n]._array for n in self._input_names]
        assert all(a is not None for a in args), \
            "feed every input via copy_from_cpu before run()"
        out = self._layer(*args)
        outs = out if isinstance(out, (list, tuple)) else [out]
        # populate the PERSISTENT handles (ZeroCopyTensor semantics: a handle
        # fetched before Run() must see the results), growing if the program
        # returned more outputs than the signature promised
        while len(self._outputs) < len(outs):
            self._outputs.append(_IOHandle(f"output_{len(self._outputs)}"))
        del self._outputs[len(outs):]
        for h, o in zip(self._outputs, outs):
            h.copy_from_cpu(o.numpy())
        if inputs is not None:
            return [h.copy_to_cpu() for h in self._outputs]

    def get_output_names(self) -> List[str]:
        return [h.name for h in self._outputs]

    def get_output_handle(self, name: str) -> _IOHandle:
        for h in self._outputs:
            if h.name == name:
                return h
        raise KeyError(name)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


__all__ = ["Config", "Predictor", "create_predictor"]


# ---- parity enums/utilities (reference paddle/inference/__init__.py over
# pybind paddle_infer types) ----
import enum as _enum


class DataType(_enum.Enum):
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6


class PlaceType(_enum.Enum):
    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    NPU = 3
    TPU = 10


class PrecisionType(_enum.Enum):
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


class Tensor:
    """Zero-copy handle parity (reference paddle_infer.Tensor): wraps the
    predictor's named input/output buffer."""

    def __init__(self, name, store):
        self._name = name
        self._store = store

    def name(self):
        return self._name

    def copy_from_cpu(self, arr):
        import numpy as _np

        self._store[self._name] = _np.asarray(arr)

    def copy_to_cpu(self):
        import numpy as _np

        return _np.asarray(self._store[self._name])

    def shape(self):
        return list(self._store[self._name].shape)


def get_version():
    from ..version import full_version

    return f"paddle_tpu {full_version} (XLA inference path)"


def get_trt_compile_version():
    return (0, 0, 0)  # no TensorRT in a TPU build


def get_trt_runtime_version():
    return (0, 0, 0)


def get_num_bytes_of_data_type(dtype):
    sizes = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
             DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
             DataType.BFLOAT16: 2}
    return sizes[dtype]


class PredictorPool:
    """N predictors over one config (reference paddle_infer.PredictorPool);
    XLA executables are thread-compatible so these share the loaded program."""

    def __init__(self, config, size=1):
        self._predictors = [Predictor(config) for _ in range(size)]

    def retrive(self, idx):  # reference spells it this way
        return self._predictors[idx]

    retrieve = retrive

"""paddle.signal: frame / overlap_add / stft / istft.

Reference: python/paddle/signal.py (stft at :181, istft at :326, built on
frame/overlap_add ops). TPU-native: framing is a gather, FFT is XLA's native
fft — the whole STFT is one fused program under jit."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .core.dispatch import apply
from .core.tensor import Tensor
from .ops._helpers import t_


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice overlapping frames of the last (or first) axis."""

    def kernel(a, frame_length, hop_length, axis):
        if axis in (-1, a.ndim - 1):
            n = a.shape[-1]
            n_frames = 1 + (n - frame_length) // hop_length
            idx = (jnp.arange(frame_length)[None, :]
                   + hop_length * jnp.arange(n_frames)[:, None])
            out = a[..., idx]          # [..., n_frames, frame_length]
            return jnp.swapaxes(out, -1, -2)  # [..., frame_length, n_frames]
        # axis == 0: frames lead
        n = a.shape[0]
        n_frames = 1 + (n - frame_length) // hop_length
        idx = (jnp.arange(frame_length)[None, :]
               + hop_length * jnp.arange(n_frames)[:, None])
        return a[idx]                  # [n_frames, frame_length, ...]

    return apply("frame", kernel, [t_(x)],
                 {"frame_length": frame_length, "hop_length": hop_length,
                  "axis": axis})


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame: add overlapping frames back together."""

    def kernel(a, hop_length, axis):
        if axis in (-1, a.ndim - 1):
            fl, n_frames = a.shape[-2], a.shape[-1]
            out_len = (n_frames - 1) * hop_length + fl
            out = jnp.zeros(a.shape[:-2] + (out_len,), a.dtype)
            for f in range(n_frames):
                out = out.at[..., f * hop_length:f * hop_length + fl].add(
                    a[..., :, f])
            return out
        fl, n_frames = a.shape[1], a.shape[0]
        out_len = (n_frames - 1) * hop_length + fl
        out = jnp.zeros((out_len,) + a.shape[2:], a.dtype)
        for f in range(n_frames):
            out = out.at[f * hop_length:f * hop_length + fl].add(a[f])
        return out

    return apply("overlap_add", kernel, [t_(x)],
                 {"hop_length": hop_length, "axis": axis})


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform (reference signal.py:181 semantics:
    output [..., n_fft//2+1 (or n_fft), n_frames], complex)."""
    x = t_(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        window = t_(window)

    def kernel(a, *maybe_win):
        if center:
            pad = n_fft // 2
            a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(pad, pad)],
                        mode=pad_mode)
        n = a.shape[-1]
        n_frames = 1 + (n - n_fft) // hop_length
        idx = (jnp.arange(n_fft)[None, :]
               + hop_length * jnp.arange(n_frames)[:, None])
        frames = a[..., idx]                      # [..., n_frames, n_fft]
        if maybe_win:
            w = maybe_win[0]
            if win_length < n_fft:               # center-pad window
                lp = (n_fft - win_length) // 2
                w = jnp.pad(w, (lp, n_fft - win_length - lp))
            frames = frames * w
        if onesided:
            spec = jnp.fft.rfft(frames, n=n_fft, axis=-1)
        else:
            spec = jnp.fft.fft(frames, n=n_fft, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(float(n_fft))
        return jnp.swapaxes(spec, -1, -2)        # [..., freq, n_frames]

    args = [x] + ([window] if window is not None else [])
    return apply("stft", kernel, args)


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Inverse STFT with window-envelope normalization (reference :326)."""
    x = t_(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        window = t_(window)

    def kernel(spec, *maybe_win):
        frames_f = jnp.swapaxes(spec, -1, -2)    # [..., n_frames, freq]
        if normalized:
            frames_f = frames_f * jnp.sqrt(float(n_fft))
        if onesided:
            frames = jnp.fft.irfft(frames_f, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(frames_f, n=n_fft, axis=-1).real
        if maybe_win:
            w = maybe_win[0]
            if win_length < n_fft:
                lp = (n_fft - win_length) // 2
                w = jnp.pad(w, (lp, n_fft - win_length - lp))
        else:
            w = jnp.ones((n_fft,), frames.dtype)
        frames = frames * w
        n_frames = frames.shape[-2]
        out_len = (n_frames - 1) * hop_length + n_fft
        out = jnp.zeros(frames.shape[:-2] + (out_len,), frames.dtype)
        env = jnp.zeros((out_len,), frames.dtype)
        for f in range(n_frames):
            sl = slice(f * hop_length, f * hop_length + n_fft)
            out = out.at[..., sl].add(frames[..., f, :])
            env = env.at[sl].add(w * w)
        out = out / jnp.maximum(env, 1e-11)
        if center:
            out = out[..., n_fft // 2: out.shape[-1] - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out

    args = [x] + ([window] if window is not None else [])
    return apply("istft", kernel, args)

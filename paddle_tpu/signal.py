"""paddle.signal: frame / overlap_add / stft / istft.

Reference: python/paddle/signal.py (stft at :181, istft at :326, built on
frame/overlap_add ops). TPU-native: framing is a gather, FFT is XLA's native
fft — the whole STFT is one fused program under jit."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .core.dispatch import apply
from .core.tensor import Tensor
from .ops._helpers import t_


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice overlapping frames of the last (or first) axis."""
    if axis not in (0, -1):
        raise ValueError(f"frame supports axis 0 or -1 (reference contract), "
                         f"got {axis}")

    def kernel(a, frame_length, hop_length, axis):
        if axis in (-1, a.ndim - 1):
            n = a.shape[-1]
            n_frames = 1 + (n - frame_length) // hop_length
            idx = (jnp.arange(frame_length)[None, :]
                   + hop_length * jnp.arange(n_frames)[:, None])
            out = a[..., idx]          # [..., n_frames, frame_length]
            return jnp.swapaxes(out, -1, -2)  # [..., frame_length, n_frames]
        # axis == 0: frames lead
        n = a.shape[0]
        n_frames = 1 + (n - frame_length) // hop_length
        idx = (jnp.arange(frame_length)[None, :]
               + hop_length * jnp.arange(n_frames)[:, None])
        return a[idx]                  # [n_frames, frame_length, ...]

    return apply("frame", kernel, [t_(x)],
                 {"frame_length": frame_length, "hop_length": hop_length,
                  "axis": axis})


def _scatter_add_frames(frames, hop_length):
    """[..., n_frames, frame_length] -> [..., out_len] in ONE scatter-add."""
    n_frames, fl = frames.shape[-2], frames.shape[-1]
    out_len = (n_frames - 1) * hop_length + fl
    idx = (hop_length * jnp.arange(n_frames)[:, None]
           + jnp.arange(fl)[None, :])                  # [n_frames, fl]
    out = jnp.zeros(frames.shape[:-2] + (out_len,), frames.dtype)
    return out.at[..., idx].add(frames)


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame: add overlapping frames back together."""
    if axis not in (0, -1):
        raise ValueError(f"overlap_add supports axis 0 or -1, got {axis}")

    def kernel(a, hop_length, axis):
        if axis in (-1, a.ndim - 1):
            # [..., frame_length, n_frames] -> [..., n_frames, frame_length]
            return _scatter_add_frames(jnp.swapaxes(a, -1, -2), hop_length)
        # axis 0: [n_frames, frame_length, ...] -> [..., n_frames, frame_length]
        moved = jnp.moveaxis(jnp.moveaxis(a, 0, -1), 0, -1)
        out = _scatter_add_frames(moved, hop_length)
        return jnp.moveaxis(out, -1, 0)

    return apply("overlap_add", kernel, [t_(x)],
                 {"hop_length": hop_length, "axis": axis})


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform (reference signal.py:181 semantics:
    output [..., n_fft//2+1 (or n_fft), n_frames], complex)."""
    x = t_(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        window = t_(window)

    def kernel(a, *maybe_win):
        if center:
            pad = n_fft // 2
            a = jnp.pad(a, [(0, 0)] * (a.ndim - 1) + [(pad, pad)],
                        mode=pad_mode)
        n = a.shape[-1]
        n_frames = 1 + (n - n_fft) // hop_length
        idx = (jnp.arange(n_fft)[None, :]
               + hop_length * jnp.arange(n_frames)[:, None])
        frames = a[..., idx]                      # [..., n_frames, n_fft]
        if maybe_win:
            w = maybe_win[0]
            if win_length < n_fft:               # center-pad window
                lp = (n_fft - win_length) // 2
                w = jnp.pad(w, (lp, n_fft - win_length - lp))
            frames = frames * w
        if onesided:
            spec = jnp.fft.rfft(frames, n=n_fft, axis=-1)
        else:
            spec = jnp.fft.fft(frames, n=n_fft, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(float(n_fft))
        return jnp.swapaxes(spec, -1, -2)        # [..., freq, n_frames]

    args = [x] + ([window] if window is not None else [])
    return apply("stft", kernel, args)


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Inverse STFT with window-envelope normalization (reference :326)."""
    x = t_(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is not None:
        window = t_(window)

    def kernel(spec, *maybe_win):
        frames_f = jnp.swapaxes(spec, -1, -2)    # [..., n_frames, freq]
        if normalized:
            frames_f = frames_f * jnp.sqrt(float(n_fft))
        if onesided:
            frames = jnp.fft.irfft(frames_f, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(frames_f, n=n_fft, axis=-1)
            if not return_complex:
                frames = frames.real
        if maybe_win:
            w = maybe_win[0]
            if win_length < n_fft:
                lp = (n_fft - win_length) // 2
                w = jnp.pad(w, (lp, n_fft - win_length - lp))
        else:
            w = jnp.ones((n_fft,), jnp.float32)
        frames = frames * w
        n_frames = frames.shape[-2]
        out = _scatter_add_frames(frames, hop_length)   # one scatter-add
        env = _scatter_add_frames(
            jnp.broadcast_to(w * w, (n_frames, n_fft)), hop_length)
        out = out / jnp.maximum(env, 1e-11)
        if center:
            out = out[..., n_fft // 2: out.shape[-1] - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out

    args = [x] + ([window] if window is not None else [])
    return apply("istft", kernel, args)

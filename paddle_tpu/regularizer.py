"""paddle.regularizer: L1Decay / L2Decay (reference python/paddle/regularizer.py).

L2 folds into the optimizer rules' weight_decay (like the reference's fusion
into the op when possible); L1 applies as a gradient penalty hook."""
from __future__ import annotations


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)
        self._coeff = self.coeff  # reference attribute name

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self.coeff})"


class L2Decay(WeightDecayRegularizer):
    """Optimizers read `._coeff` and apply decoupled/coupled L2 per their rule."""


class L1Decay(WeightDecayRegularizer):
    """L1 penalty: grad += coeff * sign(param). Applied by Optimizer.step when a
    parameter carries this regularizer or when passed as the optimizer's
    weight_decay (reference appends the l1_decay op)."""

    _is_l1 = True

    def apply(self, param, grad_data):
        import jax.numpy as jnp

        return grad_data + self.coeff * jnp.sign(param._data)

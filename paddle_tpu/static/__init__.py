"""paddle.static equivalent (round-1 slice).

Reference: python/paddle/static + fluid/framework.py Program/Block + executor.py:619.
TPU-native plan (SURVEY.md §7 step 4): a Program IR whose Executor *traces the whole program to
one XLA computation* — the InterpreterCore instruction list becomes a jitted function. The
round-1 slice gives the user-facing Program/data/Executor API running on the traced path; the
protobuf-style IR + passes land next.
"""
from __future__ import annotations

from ..core.tensor import Tensor
from ..core.place import CPUPlace, TPUPlace  # noqa: F401

from . import nn  # noqa: F401


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


class Program:
    """Placeholder IR container — filled by the static-graph milestone."""

    def __init__(self):
        self.ops = []
        self.vars = {}

    def global_block(self):
        return self

    def clone(self, for_test=False):
        import copy

        return copy.copy(self)


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


class Executor:
    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        raise NotImplementedError(
            "static Executor lands with the Program IR milestone; use dygraph or "
            "paddle_tpu.jit.to_static (whole-program XLA tracing) meanwhile")


def program_guard(main_program, startup_program=None):
    import contextlib

    @contextlib.contextmanager
    def guard():
        yield

    return guard()

"""paddle.static equivalent: Program IR + Executor over one jitted XLA computation.

Reference: python/paddle/static + fluid/framework.py (Program/Block/Operator,
executor.py:619). See framework.py / executor.py here for the TPU-native design notes
(ops recorded at the dispatch seam; InterpreterCore ≙ jit cache; backward appended by
AD at lowering).
"""
from __future__ import annotations

from ..core.place import CPUPlace, TPUPlace  # noqa: F401
from .framework import (  # noqa: F401
    Block, OpDesc, Program, Variable, data, default_main_program,
    default_startup_program, program_guard,
)
from .executor import (  # noqa: F401
    BuildStrategy, CompiledProgram, ExecutionStrategy, Executor, Scope,
    global_scope, scope_guard,
)
from . import nn  # noqa: F401
from .io import (  # noqa: F401
    deserialize_persistables, deserialize_program, load, load_from_file,
    load_inference_model, load_program_state, normalize_program, save,
    save_inference_model, save_to_file, serialize_persistables,
    serialize_program, set_program_state,
)
from .misc import (  # noqa: F401
    ExponentialMovingAverage, IpuCompiledProgram, IpuStrategy, Print,
    WeightNormParamAttr, accuracy, auc, cpu_places, create_global_var,
    create_parameter, cuda_places, device_guard, gradients, ipu_shard_guard,
    mlu_places, npu_places, py_func, xpu_places,
)

# ParallelExecutor parity: multi-device execution happens through pjit/GSPMD
# in this build; the class accepts the reference surface and runs the program
# through the (single fused computation) Executor.
class ParallelExecutor:
    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None):
        self._program = main_program or default_main_program()
        self._exe = Executor()

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        return self._exe.run(self._program, feed=feed or feed_dict,
                             fetch_list=fetch_list, return_numpy=return_numpy)


class InputSpec:
    """Shape/dtype declaration for jit.to_static (paddle.static.InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def append_backward(loss, parameter_list=None, no_grad_set=None):
    """Mark `loss` for training; grads materialize inside the Executor lowering
    (jax.grad over the replayed program) rather than as explicit grad OpDescs.
    Pair with Optimizer.minimize(loss), which installs the optimizer rule."""
    prog = loss.block.program
    if prog._train is None:
        prog._train = (loss.name, None)
    return []


def name_scope(prefix=None):
    import contextlib

    return contextlib.nullcontext()

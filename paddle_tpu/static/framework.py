"""Static-graph IR: Program / Block / OpDesc / Variable + op capture.

Reference: the ProgramDesc protobuf IR (paddle/fluid/framework/framework.proto,
program_desc.h, python/paddle/fluid/framework.py Program/Block/Operator) built by the
Python API appending OpDescs, then executed by Executor/InterpreterCore
(SURVEY.md §3.4).

TPU-native redesign: the IR records (op name, kernel, inputs, attrs, outputs) at the
SAME dispatch point every eager op goes through (core/dispatch.apply) — when an op sees
a symbolic Variable input it appends an OpDesc instead of executing. The Executor then
lowers the whole op list into ONE jitted XLA computation (the InterpreterCore
instruction list becomes a single compiled program; XLA does the stream analysis,
scheduling and memory planning the reference's interpreter does by hand). Concrete
Tensors touched by captured ops (parameters, constants) are recorded as program
captures; trainable Parameters become differentiable leaves of the lowered step.

Shape inference (the infermeta analogue) is jax.eval_shape over the recorded kernel —
exact by construction — and degrades to unknown (-1) dims when inputs carry dynamic
batch dims; unknown shapes resolve at first Executor.run when real feeds arrive.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

import jax
import numpy as np

from ..core import dtype as dtypes
from ..core import dispatch as _dispatch
from ..core.tensor import Tensor

_state = threading.local()


def _cur_program() -> Optional["Program"]:
    return getattr(_state, "program", None)


def _cur_startup() -> Optional["Program"]:
    return getattr(_state, "startup", None)


class Variable(Tensor):
    """Symbolic tensor living in a Block (VarDesc + Variable in the reference)."""

    is_symbolic = True

    def __init__(self, block, name, shape, dtype, stop_gradient=True, persistable=False):
        # deliberately NOT calling Tensor.__init__: there is no concrete data
        self.block = block
        self.name = name
        self._shape = [(-1 if s is None else int(s)) for s in shape]
        self._dtype = dtypes.convert_dtype(dtype)
        self._stop_gradient = stop_gradient
        self.persistable = persistable
        self._grad = None
        self._node = None
        self._out_index = 0
        self._hooks = []
        self._retain_grads = False

    @property
    def _data(self):
        if all(d >= 0 for d in self._shape):
            return jax.ShapeDtypeStruct(tuple(self._shape), self._dtype)
        raise RuntimeError(
            f"symbolic Variable '{self.name}' with dynamic shape {self._shape} has no "
            "concrete value; run it through paddle.static.Executor")

    @_data.setter
    def _data(self, v):  # pragma: no cover - assignment is a usage error
        raise RuntimeError(f"cannot assign data to symbolic Variable '{self.name}'")

    @property
    def shape(self):
        return list(self._shape)

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dtype(self):
        return self._dtype

    def numpy(self):
        raise RuntimeError(
            f"Variable '{self.name}' is symbolic; fetch it via Executor.run")

    def __repr__(self):
        return f"Variable(name={self.name}, shape={self._shape}, dtype={self._dtype})"


class OpDesc:
    """One recorded op: the kernel IS the lowering (phi-kernel handle analogue)."""

    __slots__ = ("type", "kernel", "input_names", "output_names", "attrs")

    def __init__(self, type: str, kernel: Callable, input_names: List[str],
                 output_names: List[str], attrs: Dict):
        self.type = type
        self.kernel = kernel
        self.input_names = input_names
        self.output_names = output_names
        self.attrs = attrs

    def __repr__(self):
        return (f"{', '.join(self.output_names)} = {self.type}"
                f"({', '.join(self.input_names)})")


class Block:
    def __init__(self, program, idx=0):
        self.program = program
        self.idx = idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[OpDesc] = []

    def create_var(self, name=None, shape=(), dtype="float32", stop_gradient=True,
                   persistable=False):
        name = name or self.program._unique_name("tmp")
        v = Variable(self, name, shape, dtype, stop_gradient, persistable)
        self.vars[name] = v
        return v

    def var(self, name):
        return self.vars[name]

    def __repr__(self):
        return "\n".join(repr(op) for op in self.ops)


class Program:
    """The IR container (ProgramDesc analogue). One global block in round 1 —
    control flow lowers to lax.cond/scan inside kernels, not to sub-blocks."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.random_seed = 0
        self._counter = 0
        self._captures: Dict[str, Tensor] = {}   # concrete tensors used by ops
        self._capture_ids: Dict[int, str] = {}
        self._train = None                        # (loss_name, optimizer)
        self._version = 0                         # bumped per recorded op
        self._opt_state = {}                      # param name -> optimizer state

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[-1]

    def _unique_name(self, stem):
        self._counter += 1
        return f"{stem}_{self._counter}"

    def capture(self, t: Tensor) -> str:
        """Register a concrete Tensor consumed by a recorded op; returns its name."""
        key = id(t)
        if key in self._capture_ids:
            return self._capture_ids[key]
        trainable = (not t.stop_gradient)
        stem = "param" if trainable else "const"
        name = self._unique_name(f"@{stem}")
        self._captures[name] = t
        self._capture_ids[key] = name
        return name

    def parameters(self):
        return {n: t for n, t in self._captures.items() if not t.stop_gradient}

    def list_vars(self):
        return list(self.global_block().vars.values())

    def clone(self, for_test=False):
        import copy

        p = copy.copy(self)
        p.blocks = []
        for b in self.blocks:
            nb = Block(p, b.idx)
            nb.vars = dict(b.vars)
            nb.ops = list(b.ops)
            p.blocks.append(nb)
        p._captures = dict(self._captures)
        p._capture_ids = dict(self._capture_ids)
        p._opt_state = {}
        if for_test:
            p._train = None
        return p

    def to_string(self, throw_on_error=False, with_details=False):
        return "\n".join(repr(b) for b in self.blocks)

    __repr__ = to_string


_default_main = Program()
_default_startup = Program()


def default_main_program() -> Program:
    return getattr(_state, "default_main", None) or _default_main


def default_startup_program() -> Program:
    return getattr(_state, "default_startup", None) or _default_startup


class program_guard:
    """`with program_guard(main, startup):` — ops record into `main`."""

    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        self._prev = (_cur_program(), _cur_startup(),
                      getattr(_state, "default_main", None),
                      getattr(_state, "default_startup", None))
        _state.program = self.main
        _state.startup = self.startup
        _state.default_main = self.main
        _state.default_startup = self.startup or _default_startup
        return self

    def __exit__(self, *exc):
        (_state.program, _state.startup,
         _state.default_main, _state.default_startup) = self._prev
        return False


def data(name, shape, dtype="float32", lod_level=0) -> Variable:
    """Declare a feed Variable in the current (or default) main program."""
    prog = _cur_program() or default_main_program()
    block = prog.current_block()
    if name in block.vars:
        raise ValueError(f"feed var '{name}' already exists")
    v = Variable(block, name, shape, dtype, stop_gradient=True)
    block.vars[name] = v
    if not hasattr(prog, "_feed_vars"):
        prog._feed_vars = []
    prog._feed_vars.append(v)  # consumed by trainer.py's dataset feed mapping
    return v


# ---- the dispatch hook: record instead of execute -------------------------------

def _infer_meta(kernel, in_vars, attrs):
    """eval_shape when every input shape is static; unknown otherwise."""
    known = all(
        all(d >= 0 for d in (v.shape if isinstance(v, Variable) else v.shape))
        for v in in_vars)
    if not known:
        return None
    ins = [jax.ShapeDtypeStruct(tuple(v.shape), v.dtype if isinstance(v, Variable)
                                else v._data.dtype) for v in in_vars]
    try:
        out = jax.eval_shape(lambda *a: kernel(*a, **attrs), *ins)
    except Exception:
        return None
    return out


def _record_op(name, kernel, tensor_args, attrs, differentiable):
    prog = None
    for t in tensor_args:
        if isinstance(t, Variable):
            prog = t.block.program
            break
    assert prog is not None
    block = prog.current_block()

    in_names = []
    for t in tensor_args:
        if isinstance(t, Variable):
            in_names.append(t.name)
        else:
            in_names.append(prog.capture(t))

    meta = _infer_meta(kernel, tensor_args, attrs)
    if meta is not None:
        multi = isinstance(meta, (tuple, list))
        metas = [(m.shape, m.dtype) for m in (meta if multi else [meta])]
    else:
        # dynamic input dims: probe twice (unknown dims -> 1 then 2); output dims
        # that differ between probes depend on the dynamic dims and stay -1
        def probe_with(fill):
            ins = [jax.ShapeDtypeStruct(
                tuple(fill if d < 0 else d for d in v.shape),
                v.dtype if isinstance(v, Variable) else v._data.dtype)
                for v in tensor_args]
            return jax.eval_shape(lambda *a: kernel(*a, **attrs), *ins)

        try:
            m1, m2 = probe_with(1), probe_with(2)
        except Exception as e:
            raise RuntimeError(
                f"cannot record op '{name}' with dynamic input shapes: shape "
                f"probe failed ({e}); declare static shapes in static.data") from e
        multi = isinstance(m1, (tuple, list))
        pairs = zip(m1 if multi else [m1], m2 if multi else [m2])
        metas = [
            (tuple(a if a == b else -1 for a, b in zip(s1.shape, s2.shape)), s1.dtype)
            for s1, s2 in pairs]

    # grads can flow to any float output when any differentiable input requires grad
    any_grad = differentiable and any(
        not t.stop_gradient for t in tensor_args)

    outs = []
    for shape, dt in metas:
        v = block.create_var(prog._unique_name(name), shape, dt,
                             stop_gradient=not any_grad)
        outs.append(v)
    block.ops.append(OpDesc(name, lambda *a, _k=kernel, _at=dict(attrs): _k(*a, **_at),
                            in_names, [o.name for o in outs], dict(attrs)))
    prog._version += 1
    return tuple(outs) if multi else outs[0]


_dispatch.set_symbolic_handler(_record_op)

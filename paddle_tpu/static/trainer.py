"""Trainer / DeviceWorker stack: Executor.train_from_dataset backing.

Reference (#12): the fleet-run training loop — `TrainerBase/MultiTrainer/
DistMultiTrainer` (paddle/fluid/framework/trainer.h:59-336) own
`DeviceWorker/HogwildWorker` threads (device_worker.h:154,249), each thread
pulling batches from its C++ `DataFeed` shard and executing the program; the
Python side (`python/paddle/fluid/executor.py` train_from_dataset) just picks
a trainer from the strategy and launches it.

TPU-native split: batch PARSING is already multithreaded inside the native
feed (core/native/data_feed.cc); the HogwildWorker thread pool here overlaps
host-side batch assembly (numpy padding, feed-dict building) with device
execution, and the device step itself is the Executor's single fused XLA
computation — one chip consumes one instruction stream, so "threads racing
ops onto the device" (the CUDA Hogwild picture) collapses into a bounded
prefetch queue in front of a serialized step loop. Sparse (lod) slots are fed
as dense-padded [batch, maxlen] int64 plus a `<name>.lens` length vector when
the program declares it — static shapes are what XLA wants; maxlen is bucketed
to powers of two to bound recompilation.
"""
from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional

import numpy as np

__all__ = ["TrainerFactory", "MultiTrainer", "DistMultiTrainer", "HogwildWorker"]


def _bucket(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b


def _assemble_feed(batch: Dict[str, object], feed_names: List[str]) -> Dict[str, np.ndarray]:
    """Dataset batch -> feed dict; sparse (vals, lod) slots become padded ids
    (+ optional .lens var). Unreferenced slots are dropped."""
    out = {}
    for name, val in batch.items():
        if isinstance(val, tuple):
            vals, offs = val
            rows = len(offs) - 1
            widths = np.diff(offs)
            maxw = _bucket(int(widths.max())) if rows and widths.max() > 0 else 1
            dense = np.zeros((rows, maxw), np.int64)
            for r in range(rows):
                w = int(widths[r])
                dense[r, :w] = vals[offs[r]:offs[r + 1]].astype(np.int64)
            if name in feed_names:
                out[name] = dense
            lens_name = name + ".lens"
            if lens_name in feed_names:
                out[lens_name] = widths.astype(np.int64)
        elif name in feed_names:
            out[name] = val
    return out


class DeviceWorker:
    def __init__(self, executor, program, fetch_list, fetch_info, print_period, debug):
        self.exe = executor
        self.program = program
        self.fetch_list = fetch_list or []
        self.fetch_info = fetch_info or [str(f) for f in self.fetch_list]
        self.print_period = print_period
        self.debug = debug
        self.steps = 0

    def run_step(self, feed):
        fetched = self.exe.run(self.program, feed=feed, fetch_list=self.fetch_list)
        self.steps += 1
        if self.fetch_list and self.print_period and self.steps % self.print_period == 0:
            msg = ", ".join(f"{i}: {np.asarray(v).mean():.6f}"
                            for i, v in zip(self.fetch_info, fetched))
            print(f"[step {self.steps}] {msg}", flush=True)
        return fetched


class HogwildWorker(DeviceWorker):
    """Plain feed->run loop (reference HogwildWorker::TrainFiles,
    device_worker.h:249). Lock-free param updates have no TPU analogue — the
    fused step owns the weights — so 'hogwild' here means workers assemble
    batches concurrently while steps run in arrival order."""


class TrainerBase:
    worker_cls = HogwildWorker

    def __init__(self, executor, program, dataset, fetch_list=None, fetch_info=None,
                 print_period=100, debug=False, thread_num=None):
        self.dataset = dataset
        self.thread_num = max(1, thread_num or getattr(dataset, "_thread_num", 1))
        self.worker = self.worker_cls(executor, program, fetch_list, fetch_info,
                                      print_period, debug)
        self._feed_names = [v.name for v in getattr(program, "_feed_vars", [])] or None

    def _feed_name_list(self, batch):
        if self._feed_names is not None:
            return self._feed_names
        # no declared feeds recorded: accept every dense slot + ids of sparse
        return [n for n in batch] + [n + ".lens" for n in batch]

    def run(self):
        """Bounded prefetch queue: thread_num assembly workers (host) ahead of
        the device step loop. Returns the last fetch values. Worker exceptions
        are re-raised here — a truncated epoch must not look like a clean one."""
        q: "queue.Queue" = queue.Queue(maxsize=4 * self.thread_num)
        stop = object()
        it = iter(self.dataset)
        it_lock = threading.Lock()

        def produce():
            try:
                while True:
                    with it_lock:
                        batch = next(it, stop)
                    if batch is stop:
                        break
                    q.put(_assemble_feed(batch, self._feed_name_list(batch)))
            except BaseException as e:  # propagate to the consumer
                q.put(e)
            finally:
                q.put(stop)

        threads = [threading.Thread(target=produce, daemon=True)
                   for _ in range(self.thread_num)]
        for t in threads:
            t.start()
        last = None
        stops = 0
        error = None
        while stops < len(threads):
            item = q.get()
            if item is stop:
                stops += 1
                continue
            if isinstance(item, BaseException):
                error = error or item
                continue
            if error is None:
                try:
                    last = self.worker.run_step(item)
                except BaseException as e:
                    # keep draining so producers blocked on q.put can exit and
                    # join; re-raise after shutdown
                    error = e
        for t in threads:
            t.join()
        if error is not None:
            raise error
        return last


class MultiTrainer(TrainerBase):
    """Single-host collective/plain training (reference MultiTrainer,
    trainer.h:59)."""


class DistMultiTrainer(TrainerBase):
    """PS-mode trainer: flushes the fleet communicator around the epoch
    (reference DistMultiTrainer + async Communicator, trainer.h:126)."""

    def run(self):
        comm = None
        try:
            from ..distributed.ps import runtime as ps_runtime

            comm = getattr(ps_runtime, "_global_communicator", None)
        except Exception:
            pass
        out = super().run()
        if comm is not None and hasattr(comm, "flush"):
            comm.flush()
        return out


class TrainerFactory:
    """Pick a trainer from the program's distributed strategy (reference
    TrainerFactory::CreateTrainer via trainer_desc proto)."""

    @staticmethod
    def create(executor, program, dataset, is_dist=False, **kw) -> TrainerBase:
        cls = DistMultiTrainer if is_dist else MultiTrainer
        return cls(executor, program, dataset, **kw)

"""LoD sequence ops (reference python/paddle/static/nn sequence_lod.py over
the fluid sequence_* C++ ops).

LoD convention: variable-length sequences are stored FLATTENED — one
[total_rows, ...] tensor plus level-1 offsets `lod` = [0, end_0, end_1, ...].
The reference threads lod inside LoDTensor; here the tensor carries a host
`.lod` list attached with `set_lod` (offsets are host metadata in the
reference too — shapes must be static for XLA either way). Differentiable ops
(pool/softmax/conv/pad/...) run as jnp programs over the static offsets;
gradients flow through `apply` as usual.
"""
from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..ops._helpers import t_


def set_lod(x, lod: Sequence[int]):
    """Attach level-1 offsets ([0, e0, e1, ...]) to a tensor."""
    x = t_(x)
    x.lod = [int(v) for v in lod]
    assert x.lod[0] == 0 and x.lod[-1] == x.shape[0], "bad lod offsets"
    return x


def _lod(x) -> List[int]:
    lod = getattr(x, "lod", None)
    if lod is None:
        raise ValueError(
            "sequence op input needs lod offsets; attach with "
            "paddle.static.nn.set_lod(tensor, [0, len0, len0+len1, ...])")
    return lod


def _seg_ids(lod):
    return np.repeat(np.arange(len(lod) - 1), np.diff(lod))


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0):
    x = t_(input)
    lod = _lod(x)
    n = len(lod) - 1
    ids = jnp.asarray(_seg_ids(lod))
    pt = pool_type.lower()

    def kernel(a, pt):
        if pt == "sum":
            return jax.ops.segment_sum(a, ids, num_segments=n)
        if pt in ("average", "mean"):
            s = jax.ops.segment_sum(a, ids, num_segments=n)
            c = jnp.asarray(np.diff(lod)).reshape((-1,) + (1,) * (a.ndim - 1))
            return s / jnp.maximum(c, 1)
        if pt == "sqrt":
            s = jax.ops.segment_sum(a, ids, num_segments=n)
            c = jnp.asarray(np.diff(lod)).reshape((-1,) + (1,) * (a.ndim - 1))
            return s / jnp.sqrt(jnp.maximum(c, 1).astype(a.dtype))
        if pt == "max":
            out = jax.ops.segment_max(a, ids, num_segments=n)
            return jnp.where(jnp.isfinite(out), out, pad_value)
        if pt == "first":
            return a[jnp.asarray(lod[:-1])]
        if pt == "last":
            return a[jnp.asarray(lod[1:]) - 1]
        raise ValueError(pool_type)

    return apply("sequence_pool", kernel, [x], {"pt": pt})


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_softmax(input, use_cudnn=False, name=None):
    x = t_(input)
    lod = _lod(x)
    ids = jnp.asarray(_seg_ids(lod))
    n = len(lod) - 1

    def kernel(a):
        flat = a.reshape(-1)
        mx = jax.ops.segment_max(flat, ids, num_segments=n)
        e = jnp.exp(flat - mx[ids])
        s = jax.ops.segment_sum(e, ids, num_segments=n)
        return (e / s[ids]).reshape(a.shape)

    out = apply("sequence_softmax", kernel, [x])
    out.lod = lod
    return out


def sequence_reverse(x, name=None):
    x = t_(x)
    lod = _lod(x)
    perm = np.concatenate([np.arange(lod[i], lod[i + 1])[::-1]
                           for i in range(len(lod) - 1)]) if len(lod) > 1 \
        else np.arange(0)
    pidx = jnp.asarray(perm.astype(np.int64))

    def kernel(a):
        return a[pidx]

    out = apply("sequence_reverse", kernel, [x])
    out.lod = lod
    return out


def sequence_concat(input, name=None):
    xs = [t_(v) for v in input]
    lods = [_lod(v) for v in xs]
    n = len(lods[0]) - 1
    order = []
    offsets = [0] * len(xs)
    bases = np.cumsum([0] + [v.shape[0] for v in xs[:-1]])
    new_lod = [0]
    for i in range(n):
        for j, lod in enumerate(lods):
            order.extend(range(bases[j] + lod[i], bases[j] + lod[i + 1]))
        new_lod.append(len(order))
    pidx = jnp.asarray(np.array(order, np.int64))

    def kernel(*arrays):
        return jnp.concatenate(arrays, 0)[pidx]

    out = apply("sequence_concat", kernel, xs)
    out.lod = new_lod
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    """Repeat each sequence i of x by the length of y's sequence i."""
    x = t_(x)
    y_lod = _lod(t_(y))
    x_lod = getattr(x, "lod", list(range(x.shape[0] + 1)))
    reps = np.diff(y_lod)
    order = []
    new_lod = [0]
    for i in range(len(x_lod) - 1):
        seq = list(range(x_lod[i], x_lod[i + 1]))
        for _ in range(int(reps[i]) if i < len(reps) else 1):
            order.extend(seq)
        new_lod.append(len(order))
    pidx = jnp.asarray(np.array(order, np.int64))
    out = apply("sequence_expand", lambda a: a[pidx], [x])
    out.lod = new_lod
    return out


def sequence_expand_as(x, y, name=None):
    """Row i of x repeats len(y_i) times (reference sequence_expand_as)."""
    x = t_(x)
    y_lod = _lod(t_(y))
    reps = np.diff(y_lod)
    ridx = jnp.asarray(np.repeat(np.arange(x.shape[0]), reps).astype(np.int64))
    out = apply("sequence_expand_as", lambda a: a[ridx], [x])
    out.lod = list(y_lod)
    return out


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """Flattened -> [num_seqs, maxlen, ...] + lengths."""
    x = t_(x)
    lod = _lod(x)
    lens = np.diff(lod)
    m = maxlen or int(lens.max())
    n = len(lens)
    gather = np.zeros((n, m), np.int64)
    mask = np.zeros((n, m), np.float32)
    for i in range(n):
        L = int(lens[i])
        gather[i, :L] = np.arange(lod[i], lod[i + 1])
        mask[i, :L] = 1
    gidx = jnp.asarray(gather)
    gmask = jnp.asarray(mask)
    pv = float(pad_value if not isinstance(pad_value, Tensor)
               else pad_value.item())

    def kernel(a):
        shaped_mask = gmask.reshape(gmask.shape + (1,) * (a.ndim - 1))
        return a[gidx] * shaped_mask + pv * (1 - shaped_mask)

    out = apply("sequence_pad", kernel, [x])
    return out, Tensor(jnp.asarray(lens.astype(np.int64)))


def sequence_unpad(x, length, name=None):
    """[num_seqs, maxlen, ...] + lengths -> flattened with lod."""
    x = t_(x)
    lens = np.asarray(t_(length)._data).astype(np.int64)
    rows = np.concatenate([np.stack([np.full(L, i), np.arange(L)], 1)
                           for i, L in enumerate(lens)]) if len(lens) else \
        np.zeros((0, 2), np.int64)
    ridx = jnp.asarray(rows)

    def kernel(a):
        return a[ridx[:, 0], ridx[:, 1]]

    out = apply("sequence_unpad", kernel, [x])
    out.lod = [0] + list(np.cumsum(lens))
    return out


def sequence_reshape(input, new_dim):
    x = t_(input)
    lod = _lod(x)
    d = x.shape[-1]
    out = apply("sequence_reshape", lambda a: a.reshape(-1, new_dim), [x])
    out.lod = [int(v * d // new_dim) for v in lod]
    return out


def sequence_slice(input, offset, length, name=None):
    x = t_(input)
    lod = _lod(x)
    offs = np.asarray(t_(offset)._data).reshape(-1)
    lens = np.asarray(t_(length)._data).reshape(-1)
    order = []
    new_lod = [0]
    for i in range(len(lod) - 1):
        start = lod[i] + int(offs[i])
        order.extend(range(start, start + int(lens[i])))
        new_lod.append(len(order))
    pidx = jnp.asarray(np.array(order, np.int64))
    out = apply("sequence_slice", lambda a: a[pidx], [x])
    out.lod = new_lod
    return out


def sequence_scatter(input, index, updates, name=None):
    """Add updates into input rows addressed per-sequence (reference
    sequence_scatter: seq i of index/updates scatters into row i of input)."""
    x, idx, upd = t_(input), t_(index), t_(updates)
    lod = _lod(idx)
    rows = jnp.asarray(_seg_ids(lod))

    def kernel(a, iv, uv):
        return a.at[rows, iv.reshape(-1).astype(jnp.int64)].add(uv.reshape(-1))

    return apply("sequence_scatter", kernel, [x, idx, upd],
                 nondiff_mask=[False, True, False])


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    """Sliding id windows per sequence (reference sequence_enumerate)."""
    x = t_(input)
    lod = _lod(x)
    a = np.asarray(x._data).reshape(-1)
    out = np.full((a.shape[0], win_size), pad_value, a.dtype)
    for i in range(len(lod) - 1):
        for r in range(lod[i], lod[i + 1]):
            for w in range(win_size):
                if r + w < lod[i + 1]:
                    out[r, w] = a[r + w]
    res = Tensor(jnp.asarray(out))
    res.lod = lod
    return res


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """Context-window convolution over each sequence (reference sequence_conv
    op): each position sees filter_size rows centered by padding_start."""
    from ..nn.layer import create_parameter
    from .. import nn as _n

    x = t_(input)
    lod = _lod(x)
    d = x.shape[-1]
    w = create_parameter([filter_size * d, num_filters], "float32",
                         attr=param_attr)
    b = create_parameter([num_filters], "float32", attr=bias_attr, is_bias=True)
    start = -((filter_size - 1) // 2) if padding_start is None else padding_start
    # per-position gather indices (host-built from lod; -1 = zero pad)
    total = x.shape[0]
    gather = np.zeros((total, filter_size), np.int64)
    valid = np.zeros((total, filter_size), np.float32)
    for i in range(len(lod) - 1):
        for r in range(lod[i], lod[i + 1]):
            for k in range(filter_size):
                src = r + start + k
                if lod[i] <= src < lod[i + 1]:
                    gather[r, k] = src
                    valid[r, k] = 1.0
    gidx = jnp.asarray(gather)
    gval = jnp.asarray(valid)

    def kernel(a, wk, bk):
        ctx = a[gidx] * gval[..., None]          # [total, fs, d]
        ctx = ctx.reshape(a.shape[0], filter_size * d)
        return ctx @ wk + bk

    out = apply("sequence_conv", kernel, [x, w, b])
    out.lod = lod
    if act:
        out = getattr(_n.functional, act)(out)
        out.lod = lod
    return out

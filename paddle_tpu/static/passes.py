"""IR pass system over the static Program.

Reference: paddle/fluid/framework/ir/ — SSA Graph + Pass + PassRegistry with
~150 passes (conv_bn_fuse_pass, coalesce_grad_tensor_pass, ...). TPU-native
altitude: XLA already performs the heavy fusions/layout work after lowering,
so the pass surface here operates on the OpDesc list for the things XLA can't
see — dead fetches, duplicate subexpressions, and op-granularity (which also
speeds the per-op debug interpreter). The registry/apply surface mirrors the
reference so strategy code can name passes.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Sequence

PASS_REGISTRY: Dict[str, Callable] = {}


class ProgramView:
    """Mutable per-lowering view of a Program: same pass surface, private op
    list, so one fetch-set's optimization never corrupts another's."""

    def __init__(self, program):
        import types

        self._block = types.SimpleNamespace(
            ops=list(program.global_block().ops),
            vars=program.global_block().vars)
        self._train = program._train
        # seed from aliases recorded by passes applied directly to the
        # PROGRAM (e.g. PassManager delete_dropout before lowering): a fetch
        # of a removed var must resolve through them on this path too
        self._var_aliases: Dict[str, str] = dict(
            getattr(program, "_var_aliases", {}))

    def global_block(self):
        return self._block


def register_pass(name: str):
    def deco(fn):
        PASS_REGISTRY[name] = fn
        fn.pass_name = name
        return fn

    return deco


def apply_pass(program, name: str, fetch_names: Sequence[str] = ()):
    """Run one registered pass in place; returns the program."""
    PASS_REGISTRY[name](program, list(fetch_names))
    return program


def apply_default_passes(program, fetch_names: Sequence[str] = ()):
    for name in ("common_subexpression_elimination", "dead_code_elimination",
                 "fuse_elementwise"):
        apply_pass(program, name, fetch_names)
    return program


def _roots(program, fetch_names):
    roots = set(fetch_names)
    if program._train is not None:
        roots.add(program._train[0])  # loss
    for name, v in program.global_block().vars.items():
        if getattr(v, "persistable", False):
            roots.add(name)
    return roots


@register_pass("dead_code_elimination")
def dead_code_elimination(program, fetch_names):
    """Drop ops whose outputs nothing consumes (reference ir pass of the same
    purpose; roots = fetches + loss + persistables)."""
    block = program.global_block()
    live = _roots(program, fetch_names)
    kept: List = []
    for op in reversed(block.ops):
        if any(o in live for o in op.output_names):
            kept.append(op)
            live.update(op.input_names)
    kept.reverse()
    removed = len(block.ops) - len(kept)
    block.ops = kept
    return removed


@register_pass("common_subexpression_elimination")
def common_subexpression_elimination(program, fetch_names):
    """Merge ops with identical (type, inputs, attrs): later occurrences alias
    the first result (safe: kernels are pure functions of their inputs)."""
    block = program.global_block()
    seen: Dict = {}
    rename: Dict[str, str] = {}
    kept: List = []
    for op in block.ops:
        ins = tuple(rename.get(n, n) for n in op.input_names)
        try:
            key = (op.type, ins, tuple(sorted(op.attrs.items())))
            hash(key)
        except TypeError:
            key = None
        if key is not None and key in seen and \
                len(seen[key].output_names) == len(op.output_names):
            for mine, theirs in zip(op.output_names, seen[key].output_names):
                rename[mine] = theirs
            continue
        if rename:
            op.input_names = [rename.get(n, n) for n in op.input_names]
        if key is not None:
            seen[key] = op
        kept.append(op)
    merged = len(block.ops) - len(kept)
    block.ops = kept
    # propagate renames into any later uses already recorded (fetches handled
    # by callers reading the rename map via var aliasing in the env replay)
    program._var_aliases = getattr(program, "_var_aliases", {})
    program._var_aliases.update(rename)
    return merged


@register_pass("fuse_elementwise")
def fuse_elementwise(program, fetch_names):
    """Compose single-consumer chains of one-input ops into one fused OpDesc
    (the micro analogue of the reference's elementwise fuse passes; XLA
    re-fuses anyway — this shrinks the op list the interpreter walks)."""
    block = program.global_block()
    consumers: Dict[str, int] = {}
    for op in block.ops:
        for n in op.input_names:
            consumers[n] = consumers.get(n, 0) + 1
    roots = _roots(program, fetch_names)

    from .framework import OpDesc

    kept: List = []
    i = 0
    ops = block.ops
    while i < len(ops):
        op = ops[i]
        chain = [op]
        while (i + 1 < len(ops)
               and len(chain[-1].output_names) == 1
               and ops[i + 1].input_names == chain[-1].output_names
               and len(ops[i + 1].input_names) == 1
               and consumers.get(chain[-1].output_names[0], 0) == 1
               and chain[-1].output_names[0] not in roots):
            chain.append(ops[i + 1])
            i += 1
        if len(chain) > 1:
            kernels = [c.kernel for c in chain]

            def fused_kernel(*args, _ks=tuple(kernels)):
                out = _ks[0](*args)
                for k in _ks[1:]:
                    out = k(out)
                return out

            kept.append(OpDesc(
                "fused_" + "_".join(c.type for c in chain), fused_kernel,
                chain[0].input_names, chain[-1].output_names, {}))
        else:
            kept.append(op)
        i += 1
    fused = len(block.ops) - len(kept)
    block.ops = kept
    return fused

"""static.nn placeholder — functional layers shared with nn.functional."""
from ..ops.nn_functional import *  # noqa: F401,F403

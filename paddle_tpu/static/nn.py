"""paddle.static.nn: layer-functions that record into the current Program.

Reference: python/paddle/static/nn/common.py (fc, embedding, conv2d, ...) which append
configured OpDescs + create persistable parameter vars. TPU-native: each call
instantiates the corresponding eager nn.Layer ONCE per call site (parameters concrete,
captured by the program as trainable leaves) and runs it on the symbolic input — the
ops record through the normal dispatch seam.

Note: batch_norm's running-stat mutation is dygraph-only; use nn.BatchNorm under
jit.to_static for that behavior.
"""
from __future__ import annotations

from ..ops.nn_functional import *  # noqa: F401,F403 (functional parity surface)

from .. import nn as _nn


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    in_features = 1
    for d in x.shape[num_flatten_dims:]:
        if d < 0:
            raise ValueError("fc needs static feature dims")
        in_features *= d
    layer = _nn.Linear(in_features, size, weight_attr=weight_attr, bias_attr=bias_attr)
    if len(x.shape) > num_flatten_dims + 1:
        from ..ops import manipulation as M

        x = M.reshape(x, tuple(x.shape[:num_flatten_dims]) + (in_features,))
    out = layer(x)
    if activation:
        from .. import nn

        out = getattr(nn.functional, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, param_attr=None,
              dtype="float32"):
    layer = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                          weight_attr=param_attr)
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, data_format="NCHW"):
    in_channels = input.shape[3] if data_format == "NHWC" else input.shape[1]
    layer = _nn.Conv2D(in_channels, num_filters, filter_size, stride=stride,
                       padding=padding, dilation=dilation, groups=groups,
                       weight_attr=param_attr, bias_attr=bias_attr,
                       data_format=data_format)
    out = layer(input)
    if act:
        from .. import nn

        out = getattr(nn.functional, act)(out)
    return out


# ----------------------------------------------------------- control flow
def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """Static cond (reference static/nn/control_flow.py cond): lowers to
    lax.cond via the dy2static runtime when the predicate is traced."""
    from ..jit import dy2static

    return dy2static.convert_ifelse(pred, true_fn or (lambda: None),
                                    false_fn or (lambda: None))


def case(pred_fn_pairs, default=None, name=None):
    """First matching predicate wins (reference control_flow.case)."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    (pred, fn), rest = pred_fn_pairs[0], pred_fn_pairs[1:]
    if not rest and default is None:
        return cond(pred, fn, fn)
    return cond(pred, fn, lambda: case(rest, default) if rest
                else (default() if default else None))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Integer dispatch (reference control_flow.switch_case); traced indices
    lower to lax.switch."""
    import jax

    from ..core.tensor import Tensor

    fns = dict(branch_fns) if not isinstance(branch_fns, dict) else branch_fns
    keys = sorted(fns)
    idx = branch_index
    if isinstance(idx, Tensor):
        import jax.numpy as jnp

        table = [fns[k] for k in keys] + [default or fns[keys[-1]]]
        # map branch_index -> position (default for misses)
        pos = jnp.searchsorted(jnp.asarray(keys), jnp.reshape(idx._data, ()))
        hit = jnp.isin(jnp.reshape(idx._data, ()), jnp.asarray(keys))
        pos = jnp.where(hit, pos, len(keys))
        return jax.lax.switch(pos, table)
    fn = fns.get(int(idx), default or fns[keys[-1]])
    return fn()


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """Static while (reference control_flow.while_loop) -> lax.while_loop."""
    from ..jit import dy2static

    out = dy2static.convert_while_loop(cond, body, tuple(loop_vars))
    return list(out)


# ------------------------------------------------------------- layer funcs
def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    num = 1 if mode == "all" else (x.shape[1] if mode == "channel"
                                   else int(np.prod(x.shape[1:])))
    layer = _nn.PReLU(num_parameters=num, weight_attr=param_attr,
                      data_format=data_format)
    return layer(x)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    layer = _nn.SpectralNorm(weight.shape, dim=dim, power_iters=power_iters,
                             eps=eps)
    return layer(weight)


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    layer = _nn.Bilinear(x.shape[-1], y.shape[-1], size, weight_attr=param_attr,
                         bias_attr=bias_attr)
    out = layer(x, y)
    if act:
        from .. import nn as _n

        out = getattr(_n.functional, act)(out)
    return out


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    from ..vision.ops import DeformConv2D

    layer = DeformConv2D(x.shape[1], num_filters, filter_size, stride, padding,
                         dilation, deformable_groups, groups,
                         weight_attr=param_attr, bias_attr=bias_attr)
    return layer(x, offset, mask)


def data_norm(input, act=None, epsilon=1e-05, param_attr=None, data_layout="NCHW",
              in_place=False, name=None, moving_mean_name=None,
              moving_variance_name=None, do_model_average_for_mean_and_var=True,
              slot_dim=-1, summary_decay_0=0.9999999, enable_scale_and_shift=False):
    """Data normalization without batch statistics coupling (reference
    data_norm op: per-feature running mean/scale learned as parameters)."""
    from ..core.dispatch import apply
    from ..ops._helpers import t_
    from ..nn.layer import create_parameter

    d = input.shape[-1]
    batch_size = create_parameter([d], "float32",
                                  default_initializer=_nn.initializer.Constant(1e4))
    batch_sum = create_parameter([d], "float32",
                                 default_initializer=_nn.initializer.Constant(0.0))
    batch_square = create_parameter(
        [d], "float32", default_initializer=_nn.initializer.Constant(1e4))

    def kernel(a, n, s, sq):
        mean = s / n
        scale = jnp.sqrt(n / sq)
        return (a - mean) * scale

    import jax.numpy as jnp

    return apply("data_norm", kernel,
                 [t_(input), batch_size, batch_sum, batch_square])


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Lookahead row convolution (reference row_conv op): out[t] =
    sum_{i=0..k} w[i] * x[t+i]."""
    from ..core.dispatch import apply
    from ..ops._helpers import t_
    from ..nn.layer import create_parameter

    d = input.shape[-1]
    k = future_context_size + 1
    w = create_parameter([k, d], "float32")

    def kernel(a, wk):
        import jax.numpy as jnp

        T = a.shape[-2]
        pad = jnp.pad(a, [(0, 0)] * (a.ndim - 2) + [(0, k - 1), (0, 0)])
        out = jnp.zeros_like(a)
        for i in range(k):
            out = out + pad[..., i:i + T, :] * wk[i]
        return out

    out = apply("row_conv", kernel, [t_(input), w])
    if act:
        from .. import nn as _n

        out = getattr(_n.functional, act)(out)
    return out


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference nce op): sampled-softmax
    style binary logistic loss over the true class + k noise classes."""
    import jax
    import jax.numpy as jnp

    from ..core import random as random_mod
    from ..core.dispatch import apply
    from ..nn.layer import create_parameter
    from ..ops._helpers import t_

    d = input.shape[-1]
    k = num_neg_samples or 10
    weight = create_parameter([num_total_classes, d], "float32",
                              attr=param_attr)
    bias = create_parameter([num_total_classes], "float32", attr=bias_attr,
                            is_bias=True)
    key = random_mod.next_key()

    def kernel(x, lab, w, b):
        n = x.shape[0]
        neg = jax.random.randint(key, (n, k), 0, num_total_classes)
        lab_f = lab.reshape(-1)
        pos_logit = (x * w[lab_f]).sum(-1) + b[lab_f]
        neg_logit = jnp.einsum("nd,nkd->nk", x, w[neg]) + b[neg]
        bce = lambda z, t: jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))
        loss = bce(pos_logit, 1.0) + bce(neg_logit, 0.0).sum(-1)
        return loss.reshape(-1, 1)

    return apply("nce", kernel, [t_(input), t_(label), weight, bias],
                 nondiff_mask=[False, True, False, False])


def crf_decoding(input, param_attr=None, label=None, length=None,
                 transition=None):
    """Viterbi decode over linear-chain CRF emissions (reference crf_decoding
    op). input: [B, T, n_tags] emissions; transition [n_tags+2, n_tags]
    (reference layout: row 0 start, row 1 stop)."""
    import jax
    import jax.numpy as jnp

    from ..core.dispatch import apply
    from ..nn.layer import create_parameter
    from ..ops._helpers import t_

    n_tags = input.shape[-1]
    trans = transition if transition is not None else create_parameter(
        [n_tags + 2, n_tags], "float32", attr=param_attr)

    def kernel(em, tr):
        start, stop, T = tr[0], tr[1], tr[2:]

        def decode_one(e):
            def step(carry, obs):
                score = carry  # [n_tags]
                cand = score[:, None] + T  # [from, to]
                best = cand.max(0) + obs
                return best, cand.argmax(0)

            init = start + e[0]
            last, back = jax.lax.scan(step, init, e[1:])
            last = last + stop

            def backtrack(tag, bp):
                return bp[tag], bp[tag]

            final = last.argmax()
            _, path_rev = jax.lax.scan(backtrack, final, back[::-1])
            return jnp.concatenate([path_rev[::-1], jnp.array([final])])

        return jax.vmap(decode_one)(em)

    return apply("crf_decoding", kernel, [t_(input), t_(trans)],
                 differentiable=False)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """PS-backed embedding (reference static.nn.sparse_embedding ->
    distributed_lookup_table): wires a DistributedEmbedding when a PS client
    is live, dense nn.Embedding otherwise."""
    from ..distributed.ps.layers import DistributedEmbedding

    layer = _nn.Embedding(size[0], size[1], sparse=True, weight_attr=param_attr)
    return layer(input)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, offset=0.5, flip=True,
                   kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head (reference multi_box_head): per-scale loc + conf
    convs over the feature pyramid + prior boxes."""
    import math as _m

    import jax.numpy as jnp

    from ..core.tensor import Tensor
    from ..ops import manipulation as P

    n_in = len(inputs)
    if min_sizes is None:
        min_ratio, max_ratio = int(min_ratio), int(max_ratio)
        step = int(_m.floor((max_ratio - min_ratio) / (n_in - 2))) if n_in > 2 else 0
        min_sizes, max_sizes = [], []
        for r in range(min_ratio, max_ratio + 1, max(step, 1)):
            min_sizes.append(base_size * r / 100.0)
            max_sizes.append(base_size * (r + step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes[:n_in - 1]
        max_sizes = [base_size * 0.20] + max_sizes[:n_in - 1]

    locs, confs, boxes_all = [], [], []
    img_h, img_w = image.shape[2], image.shape[3]
    for i, feat in enumerate(inputs):
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i], (list, tuple)) \
            else [aspect_ratios[i]]
        n_priors = len(ar) * (2 if flip else 1) + (2 if max_sizes else 1)
        c_in = feat.shape[1]
        loc = _nn.Conv2D(c_in, n_priors * 4, kernel_size, stride=stride,
                         padding=pad)(feat)
        conf = _nn.Conv2D(c_in, n_priors * num_classes, kernel_size,
                          stride=stride, padding=pad)(feat)
        fh, fw = feat.shape[2], feat.shape[3]
        locs.append(P.reshape(P.transpose(loc, (0, 2, 3, 1)), (loc.shape[0], -1, 4)))
        confs.append(P.reshape(P.transpose(conf, (0, 2, 3, 1)),
                               (conf.shape[0], -1, num_classes)))
        # prior boxes for this scale
        sk = min_sizes[i] / base_size
        sk2 = (max_sizes[i] / base_size) if max_sizes else sk
        widths = [sk] + [sk * _m.sqrt(a) for a in ar] + \
            ([sk / _m.sqrt(a) for a in ar] if flip else []) + [_m.sqrt(sk * sk2)]
        heights = [sk] + [sk / _m.sqrt(a) for a in ar] + \
            ([sk * _m.sqrt(a) for a in ar] if flip else []) + [_m.sqrt(sk * sk2)]
        cx = (np.arange(fw) + offset) / fw
        cy = (np.arange(fh) + offset) / fh
        gx, gy = np.meshgrid(cx, cy)
        pri = []
        for w_, h_ in zip(widths[:n_priors], heights[:n_priors]):
            pri.append(np.stack([gx - w_ / 2, gy - h_ / 2, gx + w_ / 2,
                                 gy + h_ / 2], -1))
        pri = np.stack(pri, 2).reshape(-1, 4).clip(0, 1)
        boxes_all.append(pri.astype(np.float32))

    mbox_locs = P.concat(locs, axis=1)
    mbox_confs = P.concat(confs, axis=1)
    boxes = Tensor(jnp.asarray(np.concatenate(boxes_all, 0)))
    variances = Tensor(jnp.full_like(boxes._data, 0.1))
    return mbox_locs, mbox_confs, boxes, variances


import numpy as np  # noqa: E402  (used by layer funcs above)

from .misc import py_func  # noqa: E402,F401


from .sequence import (  # noqa: E402,F401
    sequence_concat, sequence_conv, sequence_enumerate, sequence_expand,
    sequence_expand_as, sequence_first_step, sequence_last_step, sequence_pad,
    sequence_pool, sequence_reshape, sequence_reverse, sequence_scatter,
    sequence_slice, sequence_softmax, sequence_unpad, set_lod,
)

"""paddle.static.nn: layer-functions that record into the current Program.

Reference: python/paddle/static/nn/common.py (fc, embedding, conv2d, ...) which append
configured OpDescs + create persistable parameter vars. TPU-native: each call
instantiates the corresponding eager nn.Layer ONCE per call site (parameters concrete,
captured by the program as trainable leaves) and runs it on the symbolic input — the
ops record through the normal dispatch seam.

Note: batch_norm's running-stat mutation is dygraph-only; use nn.BatchNorm under
jit.to_static for that behavior.
"""
from __future__ import annotations

from ..ops.nn_functional import *  # noqa: F401,F403 (functional parity surface)

from .. import nn as _nn


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    in_features = 1
    for d in x.shape[num_flatten_dims:]:
        if d < 0:
            raise ValueError("fc needs static feature dims")
        in_features *= d
    layer = _nn.Linear(in_features, size, weight_attr=weight_attr, bias_attr=bias_attr)
    if len(x.shape) > num_flatten_dims + 1:
        from ..ops import manipulation as M

        x = M.reshape(x, tuple(x.shape[:num_flatten_dims]) + (in_features,))
    out = layer(x)
    if activation:
        from .. import nn

        out = getattr(nn.functional, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, param_attr=None,
              dtype="float32"):
    layer = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                          weight_attr=param_attr)
    return layer(input)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, data_format="NCHW"):
    in_channels = input.shape[3] if data_format == "NHWC" else input.shape[1]
    layer = _nn.Conv2D(in_channels, num_filters, filter_size, stride=stride,
                       padding=padding, dilation=dilation, groups=groups,
                       weight_attr=param_attr, bias_attr=bias_attr,
                       data_format=data_format)
    out = layer(input)
    if act:
        from .. import nn

        out = getattr(nn.functional, act)(out)
    return out

"""Static-graph persistence + misc utilities.

Reference: python/paddle/static/io.py (save/load_inference_model,
serialize_program/persistables, save/load_to_file, normalize_program) and
fluid/io.py (save/load, load_program_state/set_program_state). TPU-native:
a "program" serializes as the recorded OpDesc replay spec via pickle of its
structural description + captured parameter arrays; inference artifacts are
self-contained (the Executor re-lowers on load)."""
from __future__ import annotations

import os
import pickle
from typing import Dict, List, Optional

import numpy as np

try:  # kernels are closures: cloudpickle serializes them, stdlib cannot
    import cloudpickle as _kpickle
except ImportError:  # pragma: no cover
    _kpickle = pickle

from .framework import Program, Variable, default_main_program


# ------------------------------------------------------ program state (params)
def load_program_state(model_path, var_list=None):
    """Read a saved state into {name: ndarray} (reference io.load_program_state)."""
    path = model_path if model_path.endswith(".pdparams") else \
        model_path + ".pdparams"
    with open(path, "rb") as f:
        state = pickle.load(f)
    return {k: np.asarray(v) for k, v in state.items()}


def set_program_state(program, state_dict):
    """Overwrite the program's captured parameters (reference
    io.set_program_state)."""
    import jax.numpy as jnp

    missing = []
    for name, arr in state_dict.items():
        t = program._captures.get(name)
        if t is None:
            missing.append(name)
            continue
        t._data = jnp.asarray(np.asarray(arr), dtype=t._data.dtype)
    return missing


def save(program, model_path, protocol=4, **configs):
    """Persist the program's parameters (reference static.save -> .pdparams +
    .pdopt; optimizer state lives on the program here)."""
    state = {n: np.asarray(t._data) for n, t in program._captures.items()
             if getattr(t, "persistable", False) or not t.stop_gradient}
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)
    opt_state = {n: [np.asarray(s) for s in st]
                 for n, st in getattr(program, "_opt_state", {}).items()}
    with open(model_path + ".pdopt", "wb") as f:
        pickle.dump(opt_state, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    """Restore parameters (+ optimizer state if present)."""
    set_program_state(program, load_program_state(model_path))
    opt_path = model_path + ".pdopt"
    if os.path.exists(opt_path):
        import jax.numpy as jnp

        with open(opt_path, "rb") as f:
            opt_state = pickle.load(f)
        program._opt_state = {
            n: tuple(jnp.asarray(s) for s in st) for n, st in opt_state.items()}


# ---------------------------------------------------- inference model save/load
def normalize_program(program, feeds, fetches):
    """Prune to the inference slice (reference normalize_program): clone
    without the training mark; passes trim dead ops at lowering."""
    pruned = program.clone(for_test=True)
    pruned._inference_feeds = [v.name if isinstance(v, Variable) else str(v)
                               for v in feeds]
    pruned._inference_fetches = [v.name if isinstance(v, Variable) else str(v)
                                 for v in fetches]
    return pruned


def serialize_program(feed_vars=None, fetch_vars=None, program=None, **kwargs):
    program = program or default_main_program()
    ops = [{"type": op.type, "inputs": op.input_names,
            "outputs": op.output_names, "attrs": op.attrs,
            "kernel": _kpickle.dumps(op.kernel)}
           for op in program.global_block().ops]
    meta = {
        "ops": ops,
        "feeds": [v.name if isinstance(v, Variable) else str(v)
                  for v in (feed_vars or [])],
        "fetches": [v.name if isinstance(v, Variable) else str(v)
                    for v in (fetch_vars or [])],
        "var_shapes": {n: (list(v.shape), str(v.dtype))
                       for n, v in program.global_block().vars.items()},
    }
    return pickle.dumps(meta)


def serialize_persistables(feed_vars=None, fetch_vars=None, program=None,
                           **kwargs):
    program = program or default_main_program()
    state = {n: np.asarray(t._data) for n, t in program._captures.items()}
    return pickle.dumps(state)


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def deserialize_program(data):
    meta = pickle.loads(data)
    from .framework import OpDesc

    prog = Program()
    block = prog.global_block()
    for name, (shape, dtype) in meta["var_shapes"].items():
        block.create_var(name=name, shape=shape, dtype=dtype)
    for od in meta["ops"]:
        block.ops.append(OpDesc(od["type"], _kpickle.loads(od["kernel"]),
                                od["inputs"], od["outputs"], od["attrs"]))
    prog._inference_feeds = meta["feeds"]
    prog._inference_fetches = meta["fetches"]
    return prog


def deserialize_persistables(program, data, executor=None):
    from ..core.tensor import Tensor
    import jax.numpy as jnp

    state = pickle.loads(data)
    for n, arr in state.items():
        program._captures[n] = Tensor(jnp.asarray(arr))
    return program


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """`{prefix}.pdmodel` (program) + `{prefix}.pdiparams` (weights)
    (reference static.save_inference_model)."""
    program = program or default_main_program()
    program = normalize_program(program, feed_vars, fetch_vars)
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    save_to_file(path_prefix + ".pdmodel",
                 serialize_program(feed_vars, fetch_vars, program))
    save_to_file(path_prefix + ".pdiparams",
                 serialize_persistables(feed_vars, fetch_vars, program))


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns (program, feed_names, fetch_names) like the reference."""
    prog = deserialize_program(load_from_file(path_prefix + ".pdmodel"))
    deserialize_persistables(prog, load_from_file(path_prefix + ".pdiparams"))
    return prog, prog._inference_feeds, prog._inference_fetches

"""Static-mode utilities: Print/py_func/gradients/EMA/places/device_guard/
accuracy/auc/create_global_var + parity shims (reference python/paddle/static/
__init__.py surface over fluid layers/optimizer helpers)."""
from __future__ import annotations

import contextlib
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from .framework import Variable, default_main_program


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False,
                      name=None):
    """A persistable filled variable (reference layers.create_global_var)."""
    import jax.numpy as jnp

    from ..core import dtype as dtypes

    prog = default_main_program()
    t = Tensor(jnp.full(tuple(shape), value, dtypes.convert_dtype(dtype)))
    t.persistable = persistable
    if prog is not None:
        name = name or prog._unique_name("global_var")
        prog._captures[name] = t
        t.name = name
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..nn.layer import create_parameter as _cp

    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase="both"):
    """Debug print op (reference controlflow Print op). Under tracing it
    becomes a jax.debug.print; eagerly it prints immediately."""
    from ..core.dispatch import apply
    from ..ops._helpers import t_

    msg = message or ""

    def kernel(a):
        import jax

        jax.debug.print(msg + " {x}", x=a)
        return a

    return apply("print", kernel, [t_(input)])


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host python function as an op (reference py_func_op): runs via
    pure_callback under tracing, eagerly otherwise."""
    from ..core.dispatch import apply
    from ..ops._helpers import t_

    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]

    def kernel(*arrays):
        import jax

        shapes = [jax.ShapeDtypeStruct(tuple(o.shape), np.dtype(str(o.dtype)))
                  for o in outs]

        def host(*args):
            res = func(*args)
            res = res if isinstance(res, (list, tuple)) else [res]
            return tuple(np.asarray(r) for r in res)

        result = jax.pure_callback(host, tuple(shapes), *arrays,
                                   vmap_method="sequential")
        return tuple(result) if len(shapes) > 1 else result[0]

    return apply("py_func", kernel, [t_(v) for v in xs],
                 differentiable=backward_func is not None)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Static gradients API (reference static.gradients): marks the program
    for training on `targets` and returns symbolic grad placeholders resolved
    at lowering. Eager tensors differentiate immediately via paddle.grad."""
    from ..core.autograd import grad as eager_grad

    t_list = targets if isinstance(targets, (list, tuple)) else [targets]
    i_list = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if not isinstance(t_list[0], Variable):
        return eager_grad(t_list, i_list, grad_outputs=target_gradients,
                          allow_unused=True)
    raise NotImplementedError(
        "symbolic static.gradients placeholders are not supported; use "
        "append_backward + Optimizer.minimize (grads materialize at lowering)")


def device_guard(device=None):
    """Parity context (reference static.device_guard): XLA owns placement
    inside a compiled program, so this is an annotation no-op."""
    return contextlib.nullcontext()


def ipu_shard_guard(index=-1, stage=-1):
    return contextlib.nullcontext()


class IpuStrategy:  # Graphcore parity shims: accepted, inert on TPU
    def __init__(self):
        self.num_ipus = 1

    def set_graph_config(self, **kw):
        pass

    def set_pipelining_config(self, **kw):
        pass

    def set_precision_config(self, **kw):
        pass


class IpuCompiledProgram:
    def __init__(self, program=None, scope=None, ipu_strategy=None):
        self._program = program

    def compile(self, feed_list, fetch_list):
        return self._program


def cpu_places(device_count=None):
    from ..core.place import CPUPlace

    n = device_count or 1
    return [CPUPlace(i) for i in range(n)]


def cuda_places(device_ids=None):
    """Accelerator places (CUDAPlace maps onto the TPU chip set)."""
    import jax

    from ..core.place import CUDAPlace

    if device_ids is None:
        device_ids = range(len([d for d in jax.devices()
                                if d.platform != "cpu"]) or 1)
    return [CUDAPlace(i) for i in device_ids]


def xpu_places(device_ids=None):
    from ..core.place import XPUPlace

    return [XPUPlace(i) for i in (device_ids or [0])]


def npu_places(device_ids=None):
    from ..core.place import NPUPlace

    return [NPUPlace(i) for i in (device_ids or [0])]


def mlu_places(device_ids=None):
    from ..core.place import MLUPlace

    return [MLUPlace(i) for i in (device_ids or [0])]


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Top-k accuracy op (reference metric op)."""
    from ..core.dispatch import apply
    from ..ops._helpers import t_

    def kernel(pred, lab, k):
        import jax.numpy as jnp

        topk = jnp.argsort(-pred, axis=-1)[..., :k]
        hit = (topk == lab.reshape(-1, 1)).any(-1)
        return hit.astype(jnp.float32).mean()

    return apply("accuracy", kernel, [t_(input), t_(label)], {"k": k},
                 differentiable=False)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Batch AUC (reference auc op; returns the metric tensor)."""
    from ..core.dispatch import apply
    from ..ops._helpers import t_

    def kernel(pred, lab):
        import jax.numpy as jnp

        p = pred[:, 1] if pred.ndim == 2 and pred.shape[1] == 2 else \
            pred.reshape(-1)
        y = lab.reshape(-1).astype(jnp.float32)
        order = jnp.argsort(p)
        y_sorted = y[order]
        n_pos = y.sum()
        n_neg = y.shape[0] - n_pos
        ranks = jnp.arange(1, y.shape[0] + 1, dtype=jnp.float32)
        sum_pos_ranks = (ranks * y_sorted).sum()
        auc_v = (sum_pos_ranks - n_pos * (n_pos + 1) / 2) / \
            jnp.maximum(n_pos * n_neg, 1.0)
        return auc_v

    return apply("auc", kernel, [t_(input), t_(label)], differentiable=False)


class WeightNormParamAttr:
    """Parity attr (reference WeightNormParamAttr): carries dim for weight
    normalization; consumed like ParamAttr."""

    def __init__(self, dim=None, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        from ..nn.layer import ParamAttr

        self.dim = dim
        self._attr = ParamAttr(name=name, initializer=initializer,
                               learning_rate=learning_rate,
                               regularizer=regularizer, trainable=trainable)


class ExponentialMovingAverage:
    """EMA of parameters (reference static.ExponentialMovingAverage):
    update() after each step; apply()/restore() swap shadow weights in/out."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadow = {}
        self._backup = {}
        self._params = []
        self._step = 0

    def _collect(self):
        if not self._params:
            prog = default_main_program()
            if prog is not None:
                self._params = [(n, t) for n, t in prog._captures.items()
                                if not t.stop_gradient]
        return self._params

    def bind(self, parameters):
        self._params = [(getattr(p, "name", str(i)) or str(i), p)
                        for i, p in enumerate(parameters)]
        return self

    def update(self):
        import jax.numpy as jnp

        self._step += 1
        d = min(self._decay, (1 + self._step) / (10 + self._step))
        for n, p in self._collect():
            prev = self._shadow.get(n, p._data)
            self._shadow[n] = d * prev + (1 - d) * p._data

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        for n, p in self._collect():
            if n in self._shadow:
                self._backup[n] = p._data
                p._data = self._shadow[n]
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for n, p in self._collect():
            if n in self._backup:
                p._data = self._backup.pop(n)

"""paddle.sparse equivalent (COO/CSR tensors + ops).

Reference: paddle/phi sparse kernels (phi/core/sparse_coo_tensor.h,
sparse_csr_tensor.h) + python/paddle/sparse API (v2.3 incubate.sparse).
TPU-native: SparseCooTensor wraps jax.experimental.sparse.BCOO — XLA lowers
its matmuls to gather/scatter-fused dense ops, the TPU-appropriate execution
of sparsity (the MXU has no sparse datapath; structured masking is what wins).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor


class SparseCooTensor(Tensor):
    """COO sparse tensor. Dense fallback semantics mirror the reference: any
    generic Tensor op densifies first via the lazy `_data` property (phi falls
    back to dense kernels the same way)."""

    def __init__(self, bcoo: jsparse.BCOO, stop_gradient=True):
        self._bcoo = bcoo
        self._dense_cache = None
        super().__init__(jnp.zeros((), jnp.float32), stop_gradient=stop_gradient)

    @property
    def _data(self):
        if self._dense_cache is None:
            self._dense_cache = self._bcoo.todense()
        return self._dense_cache

    @_data.setter
    def _data(self, v):
        if v is not None and v.ndim == 0 and self._bcoo is not None:
            return  # Tensor.__init__'s scalar placeholder: keep the sparse view
        # in-place mutation (set_value etc.): re-sparsify so values()/indices()/
        # to_dense() stay consistent with the dense contents
        self._dense_cache = v
        if v is not None and self._bcoo is not None:
            self._bcoo = jsparse.BCOO.fromdense(jnp.asarray(v))

    # Tensor protocol pieces
    @property
    def shape(self):
        return tuple(self._bcoo.shape)

    @property
    def dtype(self):
        return str(self._bcoo.dtype)

    def indices(self) -> Tensor:
        return Tensor(self._bcoo.indices.T)  # paddle: [sparse_dim, nnz]

    def values(self) -> Tensor:
        return Tensor(self._bcoo.data)

    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def to_dense(self) -> Tensor:
        return Tensor(self._bcoo.todense())

    def numpy(self):
        return np.asarray(self._bcoo.todense())

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    """Build a COO tensor from [sparse_dim, nnz] indices + [nnz, ...] values
    (reference paddle.sparse.sparse_coo_tensor)."""
    idx = np.asarray(indices.numpy() if isinstance(indices, Tensor) else indices)
    vals = jnp.asarray(values._data if isinstance(values, Tensor) else values)
    if dtype is not None:
        from ..core.dtype import convert_dtype

        vals = vals.astype(convert_dtype(dtype))
    idx = idx.T  # BCOO wants [nnz, sparse_dim]
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(0))
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx)), shape=tuple(shape))
    return SparseCooTensor(bcoo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    """CSR input surface; stored as BCOO (XLA has one sparse path) behind a
    SparseCsrTensor view exposing crows()/cols() with CSR semantics."""
    crows_np = np.asarray(crows.numpy() if isinstance(crows, Tensor) else crows)
    cols_np = np.asarray(cols.numpy() if isinstance(cols, Tensor) else cols)
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    coo = sparse_coo_tensor(np.stack([rows, cols_np]), values, shape, dtype,
                            stop_gradient)
    return SparseCsrTensor(coo._bcoo, stop_gradient=stop_gradient)


def _as_bcoo(x):
    if isinstance(x, SparseCooTensor):
        return x._bcoo
    raise TypeError(f"expected a sparse tensor, got {type(x)}")


# ---- ops (reference python/paddle/incubate/sparse/*) ----
def add(x, y):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        return SparseCooTensor((_as_bcoo(x) + _as_bcoo(y)).sum_duplicates())
    xd = x.to_dense() if isinstance(x, SparseCooTensor) else x
    yd = y.to_dense() if isinstance(y, SparseCooTensor) else y
    return xd + yd


def matmul(x, y) -> Tensor:
    """sparse @ dense -> dense (the hot op: embedding-style gathers on TPU)."""
    y_arr = y._data if isinstance(y, Tensor) else jnp.asarray(y)
    return Tensor(_as_bcoo(x) @ y_arr)


def masked_matmul(x: Tensor, y: Tensor, mask: SparseCooTensor):
    """dense@dense evaluated only at mask's nonzeros (reference masked_matmul)."""
    out = (x._data @ y._data)
    bcoo = _as_bcoo(mask)
    idx = bcoo.indices
    vals = out[tuple(idx[:, d] for d in range(idx.shape[1]))]
    return SparseCooTensor(jsparse.BCOO((vals, idx), shape=bcoo.shape))


def _unary(name, fn):
    def op(x):
        b = _as_bcoo(x)
        return SparseCooTensor(jsparse.BCOO((fn(b.data), b.indices),
                                            shape=b.shape))
    op.__name__ = name
    return op


relu = _unary("relu", jax.nn.relu)
sin = _unary("sin", jnp.sin)
tanh = _unary("tanh", jnp.tanh)
sqrt = _unary("sqrt", jnp.sqrt)
abs = _unary("abs", jnp.abs)
neg = _unary("neg", jnp.negative)


def transpose(x, perm):
    return SparseCooTensor(_as_bcoo(x).transpose(tuple(perm)))


__all__ = ["SparseCooTensor", "sparse_coo_tensor", "sparse_csr_tensor", "add",
           "matmul", "masked_matmul", "relu", "sin", "tanh", "sqrt", "abs",
           "neg", "transpose"]


# ---- sparse layers (reference python/paddle/sparse/layer/): activation +
# 3-D (submanifold) sparse convolution over SparseCooTensor point clouds ----
class ReLU:
    """Sparse ReLU on the stored values (reference sparse/layer/activation.py)."""

    def __call__(self, x):
        if isinstance(x, SparseCooTensor):
            return relu(x)
        return Tensor(jax.nn.relu(x._data))


class Conv3D:
    """Sparse 3-D convolution on NDHWC SparseCooTensor (reference
    sparse/layer/conv.py, gpu sparse convolution kernels). Densify ->
    lax.conv -> re-sparsify: on TPU the dense conv IS the MXU fast path; the
    sparse layout is a memory format here, same numerics as the reference."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, bias_attr=None, subm=False):
        from ..nn import initializer as I
        from ..nn.layer import create_parameter
        from ..nn.layers.conv_pool import _ntuple

        ks = _ntuple(kernel_size, 3)
        fan_in = in_channels * int(np.prod(ks))
        self.weight = create_parameter(
            (out_channels, in_channels) + tuple(ks), "float32",
            default_initializer=I.Normal(0.0, (2.0 / fan_in) ** 0.5))
        self.bias = None if bias_attr is False else create_parameter(
            (out_channels,), "float32", is_bias=True)
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.groups = groups
        self.subm = subm

    def __call__(self, x):
        from ..ops import nn_functional as F

        is_sparse = isinstance(x, SparseCooTensor)
        dense = x.to_dense() if is_sparse else x
        d = Tensor(jnp.moveaxis(dense._data, -1, 1))  # NDHWC -> NCDHW
        out = F.conv3d(d, self.weight, self.bias, self.stride, self.padding,
                       self.dilation, self.groups)
        out_nd = jnp.moveaxis(out._data, 1, -1)       # back to NDHWC
        if not is_sparse:
            return Tensor(out_nd)
        if self.subm:
            # submanifold: output sparsity pattern == input pattern
            idx = x._bcoo.indices                      # [nnz, sparse_dim]
            sd = idx.shape[1]
            vals = out_nd[tuple(idx[:, i] for i in range(sd))]  # [nnz, C]
            bcoo = jsparse.BCOO((vals, idx), shape=tuple(out_nd.shape))
            return SparseCooTensor(bcoo, stop_gradient=x.stop_gradient)
        bcoo = jsparse.BCOO.fromdense(out_nd, n_dense=1)
        return SparseCooTensor(bcoo, stop_gradient=x.stop_gradient)


class SubmConv3D(Conv3D):
    def __init__(self, *args, **kwargs):
        kwargs["subm"] = True
        super().__init__(*args, **kwargs)


class SparseCsrTensor(SparseCooTensor):
    """CSR surface (reference SparseCsrTensor, phi::SparseCsrTensor): storage
    stays BCOO (XLA has one sparse path — module docstring), crows/cols are
    derived accessors with CSR semantics."""

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def crows(self) -> Tensor:
        rows = np.asarray(self._bcoo.indices)[:, 0]
        n_rows = self.shape[0]
        counts = np.bincount(rows, minlength=n_rows)
        return Tensor(jnp.asarray(np.concatenate([[0], np.cumsum(counts)])
                                  .astype(np.int64)))

    def cols(self) -> Tensor:
        return Tensor(jnp.asarray(
            np.asarray(self._bcoo.indices)[:, 1].astype(np.int64)))


def _dense_to_coo(x: Tensor, sparse_dim: int = None) -> SparseCooTensor:
    """Tensor.to_sparse_coo (reference api.yaml dense_to_coo/to_sparse_coo):
    the leading `sparse_dim` dims become sparse, the rest dense."""
    n_dense = 0 if sparse_dim is None else x.ndim - sparse_dim
    bcoo = jsparse.BCOO.fromdense(x._data, n_dense=n_dense)
    return SparseCooTensor(bcoo, stop_gradient=x.stop_gradient)


def _dense_to_csr(x: Tensor) -> SparseCsrTensor:
    """Tensor.to_sparse_csr (reference to_sparse_csr): 2-D only."""
    if x.ndim != 2:
        raise ValueError(f"to_sparse_csr needs a 2-D tensor, got {x.ndim}-D")
    bcoo = jsparse.BCOO.fromdense(x._data)
    return SparseCsrTensor(bcoo, stop_gradient=x.stop_gradient)


Tensor.to_sparse_coo = _dense_to_coo
Tensor.to_sparse_csr = _dense_to_csr

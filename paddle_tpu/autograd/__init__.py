"""paddle.autograd equivalent: PyLayer + backward + grad.

Reference: python/paddle/autograd/py_layer.py; eager PyLayer plumbing in
paddle/fluid/eager/pylayer/. A PyLayer's backward is spliced into the tape as a custom Node.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.autograd import (  # noqa: F401
    Node, enable_grad, grad, is_grad_enabled, no_grad, run_backward,
    set_grad_enabled,
)
from ..core.tensor import Tensor


def backward(tensors, grad_tensors=None, retain_graph=False):
    run_backward(tensors, grad_tensors, retain_graph)


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved

    def mark_not_inplace(self, *args):
        pass

    def set_materialize_grads(self, v):
        pass


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """Custom autograd op:

        class Exp(PyLayer):
            @staticmethod
            def forward(ctx, x): ...
            @staticmethod
            def backward(ctx, dy): ...
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from ..core.autograd import is_grad_enabled

        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]
        need_grad = is_grad_enabled() and any(not t.stop_gradient for t in tensor_args)

        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        multi = isinstance(outputs, (tuple, list))
        outs = list(outputs) if multi else [outputs]

        if need_grad:
            diff_inputs = [t for t in tensor_args]

            def vjp_fn(cotangents):
                cots = cotangents if isinstance(cotangents, tuple) else (cotangents,)
                cot_tensors = [Tensor(c, stop_gradient=True) for c in cots]
                with no_grad():
                    in_grads = cls.backward(ctx, *cot_tensors)
                if not isinstance(in_grads, (tuple, list)):
                    in_grads = (in_grads,)
                result = []
                gi = iter(in_grads)
                for t in diff_inputs:
                    g = next(gi, None)
                    result.append(None if g is None else (g._data if isinstance(g, Tensor) else jnp.asarray(g)))
                return tuple(result)

            node = Node(
                vjp_fn,
                diff_inputs,
                [(tuple(o.shape), np.dtype(o.dtype)) for o in outs],
                name=cls.__name__,
            )
            for i, o in enumerate(outs):
                o._stop_gradient = False
                o._node = node
                o._out_index = i

        return tuple(outs) if multi else outs[0]


LegacyPyLayer = PyLayer


def set_grad_enabled_fn(mode):
    return set_grad_enabled(mode)

"""paddle.sysconfig (reference python/paddle/sysconfig.py)."""
import os


def get_include():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "include")


def get_lib():
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "libs")

"""paddle.strings — string-tensor ops.

Reference: python/paddle/utils/code_gen/strings_api.yaml (empty, empty_like,
lower, upper) over phi::StringTensor (paddle/phi/core/string_tensor.h), whose
kernels are CPU-only in the reference too — strings never touch the
accelerator. TPU-natively the same is true: a StringTensor is a host-side
numpy unicode array; lower/upper follow the reference's utf8/ascii split
(strings_lower_upper_kernel: ascii fast path vs full utf8 case mapping).
"""
from __future__ import annotations

import numpy as np

__all__ = ["StringTensor", "to_string_tensor", "empty", "empty_like",
           "lower", "upper"]


class StringTensor:
    """Host-resident tensor of unicode strings."""

    def __init__(self, data):
        self._data = np.asarray(data, dtype=object)

    @property
    def shape(self):
        return list(self._data.shape)

    def numpy(self):
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, {self._data!r})"

    def __eq__(self, other):
        other = other._data if isinstance(other, StringTensor) else other
        return bool(np.array_equal(self._data, np.asarray(other, dtype=object)))


def to_string_tensor(data) -> StringTensor:
    return data if isinstance(data, StringTensor) else StringTensor(data)


def empty(shape, name=None) -> StringTensor:
    """strings_api.yaml `empty`: a string tensor of empty strings."""
    return StringTensor(np.full(tuple(shape), "", dtype=object))


def empty_like(x, name=None) -> StringTensor:
    return empty(to_string_tensor(x).shape)


def _map(x, fn):
    src = to_string_tensor(x)._data
    out = np.empty_like(src)
    for idx in np.ndindex(src.shape):
        out[idx] = fn(src[idx])
    return StringTensor(out)


def _ascii_only(fn_name):
    # reference ascii fast path: only [A-Za-z] change case, other bytes kept
    lo = ord("a") - ord("A")

    def f(s):
        if fn_name == "lower":
            return "".join(chr(ord(c) + lo) if "A" <= c <= "Z" else c
                           for c in s)
        return "".join(chr(ord(c) - lo) if "a" <= c <= "z" else c for c in s)

    return f


def lower(x, use_utf8_encoding: bool = False, name=None) -> StringTensor:
    """strings_api.yaml `lower` (strings_lower_upper_kernel): ascii fast path
    by default; use_utf8_encoding=True applies the full unicode mapping."""
    if use_utf8_encoding:
        return _map(x, str.lower)
    return _map(x, _ascii_only("lower"))


def upper(x, use_utf8_encoding: bool = False, name=None) -> StringTensor:
    if use_utf8_encoding:
        return _map(x, str.upper)
    return _map(x, _ascii_only("upper"))

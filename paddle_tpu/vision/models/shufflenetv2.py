"""ShuffleNetV2. Reference: python/paddle/vision/models/shufflenetv2.py."""
from __future__ import annotations

from ... import nn
from ...ops import concat, reshape, split, transpose


def channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = reshape(x, [n, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [n, c, h, w])


_ACTS = {"relu": nn.ReLU, "swish": nn.Swish}


def _conv_bn_act(in_c, out_c, k, stride=1, groups=1, act="relu"):
    layers = [nn.Conv2D(in_c, out_c, k, stride=stride, padding=(k - 1) // 2,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(out_c)]
    if act is not None:
        layers.append(_ACTS[act]())
    return nn.Sequential(*layers)


class InvertedResidual(nn.Layer):
    def __init__(self, in_channels, out_channels, stride, act="relu"):
        super().__init__()
        self._stride = stride
        act_layer = _ACTS[act]
        branch_features = out_channels // 2
        if stride == 1:
            assert in_channels == branch_features * 2
        else:
            self.branch1 = nn.Sequential(
                nn.Conv2D(in_channels, in_channels, 3, stride=stride, padding=1,
                          groups=in_channels, bias_attr=False),
                nn.BatchNorm2D(in_channels),
                nn.Conv2D(in_channels, branch_features, 1, bias_attr=False),
                nn.BatchNorm2D(branch_features), act_layer())
        b2_in = in_channels if stride > 1 else branch_features
        self.branch2 = nn.Sequential(
            nn.Conv2D(b2_in, branch_features, 1, bias_attr=False),
            nn.BatchNorm2D(branch_features), act_layer(),
            nn.Conv2D(branch_features, branch_features, 3, stride=stride, padding=1,
                      groups=branch_features, bias_attr=False),
            nn.BatchNorm2D(branch_features),
            nn.Conv2D(branch_features, branch_features, 1, bias_attr=False),
            nn.BatchNorm2D(branch_features), act_layer())

    def forward(self, x):
        if self._stride == 1:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        stage_repeats = [4, 8, 4]
        arch = {0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
                0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
                1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048]}
        assert scale in arch, f"supported scales: {sorted(arch)}, got {scale}"
        stage_out = arch[scale]

        self.conv1 = _conv_bn_act(3, stage_out[0], 3, stride=2, act=act)
        self.max_pool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1)
        in_c = stage_out[0]
        stages = []
        for i, repeats in enumerate(stage_repeats):
            out_c = stage_out[i + 1]
            blocks = [InvertedResidual(in_c, out_c, 2, act=act)]
            blocks.extend(InvertedResidual(out_c, out_c, 1, act=act)
                          for _ in range(repeats - 1))
            stages.append(nn.Sequential(*blocks))
            in_c = out_c
        self.stages = nn.LayerList(stages)
        self.conv_last = _conv_bn_act(in_c, stage_out[-1], 1, act=act)
        if with_pool:
            self.pool2d_avg = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(stage_out[-1], num_classes)

    def forward(self, x):
        x = self.max_pool(self.conv1(x))
        for stage in self.stages:
            x = stage(x)
        x = self.conv_last(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def _shufflenet(scale, pretrained=False, **kwargs):
    assert not pretrained, "pretrained weights are not bundled (zero-egress image)"
    return ShuffleNetV2(scale=scale, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, pretrained, act="swish", **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, pretrained, **kwargs)

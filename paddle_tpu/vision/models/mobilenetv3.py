"""MobileNetV3 (small/large). Reference: python/paddle/vision/models/mobilenetv3.py."""
from __future__ import annotations

from ... import nn
from .mobilenetv2 import _make_divisible


class SqueezeExcitation(nn.Layer):
    def __init__(self, input_channels, squeeze_channels):
        super().__init__()
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(input_channels, squeeze_channels, 1)
        self.relu = nn.ReLU()
        self.fc2 = nn.Conv2D(squeeze_channels, input_channels, 1)
        self.hardsigmoid = nn.Hardsigmoid()

    def forward(self, x):
        scale = self.avgpool(x)
        scale = self.relu(self.fc1(scale))
        scale = self.hardsigmoid(self.fc2(scale))
        return x * scale


class ConvNormActivation(nn.Sequential):
    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1,
                 padding=None, groups=1, activation_layer=nn.ReLU):
        if padding is None:
            padding = (kernel_size - 1) // 2
        layers = [
            nn.Conv2D(in_channels, out_channels, kernel_size, stride, padding,
                      groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_channels),
        ]
        if activation_layer is not None:
            layers.append(activation_layer())
        super().__init__(*layers)


class InvertedResidualConfig:
    def __init__(self, in_channels, kernel, expanded_channels, out_channels, use_se,
                 activation, stride, scale=1.0):
        self.in_channels = self.adjust_channels(in_channels, scale)
        self.kernel = kernel
        self.expanded_channels = self.adjust_channels(expanded_channels, scale)
        self.out_channels = self.adjust_channels(out_channels, scale)
        self.use_se = use_se
        self.use_hs = activation == "HS"
        self.stride = stride

    @staticmethod
    def adjust_channels(channels, scale):
        return _make_divisible(channels * scale)


class InvertedResidual(nn.Layer):
    def __init__(self, cfg: InvertedResidualConfig):
        super().__init__()
        self.use_res_connect = cfg.stride == 1 and cfg.in_channels == cfg.out_channels
        act = nn.Hardswish if cfg.use_hs else nn.ReLU
        layers = []
        if cfg.expanded_channels != cfg.in_channels:
            layers.append(ConvNormActivation(cfg.in_channels, cfg.expanded_channels,
                                             kernel_size=1, activation_layer=act))
        layers.append(ConvNormActivation(
            cfg.expanded_channels, cfg.expanded_channels, kernel_size=cfg.kernel,
            stride=cfg.stride, groups=cfg.expanded_channels, activation_layer=act))
        if cfg.use_se:
            layers.append(SqueezeExcitation(
                cfg.expanded_channels, _make_divisible(cfg.expanded_channels // 4)))
        layers.append(ConvNormActivation(cfg.expanded_channels, cfg.out_channels,
                                         kernel_size=1, activation_layer=None))
        self.block = nn.Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        if self.use_res_connect:
            out = x + out
        return out


class MobileNetV3(nn.Layer):
    def __init__(self, config, last_channel, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        firstconv_out = config[0].in_channels
        lastconv_in = config[-1].out_channels
        lastconv_out = 6 * lastconv_in

        layers = [ConvNormActivation(3, firstconv_out, kernel_size=3, stride=2,
                                     activation_layer=nn.Hardswish)]
        layers.extend(InvertedResidual(cfg) for cfg in config)
        layers.append(ConvNormActivation(lastconv_in, lastconv_out, kernel_size=1,
                                         activation_layer=nn.Hardswish))
        self.features = nn.Sequential(*layers)

        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(lastconv_out, last_channel), nn.Hardswish(),
                nn.Dropout(0.2), nn.Linear(last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def _small_config(scale):
    C = InvertedResidualConfig
    return [
        C(16, 3, 16, 16, True, "RE", 2, scale),
        C(16, 3, 72, 24, False, "RE", 2, scale),
        C(24, 3, 88, 24, False, "RE", 1, scale),
        C(24, 5, 96, 40, True, "HS", 2, scale),
        C(40, 5, 240, 40, True, "HS", 1, scale),
        C(40, 5, 240, 40, True, "HS", 1, scale),
        C(40, 5, 120, 48, True, "HS", 1, scale),
        C(48, 5, 144, 48, True, "HS", 1, scale),
        C(48, 5, 288, 96, True, "HS", 2, scale),
        C(96, 5, 576, 96, True, "HS", 1, scale),
        C(96, 5, 576, 96, True, "HS", 1, scale),
    ]


def _large_config(scale):
    C = InvertedResidualConfig
    return [
        C(16, 3, 16, 16, False, "RE", 1, scale),
        C(16, 3, 64, 24, False, "RE", 2, scale),
        C(24, 3, 72, 24, False, "RE", 1, scale),
        C(24, 5, 72, 40, True, "RE", 2, scale),
        C(40, 5, 120, 40, True, "RE", 1, scale),
        C(40, 5, 120, 40, True, "RE", 1, scale),
        C(40, 3, 240, 80, False, "HS", 2, scale),
        C(80, 3, 200, 80, False, "HS", 1, scale),
        C(80, 3, 184, 80, False, "HS", 1, scale),
        C(80, 3, 184, 80, False, "HS", 1, scale),
        C(80, 3, 480, 112, True, "HS", 1, scale),
        C(112, 3, 672, 112, True, "HS", 1, scale),
        C(112, 5, 672, 160, True, "HS", 2, scale),
        C(160, 5, 960, 160, True, "HS", 1, scale),
        C(160, 5, 960, 160, True, "HS", 1, scale),
    ]


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_small_config(scale),
                         last_channel=_make_divisible(1024 * scale),
                         scale=scale, num_classes=num_classes, with_pool=with_pool)


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_large_config(scale),
                         last_channel=_make_divisible(1280 * scale),
                         scale=scale, num_classes=num_classes, with_pool=with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    assert not pretrained, "pretrained weights are not bundled (zero-egress image)"
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    assert not pretrained, "pretrained weights are not bundled (zero-egress image)"
    return MobileNetV3Large(scale=scale, **kwargs)

"""Minimal transforms (numpy-based, run in dataloader workers).
Reference: python/paddle/vision/transforms/transforms.py."""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and arr.shape[-1] in (1, 3, 4) and self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def __call__(self, img):
        return (np.asarray(img, np.float32) - self.mean) / self.std


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        oh, ow = self.size
        ih, iw = arr.shape[h_ax], arr.shape[w_ax]
        ri = (np.arange(oh) * ih / oh).astype(int).clip(0, ih - 1)
        ci = (np.arange(ow) * iw / ow).astype(int).clip(0, iw - 1)
        out = np.take(np.take(arr, ri, h_ax), ci, w_ax)
        return out


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return np.asarray(img)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h_ax = 1 if chw else 0
        h, w = arr.shape[h_ax], arr.shape[h_ax + 1]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        if chw:
            return arr[:, i:i + th, j:j + tw]
        return arr[i:i + th, j:j + tw]


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        arr = np.asarray(img)
        if np.random.rand() < self.prob:
            chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
            ax = 1 if chw else 0
            return np.flip(arr, axis=ax).copy()
        return arr


def _hw_axes(arr):
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
    return (1, 2) if chw else (0, 1)


def _norm_padding(padding):
    """int -> all sides; (w, h) -> (l, t, r, b); 4-tuple passes through."""
    if isinstance(padding, int):
        return (padding,) * 4
    padding = tuple(padding)
    if len(padding) == 2:
        return (padding[0], padding[1], padding[0], padding[1])
    assert len(padding) == 4, f"padding must be int, 2- or 4-tuple: {padding}"
    return padding


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed

    def __call__(self, img):
        arr = np.asarray(img)
        h_ax, w_ax = _hw_axes(arr)
        if self.padding is not None:
            left, top, right, bottom = _norm_padding(self.padding)
            pads = [(0, 0)] * arr.ndim
            pads[h_ax], pads[w_ax] = (top, bottom), (left, right)
            arr = np.pad(arr, pads)
        th, tw = self.size
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        if self.pad_if_needed and (h < th or w < tw):
            pads = [(0, 0)] * arr.ndim
            pads[h_ax] = (0, max(0, th - h))
            pads[w_ax] = (0, max(0, tw - w))
            arr = np.pad(arr, pads)
            h, w = arr.shape[h_ax], arr.shape[w_ax]
        if h < th or w < tw:
            raise ValueError(
                f"RandomCrop: image ({h}x{w}) smaller than crop {self.size}; "
                f"use pad_if_needed=True or a smaller crop size")
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_ax], sl[w_ax] = slice(i, i + th), slice(j, j + tw)
        return arr[tuple(sl)]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        h_ax, w_ax = _hw_axes(arr)
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                sl = [slice(None)] * arr.ndim
                sl[h_ax], sl[w_ax] = slice(i, i + th), slice(j, j + tw)
                arr = arr[tuple(sl)]
                break
        return Resize(self.size, interpolation=self.interpolation)(arr)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = (padding,) * 4 if isinstance(padding, int) else \
            tuple(padding) * (2 if len(padding) == 2 else 1)
        self.fill = fill
        self.mode = padding_mode

    def __call__(self, img):
        arr = np.asarray(img)
        left, top, right, bottom = (self.padding if len(self.padding) == 4 else
                                    self.padding * 2)
        h_ax, w_ax = _hw_axes(arr)
        pads = [(0, 0)] * arr.ndim
        pads[h_ax], pads[w_ax] = (top, bottom), (left, right)
        if self.mode == "constant":
            return np.pad(arr, pads, constant_values=self.fill)
        return np.pad(arr, pads, mode=self.mode)


class Grayscale:
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if arr.ndim == 3 and arr.shape[0] in (3, 4):  # CHW color
            g = (0.299 * arr[0] + 0.587 * arr[1] + 0.114 * arr[2])[None]
            ch_ax = 0
        elif arr.ndim == 3 and arr.shape[-1] in (3, 4):  # HWC color
            g = (arr[..., :3] @ np.array([0.299, 0.587, 0.114],
                                         np.float32))[..., None]
            ch_ax = -1
        elif arr.ndim == 3 and arr.shape[0] == 1:  # (1,H,W) already gray
            g, ch_ax = arr, 0
        elif arr.ndim == 2:  # HW: grow a trailing channel dim
            g, ch_ax = arr[..., None], -1
        else:
            raise ValueError(f"Grayscale: unsupported image shape {arr.shape}")
        reps = [1] * g.ndim
        reps[ch_ax] = self.n
        return np.tile(g, reps)


def _jitter_factor(value):
    # reference samples uniform(max(0, 1-v), 1+v): never inverts pixels
    return np.random.uniform(max(0.0, 1.0 - value), 1.0 + value)


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        hi = 255 if arr.max() > 1.5 else 1.0
        return (arr * _jitter_factor(self.value)).clip(0, hi)


class ContrastTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        mean = arr.mean()
        hi = 255 if arr.max() > 1.5 else 1.0
        return ((arr - mean) * _jitter_factor(self.value) + mean).clip(0, hi)


class SaturationTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        f = _jitter_factor(self.value)
        gray = Grayscale(arr.shape[0] if _hw_axes(arr) == (1, 2) else
                         arr.shape[-1] if arr.ndim == 3 else 1)(arr)
        hi = 255 if arr.max() > 1.5 else 1.0
        return (gray + (arr - gray) * f).clip(0, hi)


class HueTransform:
    """Hue rotation by a uniform shift in [-value, value] (value <= 0.5 in the
    paddle API, interpreted as a fraction of the full hue circle)."""

    def __init__(self, value):
        assert 0 <= value <= 0.5, "hue value must be in [0, 0.5]"
        self.value = value

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        chw = _hw_axes(arr) == (1, 2)
        if arr.ndim != 3 or (arr.shape[0] if chw else arr.shape[-1]) < 3:
            return arr  # hue is undefined for grayscale
        rgb = arr if not chw else np.moveaxis(arr, 0, -1)
        hi = 255 if rgb.max() > 1.5 else 1.0
        x = rgb[..., :3] / hi
        # RGB hue rotation via the YIQ chroma-plane rotation matrix
        theta = 2 * np.pi * np.random.uniform(-self.value, self.value)
        c, s = np.cos(theta), np.sin(theta)
        to_yiq = np.array([[0.299, 0.587, 0.114],
                           [0.596, -0.274, -0.321],
                           [0.211, -0.523, 0.311]], np.float32)
        rot = np.array([[1, 0, 0], [0, c, -s], [0, s, c]], np.float32)
        m = np.linalg.inv(to_yiq) @ rot @ to_yiq
        out3 = (x @ m.T).clip(0, 1) * hi
        out = np.concatenate([out3, rgb[..., 3:]], -1) if rgb.shape[-1] > 3 \
            else out3
        return np.moveaxis(out, -1, 0) if chw else out


class ColorJitter:
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.ts = []
        if brightness:
            self.ts.append(BrightnessTransform(brightness))
        if contrast:
            self.ts.append(ContrastTransform(contrast))
        if saturation:
            self.ts.append(SaturationTransform(saturation))
        if hue:
            self.ts.append(HueTransform(hue))

    def __call__(self, img):
        order = np.random.permutation(len(self.ts)) if self.ts else []
        for i in order:
            img = self.ts[i](img)
        return img


class RandomRotation:
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0):
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) else degrees

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        angle = np.random.uniform(*self.degrees)
        h_ax, w_ax = _hw_axes(arr)
        # nearest-neighbor rotation via inverse mapping
        h, w = arr.shape[h_ax], arr.shape[w_ax]
        cy, cx = (h - 1) / 2, (w - 1) / 2
        th = np.deg2rad(angle)
        yy, xx = np.mgrid[0:h, 0:w]
        ys = (cy + (yy - cy) * np.cos(th) + (xx - cx) * np.sin(th)).round()
        xs = (cx - (yy - cy) * np.sin(th) + (xx - cx) * np.cos(th)).round()
        valid = (ys >= 0) & (ys < h) & (xs >= 0) & (xs < w)
        ys, xs = ys.clip(0, h - 1).astype(int), xs.clip(0, w - 1).astype(int)
        if h_ax == 1:  # CHW
            out = arr[:, ys, xs]
            out = np.where(valid[None], out, 0)
        else:
            out = arr[ys, xs]
            out = np.where(valid if out.ndim == 2 else valid[..., None], out, 0)
        return out


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


# ---------------- functional API (reference vision/transforms/functional.py) ---
class BaseTransform:
    """Base for custom transforms (reference transforms.BaseTransform):
    subclasses implement _apply_image / _apply_* per data kind."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def _apply_image(self, image):
        raise NotImplementedError

    def __call__(self, inputs):
        if not isinstance(inputs, (list, tuple)):
            return self._apply_image(inputs)
        out = []
        for key, data in zip(self.keys, inputs):
            fn = getattr(self, f"_apply_{key}", None)
            out.append(fn(data) if fn else data)
        return tuple(out)


def _chw(arr):
    a = np.asarray(arr)
    return a, (a.ndim == 3 and a.shape[0] in (1, 3, 4))


def to_tensor(pic, data_format="CHW"):
    from ...core.tensor import Tensor
    import jax.numpy as jnp

    a = np.asarray(pic)
    if a.ndim == 2:
        a = a[None] if data_format == "CHW" else a[..., None]
    elif a.ndim == 3 and data_format == "CHW" and a.shape[-1] in (1, 3, 4) \
            and a.shape[0] not in (1, 3, 4):
        a = a.transpose(2, 0, 1)  # HWC -> CHW
    if a.dtype == np.uint8:
        a = a.astype(np.float32) / 255.0
    return Tensor(jnp.asarray(a.astype(np.float32)))


def hflip(img):
    a, chw = _chw(img)
    return a[..., ::-1] if chw or a.ndim == 2 else a[:, ::-1]


def vflip(img):
    a, chw = _chw(img)
    return a[..., ::-1, :] if chw else a[::-1]


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(np.asarray(img))


def pad(img, padding, fill=0, padding_mode="constant"):
    return Pad(padding, fill, padding_mode)(np.asarray(img))


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    arr = np.asarray(img, np.float32)
    h_ax, w_ax = _hw_axes(arr)
    h, w = arr.shape[h_ax], arr.shape[w_ax]
    cy, cx = (h - 1) / 2, (w - 1) / 2
    th = np.deg2rad(float(angle))
    yy, xx = np.mgrid[0:h, 0:w]
    ys = (cy + (yy - cy) * np.cos(th) + (xx - cx) * np.sin(th)).round()
    xs = (cx - (yy - cy) * np.sin(th) + (xx - cx) * np.cos(th)).round()
    valid = (ys >= 0) & (ys < h) & (xs >= 0) & (xs < w)
    ys, xs = ys.clip(0, h - 1).astype(int), xs.clip(0, w - 1).astype(int)
    if h_ax == 1:  # CHW
        out = arr[:, ys, xs]
        return np.where(valid[None], out, fill)
    out = arr[ys, xs]
    return np.where(valid if out.ndim == 2 else valid[..., None], out, fill)


def to_grayscale(img, num_output_channels=1):
    return Grayscale(num_output_channels)(np.asarray(img))


def crop(img, top, left, height, width):
    a, chw = _chw(img)
    if chw:
        return a[:, top:top + height, left:left + width]
    return a[top:top + height, left:left + width]


def center_crop(img, output_size):
    return CenterCrop(output_size)(np.asarray(img))


def adjust_brightness(img, brightness_factor):
    a, _ = _chw(img)
    return np.clip(a * brightness_factor, 0, 255 if a.dtype == np.uint8 else 1e9).astype(a.dtype)


def adjust_contrast(img, contrast_factor):
    arr = np.asarray(img, np.float32)
    mean = arr.mean()
    hi = 255 if arr.max() > 1.5 else 1.0
    return ((arr - mean) * contrast_factor + mean).clip(0, hi)


def adjust_hue(img, hue_factor):
    """hue_factor in [-0.5, 0.5]: fraction of the hue circle to rotate by."""
    arr = np.asarray(img, np.float32)
    chw = _hw_axes(arr) == (1, 2)
    if arr.ndim != 3 or (arr.shape[0] if chw else arr.shape[-1]) < 3:
        return arr
    rgb = arr if not chw else np.moveaxis(arr, 0, -1)
    hi = 255 if rgb.max() > 1.5 else 1.0
    x = rgb[..., :3] / hi
    theta = 2 * np.pi * float(hue_factor)
    c, s = np.cos(theta), np.sin(theta)
    to_yiq = np.array([[0.299, 0.587, 0.114],
                       [0.596, -0.274, -0.321],
                       [0.211, -0.523, 0.311]], np.float32)
    rot = np.array([[1, 0, 0], [0, c, -s], [0, s, c]], np.float32)
    m = np.linalg.inv(to_yiq) @ rot @ to_yiq
    out3 = (x @ m.T).clip(0, 1) * hi
    out = np.concatenate([out3, rgb[..., 3:]], -1) if rgb.shape[-1] > 3 else out3
    return np.moveaxis(out, -1, 0) if chw else out


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    a = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        return (a - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    return (a - mean) / std

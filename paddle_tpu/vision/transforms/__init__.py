"""Minimal transforms (numpy-based, run in dataloader workers).
Reference: python/paddle/vision/transforms/transforms.py."""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and arr.shape[-1] in (1, 3, 4) and self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def __call__(self, img):
        return (np.asarray(img, np.float32) - self.mean) / self.std


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        oh, ow = self.size
        ih, iw = arr.shape[h_ax], arr.shape[w_ax]
        ri = (np.arange(oh) * ih / oh).astype(int).clip(0, ih - 1)
        ci = (np.arange(ow) * iw / ow).astype(int).clip(0, iw - 1)
        out = np.take(np.take(arr, ri, h_ax), ci, w_ax)
        return out


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[..., ::-1].copy()
        return np.asarray(img)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h_ax = 1 if chw else 0
        h, w = arr.shape[h_ax], arr.shape[h_ax + 1]
        th, tw = self.size
        i, j = (h - th) // 2, (w - tw) // 2
        if chw:
            return arr[:, i:i + th, j:j + tw]
        return arr[i:i + th, j:j + tw]

from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import ops  # noqa: F401


_image_backend = "pil"


def set_image_backend(backend):
    """Select the image-decoding backend for datasets (reference:
    python/paddle/vision/image.py). 'cv2' is accepted but decoding here goes
    through numpy either way."""
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unsupported image backend {backend}")
    _image_backend = backend


def get_image_backend():
    return _image_backend

"""Builtin datasets. Reference: python/paddle/vision/datasets/mnist.py etc.

Zero-egress environment: when the real dataset files are absent, MNIST/CIFAR fall back to a
deterministic synthetic sample set (same shapes/dtypes/label distribution) so tests and the
MNIST-LeNet baseline run hermetically. Pass download=False + files to use real data.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset


class MNIST(Dataset):
    def __init__(self, image_path=None, label_path=None, mode="train", transform=None,
                 download=True, backend=None, size=2048, seed=0):
        self.mode = mode
        self.transform = transform
        images = labels = None
        if image_path and label_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                labels = np.frombuffer(f.read(), np.uint8)
        if images is None:
            # deterministic synthetic data: class-dependent blob patterns so a model
            # can actually learn (loss decreases) in hermetic tests
            rng = np.random.RandomState(seed if mode == "train" else seed + 1)
            n = size if mode == "train" else max(size // 4, 256)
            labels = rng.randint(0, 10, n).astype(np.int64)
            images = np.zeros((n, 28, 28), np.float32)
            for i, lab in enumerate(labels):
                img = rng.rand(28, 28).astype(np.float32) * 0.3
                r, c = divmod(int(lab), 4)
                img[4 + r * 7:11 + r * 7, 3 + c * 6:9 + c * 6] += 0.7
                images[i] = img
            images = (images * 255).clip(0, 255).astype(np.uint8)
        self.images = images
        self.labels = labels.astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        img = img.reshape(1, 28, 28)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None, download=True,
                 backend=None, size=1024, seed=0):
        self.transform = transform
        rng = np.random.RandomState(seed if mode == "train" else seed + 1)
        n = size if mode == "train" else max(size // 4, 128)
        self.labels = rng.randint(0, 10, n).astype(np.int64)
        self.images = (rng.rand(n, 3, 32, 32) * 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], np.int64)

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        rng = np.random.RandomState(7)
        self.labels = rng.randint(0, 100, len(self.labels)).astype(np.int64)


class DatasetFolder(Dataset):
    """Directory-per-class image tree (reference vision/datasets/folder.py).
    Loads .npy arrays or image files (via PIL when available); samples are
    (image, class_index) with classes sorted by folder name."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        extensions = extensions or (".npy", ".png", ".jpg", ".jpeg", ".bmp")
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for fname in sorted(os.listdir(cdir)):
                path = os.path.join(cdir, fname)
                ok = (is_valid_file(path) if is_valid_file
                      else fname.lower().endswith(extensions))
                if ok:
                    self.samples.append((path, self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat/recursive image list without labels (reference folder.py:ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        extensions = extensions or (".npy", ".png", ".jpg", ".jpeg", ".bmp")
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(dirpath, fname)
                ok = (is_valid_file(path) if is_valid_file
                      else fname.lower().endswith(tuple(extensions)))
                if ok:
                    self.samples.append(path)

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


def _default_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image

        return np.asarray(Image.open(path))
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(f"cannot load {path}: PIL unavailable") from e


class Flowers(Dataset):
    """Flowers-102 (synthetic fallback, shapes per the reference dataset)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None,
                 size=256, seed=0):
        self.transform = transform
        rng = np.random.RandomState(seed if mode == "train" else seed + 1)
        n = size if mode == "train" else max(size // 4, 64)
        self.labels = rng.randint(0, 102, n).astype(np.int64)
        self.images = (rng.rand(n, 3, 96, 96) * 255).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([self.labels[idx]], np.int64)

    def __len__(self):
        return len(self.images)


class VOC2012(Dataset):
    """VOC2012 segmentation (synthetic fallback: image + label mask pairs)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, size=64, seed=0):
        self.transform = transform
        rng = np.random.RandomState(seed if mode == "train" else seed + 1)
        n = size if mode == "train" else max(size // 4, 16)
        self.images = (rng.rand(n, 3, 128, 128) * 255).astype(np.uint8)
        self.labels = rng.randint(0, 21, (n, 128, 128)).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        lab = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, lab

    def __len__(self):
        return len(self.images)

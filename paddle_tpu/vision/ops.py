"""paddle.vision.ops: detection operators.

Reference: python/paddle/vision/ops.py over CUDA kernels (roi_align_op.cu,
deformable_conv_op.cu, yolo_box_op.cu, nms via multiclass_nms). TPU-native:
the pooling/alignment ops are gather+interpolate programs (XLA fuses them);
NMS is data-dependent sequential suppression, done host-side like the
reference's CPU kernel; deform_conv2d builds on grid-sample-style bilinear
gathers so the MXU still does the contraction.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import apply
from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..ops._helpers import t_
from ..ops import nn_functional as F


# ----------------------------------------------------------------- RoI ops
def _bilinear_sample(feat, ys, xs):
    """feat [C, H, W]; ys/xs arbitrary float grids -> [C, *grid]."""
    H, W = feat.shape[-2], feat.shape[-1]
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    y1, x1 = y0 + 1, x0 + 1
    wy = ys - y0
    wx = xs - x0

    def g(yi, xi):
        inside = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        v = feat[:, yi.clip(0, H - 1), xi.clip(0, W - 1)]
        return v * inside.astype(feat.dtype)

    return (g(y0, x0) * (1 - wy) * (1 - wx) + g(y0, x1) * (1 - wy) * wx
            + g(y1, x0) * wy * (1 - wx) + g(y1, x1) * wy * wx)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference ops.py:roi_align / roi_align_op): boxes [R, 4]
    (x1,y1,x2,y2) in input coords, boxes_num per batch image."""
    x, boxes = t_(x), t_(boxes)
    boxes_num = t_(boxes_num)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else output_size

    def kernel(feat, bxs, bnum):
        # map each roi to its batch image
        img_idx = jnp.repeat(jnp.arange(bnum.shape[0]), bnum,
                             total_repeat_length=bxs.shape[0])
        offset = 0.5 if aligned else 0.0
        sr = sampling_ratio if sampling_ratio > 0 else 2

        def one_roi(box, bi):
            fx = feat[bi]
            x1, y1, x2, y2 = box * spatial_scale
            x1, y1 = x1 - offset, y1 - offset
            x2, y2 = x2 - offset, y2 - offset
            rw = jnp.maximum(x2 - x1, 1.0 if not aligned else 1e-6)
            rh = jnp.maximum(y2 - y1, 1.0 if not aligned else 1e-6)
            bin_h, bin_w = rh / oh, rw / ow
            # sr x sr samples per bin, averaged
            iy = jnp.arange(oh)[:, None, None, None]
            ix = jnp.arange(ow)[None, :, None, None]
            sy = jnp.arange(sr)[None, None, :, None]
            sx = jnp.arange(sr)[None, None, None, :]
            ys = y1 + (iy + (sy + 0.5) / sr) * bin_h
            xs = x1 + (ix + (sx + 0.5) / sr) * bin_w
            ys = jnp.broadcast_to(ys, (oh, ow, sr, sr))
            xs = jnp.broadcast_to(xs, (oh, ow, sr, sr))
            vals = _bilinear_sample(fx, ys, xs)     # [C, oh, ow, sr, sr]
            return vals.mean(axis=(-1, -2))

        return jax.vmap(one_roi)(bxs, img_idx)

    return apply("roi_align", kernel, [x, boxes, boxes_num],
                 nondiff_mask=[False, True, True])


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool (max over quantized bins; reference roi_pool_op)."""
    x, boxes, boxes_num = t_(x), t_(boxes), t_(boxes_num)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else output_size

    def kernel(feat, bxs, bnum):
        H, W = feat.shape[-2], feat.shape[-1]
        img_idx = jnp.repeat(jnp.arange(bnum.shape[0]), bnum,
                             total_repeat_length=bxs.shape[0])

        def one_roi(box, bi):
            fx = feat[bi]
            x1 = jnp.round(box[0] * spatial_scale).astype(jnp.int32)
            y1 = jnp.round(box[1] * spatial_scale).astype(jnp.int32)
            x2 = jnp.round(box[2] * spatial_scale).astype(jnp.int32)
            y2 = jnp.round(box[3] * spatial_scale).astype(jnp.int32)
            rh = jnp.maximum(y2 - y1 + 1, 1)
            rw = jnp.maximum(x2 - x1 + 1, 1)
            # sample a dense grid per bin (static shape), max-reduce
            G = 4
            iy = jnp.arange(oh)[:, None, None, None]
            ix = jnp.arange(ow)[None, :, None, None]
            gy = jnp.arange(G)[None, None, :, None] / G
            gx = jnp.arange(G)[None, None, None, :] / G
            ys = (y1 + (iy + gy) * rh / oh).astype(jnp.int32).clip(0, H - 1)
            xs = (x1 + (ix + gx) * rw / ow).astype(jnp.int32).clip(0, W - 1)
            ys = jnp.broadcast_to(ys, (oh, ow, G, G))
            xs = jnp.broadcast_to(xs, (oh, ow, G, G))
            vals = fx[:, ys, xs]
            return vals.max(axis=(-1, -2))

        return jax.vmap(one_roi)(bxs, img_idx)

    return apply("roi_pool", kernel, [x, boxes, boxes_num],
                 nondiff_mask=[False, True, True])


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive RoI pooling (reference psroi_pool_op): channel
    C = out_c * oh * ow; bin (i,j) reads its own channel group."""
    x, boxes, boxes_num = t_(x), t_(boxes), t_(boxes_num)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else output_size

    def kernel(feat, bxs, bnum):
        N, C, H, W = feat.shape
        out_c = C // (oh * ow)
        img_idx = jnp.repeat(jnp.arange(bnum.shape[0]), bnum,
                             total_repeat_length=bxs.shape[0])

        def one_roi(box, bi):
            fx = feat[bi].reshape(out_c, oh, ow, H, W)
            x1, y1, x2, y2 = box * spatial_scale
            rh = jnp.maximum(y2 - y1, 0.1)
            rw = jnp.maximum(x2 - x1, 0.1)
            G = 4
            iy = jnp.arange(oh)[:, None, None, None]
            ix = jnp.arange(ow)[None, :, None, None]
            gy = jnp.arange(G)[None, None, :, None] / G
            gx = jnp.arange(G)[None, None, None, :] / G
            ys = (y1 + (iy + gy) * rh / oh).astype(jnp.int32).clip(0, H - 1)
            xs = (x1 + (ix + gx) * rw / ow).astype(jnp.int32).clip(0, W - 1)
            ys = jnp.broadcast_to(ys, (oh, ow, G, G))
            xs = jnp.broadcast_to(xs, (oh, ow, G, G))
            # position-sensitive: bin (i,j) reads channel group (i,j)
            iy_idx = jnp.broadcast_to(jnp.arange(oh)[:, None, None, None],
                                      (oh, ow, G, G))
            ix_idx = jnp.broadcast_to(jnp.arange(ow)[None, :, None, None],
                                      (oh, ow, G, G))
            vals = fx[:, iy_idx, ix_idx, ys, xs]  # [out_c, oh, ow, G, G]
            return vals.mean(axis=(-1, -2))

        return jax.vmap(one_roi)(bxs, img_idx)

    return apply("psroi_pool", kernel, [x, boxes, boxes_num],
                 nondiff_mask=[False, True, True])


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Greedy IoU suppression (reference nms — sequential, host-side like the
    reference CPU kernel). Returns kept indices sorted by score."""
    b = np.asarray(t_(boxes)._data, np.float32)
    n = b.shape[0]
    s = (np.asarray(t_(scores)._data, np.float32) if scores is not None
         else np.ones(n, np.float32))
    cats = (np.asarray(t_(category_idxs)._data) if category_idxs is not None
            else np.zeros(n, np.int64))
    areas = (b[:, 2] - b[:, 0]).clip(0) * (b[:, 3] - b[:, 1]).clip(0)
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(n, bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(b[i, 0], b[:, 0])
        yy1 = np.maximum(b[i, 1], b[:, 1])
        xx2 = np.minimum(b[i, 2], b[:, 2])
        yy2 = np.minimum(b[i, 3], b[:, 3])
        inter = (xx2 - xx1).clip(0) * (yy2 - yy1).clip(0)
        iou = inter / (areas[i] + areas - inter + 1e-10)
        suppressed |= (iou > iou_threshold) & (cats == cats[i])
        suppressed[i] = True
    keep = np.array(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


# -------------------------------------------------------------- deform conv
def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference deformable_conv_op): sample input at
    offset-shifted kernel taps (bilinear), then contract with the weight."""
    args = [t_(x), t_(offset), t_(weight)]
    if mask is not None:
        args.append(t_(mask))
    if bias is not None:
        args.append(t_(bias))
    has_mask = mask is not None
    has_bias = bias is not None
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation

    def kernel(a, off, w, *rest):
        m = rest[0] if has_mask else None
        bvec = rest[-1] if has_bias else None
        N, C, H, W = a.shape
        Co, Cg, kh, kw = w.shape
        oh = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        ow = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        # base sampling grid per output position and kernel tap
        oy = jnp.arange(oh)[:, None] * sh
        ox = jnp.arange(ow)[None, :] * sw
        cols = []
        for ki in range(kh):
            for kj in range(kw):
                idx = ki * kw + kj
                dy = off[:, 2 * idx]        # [N, oh, ow]
                dx = off[:, 2 * idx + 1]
                ys = oy[None] + ki * dh - ph + dy
                xs = ox[None] + kj * dw - pw + dx

                def sample(fi, yy, xx):
                    return _bilinear_sample(fi, yy, xx)

                v = jax.vmap(sample)(a, ys, xs)   # [N, C, oh, ow]
                if m is not None:
                    v = v * m[:, idx][:, None]
                cols.append(v)
        col = jnp.stack(cols, axis=2)             # [N, C, K, oh, ow]
        col = col.reshape(N, C * kh * kw, oh * ow)
        wmat = w.reshape(Co, Cg * kh * kw)
        if groups == 1:
            out = jnp.einsum("ok,nkl->nol", wmat, col)
        else:
            col_g = col.reshape(N, groups, (C // groups) * kh * kw, oh * ow)
            w_g = wmat.reshape(groups, Co // groups, Cg * kh * kw)
            out = jnp.einsum("gok,ngkl->ngol", w_g, col_g).reshape(N, Co, -1)
        out = out.reshape(N, Co, oh, ow)
        if bvec is not None:
            out = out + bvec.reshape(1, -1, 1, 1)
        return out

    return apply("deform_conv2d", kernel, args)


class DeformConv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        kh, kw = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else kernel_size
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.deformable_groups, self.groups = deformable_groups, groups
        import numpy as _np

        from ..nn import initializer as I

        fan_in = in_channels // groups * kh * kw
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, kh, kw), attr=weight_attr,
            default_initializer=I.Uniform(-1 / math.sqrt(fan_in),
                                          1 / math.sqrt(fan_in)))
        self.bias = self.create_parameter((out_channels,), attr=bias_attr,
                                          is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, self.stride,
                             self.padding, self.dilation,
                             self.deformable_groups, self.groups, mask)


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size, self.spatial_scale = output_size, spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size, self.spatial_scale = output_size, spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size, self.spatial_scale = output_size, spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


# ------------------------------------------------------------------- YOLO
def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, name=None, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5):
    """Decode YOLOv3 head output into boxes+scores (reference yolo_box_op)."""
    x, img_size = t_(x), t_(img_size)
    na = len(anchors) // 2
    anchors_np = np.asarray(anchors, np.float32).reshape(na, 2)

    def kernel(a, imgs):
        N, C, H, W = a.shape
        an = jnp.asarray(anchors_np)
        a = a.reshape(N, na, 5 + class_num, H, W)
        gx = jnp.arange(W)[None, None, None, :]
        gy = jnp.arange(H)[None, None, :, None]
        sig = jax.nn.sigmoid
        bx = (gx + sig(a[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2) / W
        by = (gy + sig(a[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2) / H
        bw = jnp.exp(a[:, :, 2]) * an[None, :, 0:1, None] / (W * downsample_ratio)
        bh = jnp.exp(a[:, :, 3]) * an[None, :, 1:2, None] / (H * downsample_ratio)
        conf = sig(a[:, :, 4])
        probs = sig(a[:, :, 5:]) * conf[:, :, None]
        imgs_f = imgs.astype(a.dtype)
        img_h = imgs_f[:, 0].reshape(N, 1, 1, 1)
        img_w = imgs_f[:, 1].reshape(N, 1, 1, 1)
        x1 = (bx - bw / 2) * img_w
        y1 = (by - bh / 2) * img_h
        x2 = (bx + bw / 2) * img_w
        y2 = (by + bh / 2) * img_h
        if clip_bbox:
            x1 = x1.clip(0)
            y1 = y1.clip(0)
            x2 = jnp.minimum(x2, img_w - 1)
            y2 = jnp.minimum(y2, img_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1).reshape(N, -1, 4)
        scores = probs.transpose(0, 1, 3, 4, 2).reshape(N, -1, class_num)
        # zero out low-confidence predictions (reference conf_thresh gate)
        keep = (conf.reshape(N, -1, 1) >= conf_thresh).astype(a.dtype)
        return boxes * keep, scores * keep

    return apply("yolo_box", kernel, [x, img_size],
                 nondiff_mask=[False, True])


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """Simplified YOLOv3 loss (coordinate + objectness + class BCE over
    assigned anchors; reference yolov3_loss_op). Host-side target assignment,
    device-side loss — sufficient for training-parity tests."""
    x_t, gt_box, gt_label = t_(x), t_(gt_box), t_(gt_label)
    na = len(anchor_mask)
    masked = np.asarray(anchors, np.float32).reshape(-1, 2)[anchor_mask]

    a = np.asarray(x_t._data)
    N, C, H, W = a.shape
    gb = np.asarray(gt_box._data)    # [N, B, 4] (cx, cy, w, h) normalized
    gl = np.asarray(gt_label._data)  # [N, B]
    obj_mask = np.zeros((N, na, H, W), np.float32)
    targets = np.zeros((N, na, 5 + class_num, H, W), np.float32)
    for n in range(N):
        for bidx in range(gb.shape[1]):
            cx, cy, w, h = gb[n, bidx]
            if w <= 0 or h <= 0:
                continue
            gi = min(int(cx * W), W - 1)
            gj = min(int(cy * H), H - 1)
            # best anchor by wh-IoU
            wh = np.array([w, h], np.float32)
            inter = np.minimum(masked / np.array([W, H]) / downsample_ratio,
                               wh).prod(1)
            best = int(np.argmax(inter))
            obj_mask[n, best, gj, gi] = 1.0
            targets[n, best, 0, gj, gi] = cx * W - gi
            targets[n, best, 1, gj, gi] = cy * H - gj
            targets[n, best, 2, gj, gi] = np.log(max(
                w * W * downsample_ratio / masked[best, 0], 1e-9))
            targets[n, best, 3, gj, gi] = np.log(max(
                h * H * downsample_ratio / masked[best, 1], 1e-9))
            targets[n, best, 4, gj, gi] = 1.0
            targets[n, best, 5 + int(gl[n, bidx]), gj, gi] = 1.0

    tgt = Tensor(jnp.asarray(targets))
    omask = Tensor(jnp.asarray(obj_mask))

    def kernel(pred, tg, om):
        p = pred.reshape(N, na, 5 + class_num, H, W)
        sig = jax.nn.sigmoid
        bce = lambda z, t: jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))
        om_e = om[:, :, None]
        loss_xy = (bce(p[:, :, 0:2], tg[:, :, 0:2]) * om_e).sum(axis=(1, 2, 3, 4))
        loss_wh = (jnp.abs(p[:, :, 2:4] - tg[:, :, 2:4]) * om_e).sum(axis=(1, 2, 3, 4))
        loss_obj = bce(p[:, :, 4], tg[:, :, 4]).sum(axis=(1, 2, 3))
        loss_cls = (bce(p[:, :, 5:], tg[:, :, 5:]) * om_e).sum(axis=(1, 2, 3, 4))
        return loss_xy + loss_wh + loss_obj + loss_cls

    return apply("yolo_loss", kernel, [x_t, tgt, omask],
                 nondiff_mask=[False, True, True])


# ------------------------------------------------------------------ image io
def read_file(filename, name=None):
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    try:
        import io

        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("decode_jpeg needs PIL") from e

    raw = bytes(np.asarray(t_(x)._data).astype(np.uint8))
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))

"""Native (C++) runtime components, built lazily with the system toolchain.

The reference implements its runtime core in C++ (TCPStore at
paddle/fluid/distributed/store/tcp_store.h, allocator stats, data feed). The TPU
build keeps that split: JAX/XLA/Pallas is the compute path, these C++ pieces are
the runtime around it. Sources compile once per machine into a cache directory;
pure-Python fallbacks keep everything working where no compiler exists.
"""
from __future__ import annotations

import hashlib
import os
import subprocess
import sys
import threading

_lock = threading.Lock()
_libs = {}

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))


def _cache_dir() -> str:
    d = os.environ.get("PADDLE_TPU_NATIVE_CACHE",
                       os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                                    "native"))
    os.makedirs(d, exist_ok=True)
    return d


def _out_path(name: str, sources, extra_flags) -> str:
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join(extra_flags).encode())
    return os.path.join(_cache_dir(), f"{name}-{h.hexdigest()[:16]}.so")


def _elf_intact(path: str) -> bool:
    """Structural sanity for a cached .so: magic AND the section-header table
    the ELF header promises actually fits inside the file. A half-written
    object from an interrupted build keeps the magic (the header is written
    first) but its e_shoff points past the truncation, so this distinguishes
    'file is damaged — rebuild' from 'file is fine but undlopenable —
    environment problem, rebuilding would reproduce it'."""
    import struct

    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            hdr = f.read(64)
    except OSError:
        return False
    if len(hdr) < 64 or hdr[:4] != b"\x7fELF":
        return False
    is64 = hdr[4] == 2
    little = hdr[5] == 1
    end = "<" if little else ">"
    if is64:
        (e_shoff,) = struct.unpack_from(end + "Q", hdr, 0x28)
        e_shentsize, e_shnum = struct.unpack_from(end + "HH", hdr, 0x3A)
    else:
        (e_shoff,) = struct.unpack_from(end + "I", hdr, 0x20)
        e_shentsize, e_shnum = struct.unpack_from(end + "HH", hdr, 0x2E)
    return size >= e_shoff + e_shentsize * e_shnum


def _compile(sources, extra_flags, out: str) -> None:
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           *extra_flags, *sources, "-o", out]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except FileNotFoundError as e:
        raise RuntimeError(f"no C++ toolchain: {e}") from e
    except subprocess.CalledProcessError as e:
        import contextlib

        with contextlib.suppress(OSError):  # no orphaned temp on failure
            os.remove(out)
        raise RuntimeError(f"native build failed:\n{e.stderr}") from e


def build_library(name: str, sources=None, extra_flags=()) -> str:
    """Compile `<name>.cc` (plus extra sources) into a cached shared library and
    return its path. Raises RuntimeError if the toolchain is missing/fails."""
    sources = sources or [os.path.join(_SRC_DIR, f"{name}.cc")]
    out = _out_path(name, sources, extra_flags)
    if os.path.exists(out):
        return out
    # per-process temp name: concurrent ranks of a multi-process cluster may
    # build the same library simultaneously, and a SHARED .tmp target lets one
    # rank rename the other's half-written object (os.replace is atomic, so
    # with unique temps the last complete build simply wins)
    tmp = f"{out}.tmp.{os.getpid()}"
    _compile(sources, extra_flags, tmp)
    os.replace(tmp, out)
    return out


def load_library(name: str):
    """ctypes.CDLL for a native component, building it on first use. Returns None
    when the toolchain is unavailable (callers fall back to Python)."""
    import ctypes

    with _lock:
        if name in _libs:
            return _libs[name]
        try:
            out = build_library(name)
            if _elf_intact(out):
                # structurally sound: a dlopen failure now is an environment
                # problem (missing runtime dep, incompatible libstdc++) that a
                # rebuild would only reproduce at multi-second cost — let the
                # OSError fall through to the Python fallback.
                lib = ctypes.CDLL(out)
            else:
                # the cached .so is damaged (e.g. truncated by an interrupted
                # pre-fix concurrent build). The check MUST run before dlopen:
                # mapping a truncated object can die with SIGBUS, not OSError.
                # Recompile to a fresh temp, load THAT, then swap it into the
                # cache — never delete the entry, other processes may hold it
                # open (dlopen keeps the mapping across the rename).
                sources = [os.path.join(_SRC_DIR, f"{name}.cc")]
                tmp = f"{out}.retry.{os.getpid()}"
                _compile(sources, (), tmp)
                try:
                    lib = ctypes.CDLL(tmp)  # raises OSError -> fallback below
                except OSError:
                    import contextlib

                    with contextlib.suppress(OSError):
                        os.remove(tmp)
                    raise
                os.replace(tmp, out)
        except (RuntimeError, OSError) as e:
            print(f"paddle_tpu: native {name} unavailable ({e}); using Python "
                  f"fallback", file=sys.stderr)
            lib = None
        _libs[name] = lib
        return lib

// FasterTokenizer host op: C++ wordpiece tokenization.
//
// Reference: the in-graph tokenizer op family
// (paddle/fluid/operators/string/faster_tokenizer_op.h — BertTokenizer =
// BasicTokenizer (clean / lowercase / punctuation & CJK isolation) followed by
// greedy-longest-match WordPiece). Tokenization is host compute on any
// accelerator, so on TPU it stays a native C++ component in front of the
// device program; the Python layer (paddle_tpu/text/faster_tokenizer.py) adds
// [CLS]/[SEP], pair encoding, truncation and padding.
//
// Unicode handling: UTF-8 is decoded to codepoints; ASCII is lowercased,
// Latin-1 letters are lowercased + accent-folded to their base ASCII letter,
// CJK ideographs and punctuation are isolated as single-codepoint tokens.
// (The reference relies on full ICU normalization; this table-driven fold
// covers the Latin-1 range that dominates the reference's test corpora.)
//
// C ABI (ctypes):
//   void* tk_create(const char* vocab_blob, long n, int do_lower)
//       vocab_blob: "token\n" lines (id = line index) or "token\tid\n" lines
//       (explicit ids, for vocabularies with gaps / non-contiguous ids)
//   long  tk_vocab_id(void* h, const char* token)   // -1 when absent
//   long  tk_tokenize(void* h, const char* text, long* out, long max_out)
//   void  tk_destroy(void* h)

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Tokenizer {
  std::unordered_map<std::string, long> vocab;
  bool do_lower = true;
  long unk = -1;
  size_t max_chars_per_word = 100;  // reference kMaxInputCharsPerWord
};

// ---- utf8 ----
struct Cp {
  uint32_t v;
  int len;
};

Cp decode(const unsigned char* s, size_t i, size_t n) {
  unsigned char c = s[i];
  if (c < 0x80) return {c, 1};
  if ((c >> 5) == 0x6 && i + 1 < n) return {uint32_t((c & 0x1F) << 6 | (s[i + 1] & 0x3F)), 2};
  if ((c >> 4) == 0xE && i + 2 < n)
    return {uint32_t((c & 0x0F) << 12 | (s[i + 1] & 0x3F) << 6 | (s[i + 2] & 0x3F)), 3};
  if ((c >> 3) == 0x1E && i + 3 < n)
    return {uint32_t((c & 0x07) << 18 | (s[i + 1] & 0x3F) << 12 | (s[i + 2] & 0x3F) << 6 |
                     (s[i + 3] & 0x3F)),
            4};
  return {0xFFFD, 1};
}

void encode(uint32_t cp, std::string* out) {
  if (cp < 0x80) {
    out->push_back(char(cp));
  } else if (cp < 0x800) {
    out->push_back(char(0xC0 | (cp >> 6)));
    out->push_back(char(0x80 | (cp & 0x3F)));
  } else if (cp < 0x10000) {
    out->push_back(char(0xE0 | (cp >> 12)));
    out->push_back(char(0x80 | ((cp >> 6) & 0x3F)));
    out->push_back(char(0x80 | (cp & 0x3F)));
  } else {
    out->push_back(char(0xF0 | (cp >> 18)));
    out->push_back(char(0x80 | ((cp >> 12) & 0x3F)));
    out->push_back(char(0x80 | ((cp >> 6) & 0x3F)));
    out->push_back(char(0x80 | (cp & 0x3F)));
  }
}

bool is_ws(uint32_t c) {
  return c == ' ' || c == '\t' || c == '\n' || c == '\r' || c == 0xA0 || c == 0x2028 ||
         (c >= 0x2000 && c <= 0x200A) || c == 0x3000;
}

bool is_control(uint32_t c) {
  if (c == '\t' || c == '\n' || c == '\r') return false;
  return c < 0x20 || c == 0x7F || (c >= 0x80 && c <= 0x9F) || c == 0xFFFD || c == 0;
}

bool is_cjk(uint32_t c) {
  return (c >= 0x4E00 && c <= 0x9FFF) || (c >= 0x3400 && c <= 0x4DBF) ||
         (c >= 0xF900 && c <= 0xFAFF) || (c >= 0x20000 && c <= 0x2A6DF) ||
         (c >= 0x2A700 && c <= 0x2CEAF) || (c >= 0x2F800 && c <= 0x2FA1F);
}

bool is_punct(uint32_t c) {
  if ((c >= 33 && c <= 47) || (c >= 58 && c <= 64) || (c >= 91 && c <= 96) ||
      (c >= 123 && c <= 126))
    return true;
  return (c >= 0x2010 && c <= 0x2027) || (c >= 0x3001 && c <= 0x303F) ||
         (c >= 0xFF01 && c <= 0xFF0F) || (c >= 0xFF1A && c <= 0xFF20) ||
         (c >= 0xFF3B && c <= 0xFF40) || (c >= 0xFF5B && c <= 0xFF65);
}

// Latin-1 + Latin-Extended-A lowercase/accent fold to base ASCII letter.
uint32_t fold(uint32_t c, bool lower) {
  if (lower && c >= 'A' && c <= 'Z') return c + 32;
  if (c < 0xC0) return c;
  if (!lower) return c;
  if (c >= 0xC0 && c <= 0xDE && c != 0xD7) c += 0x20;  // À..Þ -> à..þ
  static const struct {
    uint32_t lo, hi;
    char base;
  } folds[] = {{0xE0, 0xE5, 'a'}, {0xE7, 0xE7, 'c'}, {0xE8, 0xEB, 'e'}, {0xEC, 0xEF, 'i'},
               {0xF1, 0xF1, 'n'}, {0xF2, 0xF6, 'o'}, {0xF9, 0xFC, 'u'}, {0xFD, 0xFD, 'y'},
               {0xFF, 0xFF, 'y'}};
  for (auto& f : folds)
    if (c >= f.lo && c <= f.hi) return uint32_t(f.base);
  return c;
}

std::vector<std::string> basic_tokenize(const Tokenizer& tk, const char* text) {
  const unsigned char* s = reinterpret_cast<const unsigned char*>(text);
  size_t n = std::strlen(text);
  std::vector<std::string> words;
  std::string cur;
  auto flush = [&]() {
    if (!cur.empty()) {
      words.push_back(cur);
      cur.clear();
    }
  };
  for (size_t i = 0; i < n;) {
    Cp cp = decode(s, i, n);
    i += cp.len;
    uint32_t c = fold(cp.v, tk.do_lower);
    if (is_control(c)) continue;
    if (is_ws(c)) {
      flush();
    } else if (is_cjk(c) || is_punct(c)) {
      flush();
      std::string one;
      encode(c, &one);
      words.push_back(one);
    } else {
      encode(c, &cur);
    }
  }
  flush();
  return words;
}

void wordpiece(const Tokenizer& tk, const std::string& word, std::vector<long>* out) {
  // greedy longest-match-first over codepoint boundaries
  std::vector<size_t> bounds;  // byte offsets of codepoint starts + end
  const unsigned char* s = reinterpret_cast<const unsigned char*>(word.data());
  for (size_t i = 0; i < word.size();) {
    bounds.push_back(i);
    i += decode(s, i, word.size()).len;
  }
  bounds.push_back(word.size());
  size_t ncp = bounds.size() - 1;
  if (ncp > tk.max_chars_per_word) {
    out->push_back(tk.unk);
    return;
  }
  std::vector<long> pieces;
  size_t start = 0;
  while (start < ncp) {
    long id = -1;
    size_t end = ncp;
    for (; end > start; --end) {
      std::string sub = word.substr(bounds[start], bounds[end] - bounds[start]);
      if (start > 0) sub = "##" + sub;
      auto it = tk.vocab.find(sub);
      if (it != tk.vocab.end()) {
        id = it->second;
        break;
      }
    }
    if (id < 0) {  // no piece matched: whole word -> unk (reference behavior)
      out->push_back(tk.unk);
      return;
    }
    pieces.push_back(id);
    start = end;
  }
  out->insert(out->end(), pieces.begin(), pieces.end());
}

}  // namespace

extern "C" {

void* tk_create(const char* vocab_blob, long n, int do_lower) {
  auto* tk = new Tokenizer();
  tk->do_lower = do_lower != 0;
  long id = 0;
  const char* p = vocab_blob;
  const char* end = vocab_blob + n;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    size_t len = nl ? size_t(nl - p) : size_t(end - p);
    if (len) {
      const char* tab = static_cast<const char*>(memchr(p, '\t', len));
      if (tab) {  // "token\tid": caller-assigned id
        tk->vocab.emplace(std::string(p, tab - p), atol(tab + 1));
      } else {
        tk->vocab.emplace(std::string(p, len), id);
      }
    }
    ++id;
    p = nl ? nl + 1 : end;
  }
  auto it = tk->vocab.find("[UNK]");
  tk->unk = it == tk->vocab.end() ? 0 : it->second;
  return tk;
}

long tk_vocab_id(void* h, const char* token) {
  auto* tk = static_cast<Tokenizer*>(h);
  auto it = tk->vocab.find(token);
  return it == tk->vocab.end() ? -1 : it->second;
}

long tk_tokenize(void* h, const char* text, long* out, long max_out) {
  auto* tk = static_cast<Tokenizer*>(h);
  std::vector<long> ids;
  for (const auto& w : basic_tokenize(*tk, text)) wordpiece(*tk, w, &ids);
  long n = long(ids.size()) < max_out ? long(ids.size()) : max_out;
  for (long i = 0; i < n; ++i) out[i] = ids[i];
  return long(ids.size());
}

void tk_destroy(void* h) { delete static_cast<Tokenizer*>(h); }

}  // extern "C"

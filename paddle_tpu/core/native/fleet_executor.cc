// Actor-runtime transport: MessageBus + per-interceptor queues.
//
// Reference: paddle/fluid/distributed/fleet_executor/ — MessageBus
// (message_bus.h) carries InterceptorMessage between ranks over brpc; Carrier
// (carrier.h:49) owns per-rank interceptors and routes local messages without
// the bus. This is the TPU build's equivalent with a dependency-free TCP wire
// protocol instead of brpc. The compute side (interceptor handlers) stays in
// Python where the jax dispatch lives; this library owns what must be
// concurrent and low-latency: the listener thread, inter-rank sockets, routing
// table, and blocking per-interceptor FIFO queues.
//
// Wire format per message (little endian):
//   int64 src_id | int64 dst_id | int32 type | int32 len | payload bytes
//
// C API (ctypes):
//   fe_start(rank, nranks, port, endpoints_csv) -> handle (>0) or -errno
//   fe_port(handle) -> bound listen port
//   fe_register(handle, interceptor_id)            // queue owned by this rank
//   fe_route(handle, interceptor_id, rank)         // location table
//   fe_send(handle, src, dst, type, payload, len) -> 0 ok
//   fe_recv(handle, dst, &src, &type, buf, cap, timeout_ms) -> len or -1
//   fe_stop(handle)

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Message {
  int64_t src;
  int64_t dst;
  int32_t type;
  std::vector<char> payload;
};

struct Queue {
  std::deque<Message> q;
  std::mutex mu;
  std::condition_variable cv;
};

bool send_all(int fd, const char* data, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t w = ::send(fd, data + off, n - off, MSG_NOSIGNAL);
    if (w <= 0) return false;
    off += static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, char* data, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t r = ::recv(fd, data + off, n - off, 0);
    if (r <= 0) return false;
    off += static_cast<size_t>(r);
  }
  return true;
}

struct Bus {
  int rank = 0;
  int nranks = 1;
  int listen_fd = -1;
  int listen_port = 0;
  std::vector<std::string> endpoints;  // rank -> host:port
  std::map<int64_t, Queue*> queues;    // local interceptors
  std::map<int64_t, int> routes;       // interceptor -> rank
  std::map<int, int> peer_fds;         // rank -> connected socket
  std::mutex mu;                       // guards queues/routes/peer_fds
  std::thread listener;
  std::vector<std::thread> readers;
  std::vector<int> reader_fds;  // accepted sockets, shut down on stop
  bool stopping = false;

  ~Bus() { stop(); }

  bool deliver_local(Message&& m) {
    Queue* q = nullptr;
    {
      std::lock_guard<std::mutex> g(mu);
      auto it = queues.find(m.dst);
      if (it == queues.end()) return false;
      q = it->second;
    }
    {
      std::lock_guard<std::mutex> g(q->mu);
      q->q.push_back(std::move(m));
    }
    q->cv.notify_one();
    return true;
  }

  void reader_loop(int fd) {
    for (;;) {
      char hdr[24];
      if (!recv_all(fd, hdr, sizeof(hdr))) break;
      Message m;
      std::memcpy(&m.src, hdr, 8);
      std::memcpy(&m.dst, hdr + 8, 8);
      std::memcpy(&m.type, hdr + 16, 4);
      int32_t len;
      std::memcpy(&len, hdr + 20, 4);
      if (len < 0 || len > (1 << 30)) break;
      m.payload.resize(static_cast<size_t>(len));
      if (len > 0 && !recv_all(fd, m.payload.data(), m.payload.size())) break;
      deliver_local(std::move(m));
    }
    ::close(fd);
  }

  void listen_loop() {
    for (;;) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) break;  // listen_fd closed on stop
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> g(mu);
      if (stopping) {
        ::close(fd);
        break;
      }
      reader_fds.push_back(fd);
      readers.emplace_back(&Bus::reader_loop, this, fd);
    }
  }

  int connect_rank(int r) {
    auto it = peer_fds.find(r);
    if (it != peer_fds.end()) return it->second;
    const std::string& ep = endpoints.at(static_cast<size_t>(r));
    auto colon = ep.rfind(':');
    std::string host = ep.substr(0, colon);
    int port = std::stoi(ep.substr(colon + 1));
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return -1;
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
      ::close(fd);
      return -1;
    }
    // retry while the peer's listener comes up (reference message_bus
    // retries brpc channel init the same way)
    for (int attempt = 0; attempt < 300; ++attempt) {
      if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
        int one = 1;
        ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        peer_fds[r] = fd;
        return fd;
      }
      ::usleep(100 * 1000);
    }
    ::close(fd);
    return -1;
  }

  int send_msg(int64_t src, int64_t dst, int32_t type, const char* data,
               int32_t len) {
    int target_rank;
    {
      std::lock_guard<std::mutex> g(mu);
      auto it = routes.find(dst);
      target_rank = (it == routes.end()) ? rank : it->second;
    }
    if (target_rank == rank) {
      Message m{src, dst, type, {}};
      if (len > 0) m.payload.assign(data, data + len);
      return deliver_local(std::move(m)) ? 0 : -2;
    }
    std::lock_guard<std::mutex> g(mu);
    int fd = connect_rank(target_rank);
    if (fd < 0) return -3;
    char hdr[24];
    std::memcpy(hdr, &src, 8);
    std::memcpy(hdr + 8, &dst, 8);
    std::memcpy(hdr + 16, &type, 4);
    std::memcpy(hdr + 20, &len, 4);
    if (!send_all(fd, hdr, sizeof(hdr)) ||
        (len > 0 && !send_all(fd, data, static_cast<size_t>(len)))) {
      ::close(fd);
      peer_fds.erase(target_rank);
      return -4;
    }
    return 0;
  }

  void stop() {
    {
      std::lock_guard<std::mutex> g(mu);
      if (stopping) return;
      stopping = true;
    }
    if (listen_fd >= 0) ::shutdown(listen_fd, SHUT_RDWR), ::close(listen_fd);
    if (listener.joinable()) listener.join();
    {
      std::lock_guard<std::mutex> g(mu);
      for (auto& kv : peer_fds) ::close(kv.second);
      peer_fds.clear();
      // unblock reader threads stuck in recv on accepted sockets
      for (int fd : reader_fds) ::shutdown(fd, SHUT_RDWR);
      for (auto& kv : queues) kv.second->cv.notify_all();
    }
    for (auto& t : readers)
      if (t.joinable()) t.join();
  }
};

std::mutex g_mu;
std::map<int, Bus*> g_buses;
int g_next = 1;

Bus* get(int h) {
  std::lock_guard<std::mutex> g(g_mu);
  auto it = g_buses.find(h);
  return it == g_buses.end() ? nullptr : it->second;
}

}  // namespace

extern "C" {

int fe_start(int rank, int nranks, int port, const char* endpoints_csv) {
  Bus* b = new Bus();
  b->rank = rank;
  b->nranks = nranks;
  if (endpoints_csv && *endpoints_csv) {
    std::string s(endpoints_csv);
    size_t pos = 0;
    while (pos != std::string::npos) {
      size_t comma = s.find(',', pos);
      b->endpoints.push_back(s.substr(
          pos, comma == std::string::npos ? std::string::npos : comma - pos));
      pos = comma == std::string::npos ? comma : comma + 1;
    }
  }
  b->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (b->listen_fd < 0) {
    delete b;
    return -1;
  }
  int one = 1;
  ::setsockopt(b->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(b->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(b->listen_fd, 128) != 0) {
    ::close(b->listen_fd);
    delete b;
    return -2;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(b->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  b->listen_port = ntohs(addr.sin_port);
  b->listener = std::thread(&Bus::listen_loop, b);
  std::lock_guard<std::mutex> g(g_mu);
  int h = g_next++;
  g_buses[h] = b;
  return h;
}

int fe_port(int h) {
  Bus* b = get(h);
  return b ? b->listen_port : -1;
}

int fe_register(int h, int64_t id) {
  Bus* b = get(h);
  if (!b) return -1;
  std::lock_guard<std::mutex> g(b->mu);
  if (!b->queues.count(id)) b->queues[id] = new Queue();
  b->routes[id] = b->rank;
  return 0;
}

int fe_route(int h, int64_t id, int rank) {
  Bus* b = get(h);
  if (!b) return -1;
  std::lock_guard<std::mutex> g(b->mu);
  b->routes[id] = rank;
  return 0;
}

int fe_send(int h, int64_t src, int64_t dst, int type, const char* payload,
            int len) {
  Bus* b = get(h);
  if (!b) return -1;
  return b->send_msg(src, dst, type, payload, len);
}

int fe_recv(int h, int64_t dst, int64_t* src, int* type, char* buf, int cap,
            int timeout_ms) {
  Bus* b = get(h);
  if (!b) return -1;
  Queue* q = nullptr;
  {
    std::lock_guard<std::mutex> g(b->mu);
    auto it = b->queues.find(dst);
    if (it == b->queues.end()) return -2;
    q = it->second;
  }
  std::unique_lock<std::mutex> lk(q->mu);
  if (!q->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                      [&] { return !q->q.empty() || b->stopping; }))
    return -1;  // timeout
  if (q->q.empty()) return -3;  // stopped
  Message m = std::move(q->q.front());
  q->q.pop_front();
  lk.unlock();
  if (src) *src = m.src;
  if (type) *type = m.type;
  int n = static_cast<int>(m.payload.size());
  if (n > cap) n = cap;
  if (n > 0) std::memcpy(buf, m.payload.data(), static_cast<size_t>(n));
  return n;
}

int fe_pending(int h, int64_t id) {
  Bus* b = get(h);
  if (!b) return -1;
  Queue* q = nullptr;
  {
    std::lock_guard<std::mutex> g(b->mu);
    auto it = b->queues.find(id);
    if (it == b->queues.end()) return -2;
    q = it->second;
  }
  std::lock_guard<std::mutex> g(q->mu);
  return static_cast<int>(q->q.size());
}

void fe_stop(int h) {
  Bus* b = nullptr;
  {
    std::lock_guard<std::mutex> g(g_mu);
    auto it = g_buses.find(h);
    if (it == g_buses.end()) return;
    b = it->second;
    g_buses.erase(it);
  }
  b->stop();
  delete b;
}

}  // extern "C"
